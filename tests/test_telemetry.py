"""Worker telemetry plane tests (obs/telemetry.py + the tentpole wiring).

Covers:

- WorkerTelemetry snapshot schema, bounded size, and percentile math;
- StragglerDetector: median + k*MAD threshold, floors, hysteresis,
  min-workers guard, fleet-departure cleanup;
- TelemetryAggregator: ingest -> fleet gauges (per-worker values are
  journal-only), malformed payloads, rate-limited worker_telemetry
  journal events, straggler transitions (journal + gauge + advisory
  callback), current-world scoping;
- HeartbeatReporter jitter satellite (deterministic, decorrelated,
  bounded);
- obs.top parsing/rendering and a live frame against a real exporter;
- scripts/validate_journal.py over a real journal (subprocess);
- the metric-label-cardinality analysis rule over the new telemetry
  call sites (worker_id must never become a metric label);
- the ISSUE acceptance end-to-end: a local master + three heartbeating
  workers over real gRPC — an artificially slowed worker is flagged as
  a straggler within a bounded number of heartbeats, clears when the
  slowdown is removed, and a completed task's trace id links dispatch,
  worker span, and completion records across the journal.
"""

import json
import os
import subprocess
import sys
import time
import urllib.request

import pytest

from elasticdl_tpu import obs
from elasticdl_tpu.obs.telemetry import (
    SNAPSHOT_VERSION,
    StragglerDetector,
    TelemetryAggregator,
    WorkerTelemetry,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# WorkerTelemetry
# ---------------------------------------------------------------------------


def test_worker_telemetry_snapshot_schema():
    telemetry = WorkerTelemetry(worker_id=7)
    telemetry.set_rendezvous(3)
    telemetry.begin_task(42, "TRAINING", records_total=1000)
    for _ in range(10):
        telemetry.record_steps(4, duration_s=0.04, records=100)  # 10ms/step
    snap = telemetry.snapshot()
    assert snap["v"] == SNAPSHOT_VERSION
    assert snap["worker_id"] == 7
    assert snap["rendezvous_id"] == 3
    assert snap["steps_total"] == 40
    assert snap["records_total"] == 1000
    assert snap["task"] == {
        "id": 42, "type": "TRAINING",
        "records_done": 1000, "records_total": 1000,
    }
    assert snap["step_p50_s"] == pytest.approx(0.01)
    assert snap["step_p95_s"] == pytest.approx(0.01)
    assert snap["examples_per_s"] > 0
    assert "ts" in snap

    class _Stats:
        retries = 5
        give_ups = 1

    telemetry.bind_retry_stats(_Stats())
    snap = telemetry.snapshot()
    assert snap["rpc"] == {"retries": 5, "give_ups": 1}
    # The wire form parses back and stays bounded.
    payload = telemetry.snapshot_json()
    assert json.loads(payload) == snap
    assert len(payload.encode()) < 4096


def test_worker_telemetry_percentiles_track_recent_regime():
    telemetry = WorkerTelemetry(worker_id=0, step_window=4)
    for _ in range(4):
        telemetry.record_steps(1, duration_s=1.0)  # slow regime
    assert telemetry.snapshot()["step_p50_s"] == pytest.approx(1.0)
    for _ in range(4):
        telemetry.record_steps(1, duration_s=0.01)  # recovered
    assert telemetry.snapshot()["step_p50_s"] == pytest.approx(0.01)


def test_worker_telemetry_oversized_snapshot_degrades():
    telemetry = WorkerTelemetry(worker_id=1)
    # begin_task truncates the type, so build the bloat via a monkeyed
    # field: simulate by injecting an oversized task type directly.
    telemetry.begin_task(1, "x" * 10000, records_total=1)
    snap = telemetry.snapshot()
    assert len(snap["task"]["type"]) == 32  # truncated at ingest
    assert len(telemetry.snapshot_json().encode()) < 4096


def test_worker_telemetry_ignores_empty_flushes():
    telemetry = WorkerTelemetry(worker_id=2)
    telemetry.record_steps(0, duration_s=1.0)
    assert "step_p50_s" not in telemetry.snapshot()


# ---------------------------------------------------------------------------
# StragglerDetector
# ---------------------------------------------------------------------------


def test_straggler_detector_flags_after_hysteresis_and_clears():
    detector = StragglerDetector(flag_after=2, clear_after=2)
    fleet = {0: 0.010, 1: 0.012, 2: 0.011, 3: 0.200}
    stale = {wid: 0.1 for wid in fleet}
    assert detector.evaluate(fleet, stale) == []  # streak 1: no flag yet
    transitions = detector.evaluate(fleet, stale)  # streak 2: flagged
    assert [(t["worker_id"], t["flagged"]) for t in transitions] == [(3, True)]
    assert transitions[0]["metric"] == "step_time"
    assert transitions[0]["value"] > transitions[0]["threshold"]
    assert 3 in detector.flagged
    # Recovery: under threshold for clear_after evaluations.
    fleet[3] = 0.011
    assert detector.evaluate(fleet, stale) == []
    transitions = detector.evaluate(fleet, stale)
    assert [(t["worker_id"], t["flagged"]) for t in transitions] == [(3, False)]
    assert detector.flagged == {}


def test_straggler_detector_floors_protect_tight_fleets():
    """A healthy homogeneous fleet (MAD ~ 0) must not flag micro-jitter:
    the rel_floor keeps the threshold a fraction above the median."""
    detector = StragglerDetector(flag_after=1)
    fleet = {0: 0.0100, 1: 0.0101, 2: 0.0099, 3: 0.0104}
    stale = {wid: 0.1 for wid in fleet}
    assert detector.evaluate(fleet, stale) == []
    assert detector.flagged == {}


def test_straggler_detector_min_workers_guard():
    detector = StragglerDetector(flag_after=1, min_workers=3)
    assert detector.evaluate({0: 0.01, 1: 9.0}, {0: 0.1, 1: 0.1}) == []
    assert detector.flagged == {}


def test_straggler_detector_staleness_signal():
    detector = StragglerDetector(flag_after=1)
    fleet = {0: 0.01, 1: 0.01, 2: 0.01}
    stale = {0: 0.1, 1: 0.1, 2: 60.0}
    transitions = detector.evaluate(fleet, stale)
    assert [(t["worker_id"], t["metric"]) for t in transitions] == [
        (2, "staleness")
    ]


def test_straggler_detector_departed_worker_drops_silently():
    detector = StragglerDetector(flag_after=1)
    fleet = {0: 0.01, 1: 0.01, 2: 5.0}
    stale = {wid: 0.1 for wid in fleet}
    assert detector.evaluate(fleet, stale)  # 2 flagged
    # Worker 2 leaves the world (rescale): no straggler_cleared noise,
    # its state just evaporates.
    del fleet[2], stale[2]
    fleet[3] = 0.01
    stale[3] = 0.1
    assert detector.evaluate(fleet, stale) == []
    assert detector.flagged == {}


# ---------------------------------------------------------------------------
# TelemetryAggregator
# ---------------------------------------------------------------------------


def _snap(worker_id, p50=None, examples=0.0, **extra):
    snap = {
        "v": SNAPSHOT_VERSION,
        "worker_id": worker_id,
        "ts": time.time(),
        "examples_per_s": examples,
        **extra,
    }
    if p50 is not None:
        snap["step_p50_s"] = p50
        snap["step_p95_s"] = p50 * 1.5
    return json.dumps(snap)


def test_aggregator_folds_fleet_gauges(obs_registry_snapshot):
    clock = {"t": 100.0}
    aggregator = TelemetryAggregator(
        current_workers_fn=lambda: [0, 1, 2],
        clock=lambda: clock["t"],
        journal_interval_s=1e9,  # journaling exercised separately
    )
    aggregator.ingest(0, _snap(0, p50=0.010, examples=100.0))
    clock["t"] = 101.0
    aggregator.ingest(1, _snap(1, p50=0.012, examples=80.0))
    aggregator.ingest(2, _snap(2, p50=0.020, examples=50.0))
    registry = obs.registry()
    assert registry.get(
        "elasticdl_worker_step_time_p50_seconds"
    ).value() == pytest.approx(0.012)
    assert registry.get(
        "elasticdl_worker_step_time_p95_seconds"
    ).value() == pytest.approx(0.030)
    assert registry.get(
        "elasticdl_worker_examples_per_second_min"
    ).value() == pytest.approx(50.0)
    assert registry.get(
        "elasticdl_worker_examples_per_second_max"
    ).value() == pytest.approx(100.0)
    assert registry.get("elasticdl_telemetry_workers").value() == 3
    # Staleness: worker 0 reported at t=100, clock now 101.
    assert registry.get(
        "elasticdl_telemetry_staleness_seconds"
    ).value() == pytest.approx(1.0)
    # Reports from workers OUTSIDE the current world are excluded.
    aggregator.ingest(99, _snap(99, p50=9.0))
    assert registry.get("elasticdl_telemetry_workers").value() == 3
    assert registry.get(
        "elasticdl_worker_step_time_p95_seconds"
    ).value() == pytest.approx(0.030)
    assert 99 not in aggregator.worker_snapshots()


def test_aggregator_rejects_malformed_payloads(obs_registry_snapshot):
    aggregator = TelemetryAggregator(journal_interval_s=1e9)
    malformed = obs.registry().get("elasticdl_telemetry_malformed_total")
    base = malformed.value()
    aggregator.ingest(0, "not json at all {{{")
    aggregator.ingest(0, json.dumps(["a", "list"]))
    aggregator.ingest(0, json.dumps({"v": 999, "worker_id": 0}))
    # v=1 but wrong-typed fields: strings/bools where numbers belong
    # would poison gauge arithmetic — rejected, never cached.
    aggregator.ingest(0, json.dumps({"v": 1, "step_p50_s": "abc"}))
    aggregator.ingest(0, json.dumps({"v": 1, "examples_per_s": True}))
    aggregator.ingest(0, json.dumps({"v": 1, "task": {"id": "seven"}}))
    assert malformed.value() == base + 6
    assert aggregator.worker_snapshots() == {}


def test_ingest_is_exception_proof_and_scrape_safe(obs_registry_snapshot):
    """A hostile-but-v1 payload must neither raise out of ingest (it
    rides the liveness RPC) nor break subsequent /metrics scrapes or
    other workers' ingests."""
    aggregator = TelemetryAggregator(
        current_workers_fn=lambda: [0, 1], journal_interval_s=0.0
    )
    # Unknown keys — including an `event` key that would collide with
    # the journal-record envelope — are dropped, not forwarded.
    marker = time.time() - 1
    aggregator.ingest(
        0,
        json.dumps({"v": 1, "worker_id": 0, "step_p50_s": 0.01,
                    "event": "spoofed", "surprise": {"deep": "junk"}}),
    )
    events = [
        e for e in obs.journal().tail(50)
        if e.get("worker_id") == 0 and e["ts"] >= marker
    ]
    assert events and events[-1]["event"] == "worker_telemetry"
    assert "surprise" not in events[-1]
    aggregator.ingest(0, json.dumps({"v": 1, "step_p95_s": "NaN-ish"}))
    aggregator.ingest(1, _snap(1, p50=0.02))  # other workers unaffected
    assert sorted(aggregator.worker_snapshots()) == [0, 1]
    # The scrape still renders (sanitized values are all numeric).
    assert "elasticdl_worker_step_time_p50_seconds" in (
        obs.registry().render_prometheus()
    )


def test_worker_clock_skew_cannot_reorder_the_journal(obs_registry_snapshot):
    """The snapshot's own `ts` (worker wall clock, possibly skewed hours)
    forwards as `worker_ts`; the journal envelope keeps the MASTER's
    write time so the timeline stays sorted."""
    aggregator = TelemetryAggregator(journal_interval_s=0.0)
    before = time.time()
    aggregator.ingest(
        3, json.dumps({"v": 1, "worker_id": 3, "ts": 12345.0,
                       "step_p50_s": 0.01})
    )
    event = [
        e for e in obs.journal().tail(50)
        if e["event"] == "worker_telemetry" and e.get("worker_id") == 3
    ][-1]
    assert event["worker_ts"] == 12345.0
    assert event["ts"] >= before - 1  # master write time, not 1970+12345s


def test_aggregator_journals_worker_detail_rate_limited(obs_registry_snapshot):
    clock = {"t": 50.0}
    aggregator = TelemetryAggregator(
        clock=lambda: clock["t"], journal_interval_s=10.0
    )
    marker = time.time() - 1
    aggregator.ingest(5, _snap(5, p50=0.01, task={"id": 3}))
    clock["t"] = 51.0
    aggregator.ingest(5, _snap(5, p50=0.01))  # inside the interval: no event
    clock["t"] = 61.0
    aggregator.ingest(5, _snap(5, p50=0.02))  # interval elapsed: journaled
    events = [
        e for e in obs.journal().tail(100)
        if e["event"] == "worker_telemetry" and e.get("worker_id") == 5
        and e["ts"] >= marker
    ]
    assert len(events) == 2
    # Per-worker detail rides the JOURNAL (cardinality rule) and keeps
    # its snapshot fields.
    assert events[0]["task"] == {"id": 3}
    assert events[1]["step_p50_s"] == pytest.approx(0.02)


def test_aggregator_straggler_transitions(obs_registry_snapshot):
    clock = {"t": 10.0}
    aggregator = TelemetryAggregator(
        detector=StragglerDetector(flag_after=2, clear_after=2),
        clock=lambda: clock["t"],
        journal_interval_s=1e9,
    )
    advisories = []
    aggregator.add_straggler_callback(
        lambda wid, flagged, evidence: advisories.append((wid, flagged))
    )
    # No slack on the marker: same-process journal timestamps are
    # fine-grained, and a 1 s window can catch another test's straggler
    # events for the same worker id.
    marker = time.time()
    aggregator.ingest(0, _snap(0, p50=0.010))
    aggregator.ingest(1, _snap(1, p50=0.011))
    for _ in range(3):
        clock["t"] += 0.1
        aggregator.ingest(2, _snap(2, p50=0.500))
    stragglers_gauge = obs.registry().get("elasticdl_stragglers")
    assert stragglers_gauge.value() == 1
    assert list(aggregator.stragglers()) == [2]
    assert advisories == [(2, True)]
    detected = [
        e for e in obs.journal().tail(100)
        if e["event"] == "straggler_detected" and e["ts"] >= marker
    ]
    assert len(detected) == 1
    assert detected[0]["worker_id"] == 2
    assert detected[0]["metric"] == "step_time"
    assert detected[0]["value"] > detected[0]["threshold"]
    # Recovery clears with hysteresis.
    for _ in range(3):
        clock["t"] += 0.1
        aggregator.ingest(2, _snap(2, p50=0.011))
    assert stragglers_gauge.value() == 0
    assert advisories == [(2, True), (2, False)]
    cleared = [
        e for e in obs.journal().tail(100)
        if e["event"] == "straggler_cleared" and e["ts"] >= marker
    ]
    assert len(cleared) == 1 and cleared[0]["worker_id"] == 2


def test_one_noisy_sample_does_not_flag(obs_registry_snapshot):
    """Hysteresis counts FRESH samples from the candidate worker, not
    detector evaluations: other workers' heartbeats re-judging the same
    stale outlier must not burn through flag_after."""
    aggregator = TelemetryAggregator(
        detector=StragglerDetector(flag_after=2, clear_after=2),
        journal_interval_s=1e9,
    )
    for wid in range(4):
        aggregator.ingest(wid, _snap(wid, p50=0.01))
    # One outlier snapshot from worker 4 (a GC pause), then a storm of
    # other workers' heartbeats over the SAME stale sample.
    aggregator.ingest(4, _snap(4, p50=5.0))
    for _ in range(10):
        for wid in range(4):
            aggregator.ingest(wid, _snap(wid, p50=0.01))
    assert aggregator.stragglers() == {}
    # A SECOND slow sample from the worker itself does flag.
    aggregator.ingest(4, _snap(4, p50=5.0))
    assert list(aggregator.stragglers()) == [4]


def test_slow_then_silent_worker_flags_via_staleness(obs_registry_snapshot):
    """A worker that was over the step-time threshold and then goes
    SILENT must still flag: its frozen step evidence yields to staleness
    (which grows on every pass) — the most suspicious worker kind must
    not be the one the detector misses."""
    clock = {"t": 0.0}
    aggregator = TelemetryAggregator(
        detector=StragglerDetector(flag_after=2, clear_after=2),
        clock=lambda: clock["t"],
        journal_interval_s=1e9,
    )
    for wid, p50 in ((0, 0.010), (1, 0.011), (2, 0.012), (3, 0.500)):
        aggregator.ingest(wid, _snap(wid, p50=p50))
    # Worker 3 stops reporting entirely; the healthy fleet keeps beating.
    for beat in range(5):
        clock["t"] += 30.0
        for wid in range(3):
            aggregator.ingest(wid, _snap(wid, p50=0.011))
    assert list(aggregator.stragglers()) == [3]
    assert aggregator.stragglers()[3]["metric"] == "staleness"


def test_aggregator_prunes_departed_worker_reports(obs_registry_snapshot):
    """_reports must not leak across world re-formations: worker ids
    grow monotonically, so unpruned entries accumulate for the life of
    the master."""
    world = {"ids": [0, 1]}
    aggregator = TelemetryAggregator(
        current_workers_fn=lambda: world["ids"], journal_interval_s=1e9
    )
    aggregator.ingest(0, _snap(0, p50=0.01))
    aggregator.ingest(1, _snap(1, p50=0.01))
    world["ids"] = [2, 3]  # restart-the-world: fresh ids
    aggregator.ingest(2, _snap(2, p50=0.01))
    assert sorted(aggregator._reports) == [2]
    # A torn-down world's straggler reporting in is dropped, not cached.
    aggregator.ingest(0, _snap(0, p50=0.01))
    assert sorted(aggregator._reports) == [2]


def test_pod_manager_consumes_straggler_advisories(obs_registry_snapshot):
    from elasticdl_tpu.master.pod_manager import LocalProcessManager

    manager = LocalProcessManager(
        num_workers=1, worker_argv_fn=lambda wid: ["true"]
    )
    counter = obs.registry().get("elasticdl_straggler_advisories_total")
    base = counter.value()
    manager.note_straggler(4, True, {"metric": "step_time"})
    assert manager.current_straggler_ids() == [4]
    assert counter.value() == base + 1
    manager.note_straggler(4, False)
    assert manager.current_straggler_ids() == []


def test_pod_manager_advisories_die_with_the_world(obs_registry_snapshot):
    """A flagged worker that churns must not haunt the advisory set:
    worker ids are never reused, so world launch prunes flags for ids
    outside the new world."""
    from elasticdl_tpu.master.pod_manager import LocalProcessManager

    manager = LocalProcessManager(
        num_workers=2, worker_argv_fn=lambda wid: ["true"]
    )
    manager.note_straggler(5, True)
    assert manager.current_straggler_ids() == [5]
    try:
        manager._launch_world(2)  # ids 0,1 — worker 5 is gone
        assert manager.current_straggler_ids() == []
    finally:
        manager.stop()


# ---------------------------------------------------------------------------
# Heartbeat jitter satellite
# ---------------------------------------------------------------------------


def test_heartbeat_interval_jitter_bounded_and_decorrelated():
    from elasticdl_tpu.parallel.elastic import HeartbeatReporter, WorldInfo

    world = WorldInfo(rank=0, world_size=2, rendezvous_id=1,
                      coordinator_addr="")

    class _Client:
        def __init__(self, worker_id):
            self.worker_id = worker_id

    r0 = HeartbeatReporter(_Client(0), world, host="h", interval_s=5.0)
    r1 = HeartbeatReporter(_Client(1), world, host="h", interval_s=5.0)
    s0 = [r0.jittered_interval_s(t) for t in range(64)]
    s1 = [r1.jittered_interval_s(t) for t in range(64)]
    assert all(4.0 <= v <= 6.0 for v in s0 + s1)  # +/-20% of 5s
    assert len(set(round(v, 9) for v in s0)) > 32  # varies tick to tick
    assert s0 != s1  # decorrelated across workers
    assert s0 == [r0.jittered_interval_s(t) for t in range(64)]  # deterministic
    plain = HeartbeatReporter(
        _Client(0), world, host="h", interval_s=5.0, jitter=0.0
    )
    assert plain.jittered_interval_s(0) == 5.0


# ---------------------------------------------------------------------------
# obs.top
# ---------------------------------------------------------------------------


def test_top_worker_rows_and_render():
    from elasticdl_tpu.obs import top

    now = 1000.0
    events = [
        {"ts": now - 30, "event": "worker_telemetry", "worker_id": 0,
         "step_p50_s": 0.01, "step_p95_s": 0.02, "examples_per_s": 500.0,
         "task": {"id": 7, "records_done": 10, "records_total": 64},
         "rendezvous_id": 2, "rpc": {"retries": 1}},
        {"ts": now - 20, "event": "straggler_detected", "worker_id": 1,
         "metric": "step_time", "value": 1.0},
        {"ts": now - 10, "event": "worker_telemetry", "worker_id": 1,
         "step_p50_s": 1.0, "examples_per_s": 5.0, "rendezvous_id": 2},
        {"ts": now - 5, "event": "worker_telemetry", "worker_id": 0,
         "step_p50_s": 0.011, "step_p95_s": 0.021, "examples_per_s": 480.0,
         "task": {"id": 9, "records_done": 32, "records_total": 64},
         "rendezvous_id": 2, "rpc": {"retries": 1}},
    ]
    rows = top.worker_rows(events, now=now)
    assert [r["worker"] for r in rows] == [0, 1]
    assert rows[0]["task"] == 9  # latest snapshot wins
    assert rows[0]["progress"] == "32/64"
    assert rows[0]["state"] == "ok"
    assert rows[1]["state"] == "STRAGGLER(step_time)"
    assert rows[1]["p95_ms"] == "-"  # missing field renders as a dash
    metrics = top.parse_metrics(
        "# HELP elasticdl_world_size x\n"
        "elasticdl_world_size 2\n"
        "elasticdl_stragglers 1\n"
        'labeled_total{a="b"} 3\n'
    )
    assert metrics == {"elasticdl_world_size": 2.0, "elasticdl_stragglers": 1.0}
    frame = top.render(rows, metrics, addr="localhost:9090")
    assert "world=2" in frame and "stragglers=1" in frame
    assert "STRAGGLER(step_time)" in frame
    # Cleared stragglers drop the marker.
    events.append(
        {"ts": now, "event": "straggler_cleared", "worker_id": 1}
    )
    rows = top.worker_rows(events, now=now)
    assert rows[1]["state"] == "ok"


def test_top_render_without_workers():
    from elasticdl_tpu.obs import top

    frame = top.render([], {}, addr="x:1")
    assert "no worker_telemetry events" in frame


# ---------------------------------------------------------------------------
# validate_journal.py over a real journal
# ---------------------------------------------------------------------------


def _run_validator(*argv):
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "validate_journal.py"),
         *argv],
        capture_output=True, text=True, timeout=60,
    )


def test_validate_journal_accepts_real_journal(tmp_path):
    from elasticdl_tpu.obs.journal import EventJournal

    path = tmp_path / "events.jsonl"
    journal = EventJournal(str(path))
    journal.record("master_start", job_name="j", port=1)
    journal.record("rendezvous", rendezvous_id=1, world_size=2, workers=[0, 1])
    journal.record("task_dispatch", task_id=1, worker_id=0, trace_id="t-a-1")
    journal.record("worker_telemetry", worker_id=0, step_p50_s=0.01)
    journal.record("straggler_detected", worker_id=1, metric="step_time")
    journal.record("straggler_cleared", worker_id=1)
    journal.record("task_done", task_id=1, trace_id="t-a-1", duration_s=0.5)
    journal.close()
    result = _run_validator(str(path))
    assert result.returncode == 0, result.stderr


def test_validate_journal_rejects_malformed(tmp_path):
    path = tmp_path / "broken.jsonl"
    path.write_text(
        '{"ts": 1.0, "event": "task_requeue"}\n'   # missing reason
        'not json\n'
    )
    result = _run_validator(str(path))
    assert result.returncode == 1
    assert "missing required field 'reason'" in result.stderr
    assert "invalid JSON" in result.stderr


def test_validate_journal_selftest():
    result = _run_validator("--selftest")
    assert result.returncode == 0, result.stderr


# ---------------------------------------------------------------------------
# metric-label-cardinality over the new telemetry call sites
# ---------------------------------------------------------------------------


def test_telemetry_call_sites_pass_cardinality_rule():
    """Satellite: the telemetry plane's metric call sites keep worker ids
    out of metric labels (journal-only), and the rule still bites on a
    seeded violation — proving the clean pass is not vacuous."""
    from elasticdl_tpu.analysis.core import SourceFile, run_checks
    from elasticdl_tpu.analysis.rules import check_metric_label_cardinality

    new_call_sites = [
        os.path.join(REPO_ROOT, "elasticdl_tpu", rel)
        for rel in (
            "obs/telemetry.py",
            "obs/top.py",
            "obs/stepstats.py",
            "obs/history.py",
            "obs/slo.py",
            "obs/tracing.py",
            "obs/trace.py",
            "master/servicer.py",
            "master/pod_manager.py",
            "master/task_manager.py",
            "parallel/elastic.py",
            "common/profiler.py",
            "worker/master_client.py",
        )
    ]
    violations = run_checks(new_call_sites, [check_metric_label_cardinality])
    assert violations == [], "\n".join(v.format() for v in violations)
    seeded = SourceFile.parse(
        "seeded.py",
        "from elasticdl_tpu import obs\n"
        "obs.gauge('w_step_seconds', 'h', labelnames=('worker_id',))\n",
    )
    assert check_metric_label_cardinality(seeded), (
        "the rule no longer catches worker_id labels"
    )


# ---------------------------------------------------------------------------
# Acceptance end-to-end: master + heartbeating workers over real gRPC
# ---------------------------------------------------------------------------


def _get(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as response:
        return response.status, response.read().decode()


def test_straggler_and_trace_end_to_end(obs_registry_snapshot):
    """ISSUE acceptance: in a local master + 3 heartbeating workers, an
    artificially slowed worker is flagged within a bounded number of
    heartbeats (journal event + gauge on /metrics), clears when the
    slowdown is removed, and a completed task's trace id links dispatch,
    worker span, and completion across the journal."""
    from elasticdl_tpu.common.constants import TaskExecCounterKey
    from elasticdl_tpu.common.grpc_utils import RetryPolicy
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
    from elasticdl_tpu.master.servicer import (
        MasterServicer,
        start_master_server,
    )
    from elasticdl_tpu.master.task_manager import TaskManager
    from elasticdl_tpu.obs.exporter import MetricsExporter
    from elasticdl_tpu.parallel.elastic import HeartbeatReporter, WorldInfo
    from elasticdl_tpu.proto import elasticdl_pb2 as pb
    from elasticdl_tpu.worker.master_client import MasterClient

    test_start = time.time() - 1
    task_manager = TaskManager(
        training_shards={"shard": 64}, records_per_task=64
    )
    rendezvous = ElasticRendezvous(coordinator_port_fn=lambda host: 23456)
    rendezvous.set_worker_hosts(
        [(0, "127.0.0.1"), (1, "127.0.0.1"), (2, "127.0.0.1")]
    )
    aggregator = TelemetryAggregator(
        detector=StragglerDetector(flag_after=2, clear_after=2),
        current_workers_fn=lambda: [w for w, _h in rendezvous.world()],
    )
    advisories = []
    aggregator.add_straggler_callback(
        lambda wid, flagged, evidence: advisories.append((wid, flagged))
    )
    servicer = MasterServicer(
        task_manager=task_manager,
        rendezvous_server=rendezvous,
        telemetry=aggregator,
    )
    server, port = start_master_server(servicer, port=0)
    policy = RetryPolicy(
        timeout_s=5.0, max_attempts=3, base_backoff_s=0.01,
        max_backoff_s=0.05, jitter=0.0, total_budget_s=30.0,
        wait_for_ready=True,
    )
    clients = [
        MasterClient(f"localhost:{port}", worker_id=wid, retry_policy=policy)
        for wid in range(3)
    ]
    telemetries = {
        wid: WorkerTelemetry(wid, step_window=4) for wid in range(3)
    }
    reporters = [
        HeartbeatReporter(
            clients[wid],
            WorldInfo(rank=wid, world_size=3, rendezvous_id=1,
                      coordinator_addr=""),
            host="127.0.0.1",
            interval_s=0.05,
            telemetry=telemetries[wid],
        )
        for wid in range(3)
    ]
    exporter = MetricsExporter(port=0).start()
    reports_total = obs.registry().get("elasticdl_telemetry_reports_total")
    try:
        # Every worker has step telemetry; worker 2 is 50x slower.
        for wid, per_step in ((0, 0.01), (1, 0.012), (2, 0.5)):
            for _ in range(4):
                telemetries[wid].record_steps(
                    4, duration_s=4 * per_step, records=64
                )
        reports_before = reports_total.value()
        for reporter in reporters:
            reporter.start()

        deadline = time.time() + 60
        while time.time() < deadline and 2 not in aggregator.stragglers():
            time.sleep(0.02)
        assert 2 in aggregator.stragglers(), "slow worker never flagged"
        heartbeats_used = reports_total.value() - reports_before
        # Bounded detection: flag_after=2 means a handful of beats per
        # worker, far under this ceiling even on a loaded CI box.
        assert heartbeats_used <= 90, heartbeats_used
        assert (2, True) in advisories

        # The flag is visible on /metrics and in /journal.
        status, text = _get(f"http://127.0.0.1:{exporter.port}/metrics")
        assert status == 200
        assert "\nelasticdl_stragglers 1" in text
        assert "\nelasticdl_telemetry_workers 3" in text
        assert "\nelasticdl_worker_step_time_p50_seconds " in text
        status, body = _get(f"http://127.0.0.1:{exporter.port}/journal?n=500")
        events = json.loads(body)["events"]
        detected = [
            e for e in events
            if e["event"] == "straggler_detected" and e["ts"] >= test_start
        ]
        assert detected and detected[-1]["worker_id"] == 2
        assert any(
            e["event"] == "worker_telemetry" and e.get("worker_id") == 2
            for e in events
        )

        # obs.top renders the straggler from the same endpoints.
        from elasticdl_tpu.obs import top

        frame = top.snapshot_frame(f"127.0.0.1:{exporter.port}", tail=500)
        assert "STRAGGLER" in frame

        # Remove the slowdown: fresh fast samples displace the slow
        # window (step_window=4) and the flag clears.
        for _ in range(6):
            telemetries[2].record_steps(4, duration_s=4 * 0.011, records=64)
        deadline = time.time() + 60
        while time.time() < deadline and 2 in aggregator.stragglers():
            time.sleep(0.02)
        assert 2 not in aggregator.stragglers(), "straggler never cleared"
        assert (2, False) in advisories
        assert any(
            e["event"] == "straggler_cleared" and e["ts"] >= test_start
            for e in obs.journal().tail(500)
        )

        # ---- trace correlation across the process boundary ------------
        task = clients[0].get_task()
        assert task.task_id > 0 and task.trace_id
        # Worker half: span journal record stamped with the dispatch id.
        with obs.span(
            "worker.task", labels={"type": "TRAINING"},
            task_id=task.task_id, trace_id=task.trace_id,
        ):
            pass
        # Completion over REAL gRPC with the trace id as call metadata.
        clients[0].report_task_result(
            task.task_id,
            "",
            exec_counters={TaskExecCounterKey.BATCH_COUNT: 1,
                           TaskExecCounterKey.RECORD_COUNT: 64},
            trace_id=task.trace_id,
        )
        chain = [
            e for e in obs.journal().tail(500)
            if e.get("trace_id") == task.trace_id
        ]
        kinds = [e["event"] for e in chain]
        # The tracing plane (obs/tracing.py) grew the chain: beyond the
        # point events, every hop journals a span — client + servicer
        # halves of both RPCs, the worker task span, and the master's
        # task.lifetime root (span_id == trace_id).
        assert kinds[0] == "task_dispatch" and "task_done" in kinds, kinds
        dispatch = chain[0]
        done = next(e for e in chain if e["event"] == "task_done")
        span_names = {
            e["name"] for e in chain if e["event"] == "span"
        }
        assert span_names >= {
            "worker.get_task", "rpc.get_task", "worker.task",
            "worker.report_task", "rpc.report_task_result",
            "task.lifetime",
        }, span_names
        root = next(
            e for e in chain
            if e["event"] == "span" and e["name"] == "task.lifetime"
        )
        assert root["span_id"] == task.trace_id
        assert dispatch["worker_id"] == 0 and dispatch["task_id"] == task.task_id
        worker_span = next(
            e for e in chain
            if e["event"] == "span" and e["name"] == "worker.task"
        )
        assert worker_span["span_id"] and worker_span["start_ts"] > 0
        assert done["task_id"] == task.task_id
        assert done["worker_id"] == 0
        # The metadata echo matched the stored id: no mismatch field.
        assert "reported_trace_id" not in done
    finally:
        for reporter in reporters:
            reporter.stop()
        exporter.stop()
        for client in clients:
            client.close()
        server.stop(grace=None)
