"""Unit surface of the deterministic fault-injection registry
(common/faults.py) and the checkpoint integrity-manifest helpers it
perturbs (checkpoint/saver.py)."""

import os
import zlib

import numpy as np
import pytest

from elasticdl_tpu.common import faults
from elasticdl_tpu.checkpoint.saver import (
    file_crc32,
    verify_integrity,
    write_integrity_manifest,
)


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# Spec grammar
# ---------------------------------------------------------------------------


def test_parse_full_spec():
    (spec,) = faults.parse_specs("rpc.get_task:error=UNAVAILABLE@3x2")
    assert spec.site == "rpc.get_task"
    assert spec.kind == "error"
    assert spec.arg == "UNAVAILABLE"
    assert (spec.after, spec.count) == (3, 2)


def test_parse_defaults_and_forever():
    one, forever = faults.parse_specs(
        "ckpt.write:truncate, worker.task:crash=7x*"
    )
    assert (one.after, one.count, one.arg) == (1, 1, "")
    assert (forever.after, forever.count, forever.arg) == (1, -1, "7")


def test_parse_semicolon_separator_and_whitespace():
    specs = faults.parse_specs(" rpc.a:latency=0.5 ; rpc.b:error ")
    assert [s.site for s in specs] == ["rpc.a", "rpc.b"]


@pytest.mark.parametrize(
    "bad",
    ["rpc.a", "rpc.a:explode", "rpc.a:error@0", "rpc.a:errorx0", "rpc.a:error@x"],
)
def test_parse_rejects_malformed_specs(bad):
    with pytest.raises(ValueError):
        faults.parse_specs(bad)


# ---------------------------------------------------------------------------
# Trigger semantics
# ---------------------------------------------------------------------------


def test_fire_triggers_by_call_count_only():
    faults.install("s:error@2x2")
    hits = [faults.fire("s") is not None for _ in range(5)]
    assert hits == [False, True, True, False, False]
    assert faults.call_count("s") == 5


def test_sites_count_independently():
    faults.install("a:error@1")
    assert faults.fire("a") is not None
    assert faults.fire("b") is None
    assert faults.call_count("a") == 1
    assert faults.call_count("b") == 1


def test_install_from_env_and_clear(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "x:latency=0.1@1")
    assert faults.install_from_env()
    assert faults.enabled()
    faults.clear()
    assert not faults.enabled()
    monkeypatch.delenv(faults.ENV_VAR)
    assert not faults.install_from_env()


def test_reinstall_resets_counters():
    faults.install("s:error@1")
    faults.fire("s")
    faults.install("s:error@1")
    assert faults.call_count("s") == 0
    assert faults.fire("s") is not None


# ---------------------------------------------------------------------------
# Schedule-based triggers (@t<seconds>): the preemption-storm primitive
# ---------------------------------------------------------------------------


def test_parse_schedule_trigger():
    (spec,) = faults.parse_specs("storm.preempt:crash@t2.5")
    assert spec.site == "storm.preempt"
    assert spec.kind == "crash"
    assert spec.at_s == 2.5
    assert spec.triggers_at(1) is False  # never via the call-count path


@pytest.mark.parametrize(
    "bad", ["s:crash@t-1", "s:crash@tx", "s:crash@t1.5x2", "s:crash@t2x*"]
)
def test_parse_rejects_bad_schedule_specs(bad):
    with pytest.raises(ValueError):
        faults.parse_specs(bad)


def test_due_fires_each_schedule_spec_exactly_once():
    faults.install(
        "storm.preempt:crash@t1.0, storm.preempt:crash@t2.0, other:crash@t1.0"
    )
    assert faults.remaining_due("storm.preempt") == 2
    assert faults.due("storm.preempt", 0.5) == []
    (first,) = faults.due("storm.preempt", 1.5)
    assert first.at_s == 1.0
    # Re-polling the same elapsed time must not re-fire it.
    assert faults.due("storm.preempt", 1.5) == []
    assert faults.remaining_due("storm.preempt") == 1
    # A late poll returns everything newly due, oldest first.
    hits = faults.due("storm.preempt", 10.0)
    assert [spec.at_s for spec in hits] == [2.0]
    assert faults.remaining_due("storm.preempt") == 0
    # Other sites' schedules are independent.
    assert faults.remaining_due("other") == 1


def test_due_and_fire_are_independent_paths():
    faults.install("s:error@1, s:crash@t0.0")
    # fire() sees only the call-count spec...
    assert faults.fire("s").kind == "error"
    # ...and due() only the schedule spec.
    (hit,) = faults.due("s", 0.0)
    assert hit.kind == "crash"
    assert faults.due("s", 99.0) == []


def test_due_disarmed_registry_is_empty():
    assert faults.due("anything", 100.0) == []
    assert faults.remaining_due("anything") == 0


# ---------------------------------------------------------------------------
# Continuous train->serve loop sites (docs/failure_model.md): the specs
# the chaos e2e installs.  Site semantics are exercised end-to-end in
# test_stream.py / test_delta.py; here we pin the spec grammar.
# ---------------------------------------------------------------------------


def test_parse_continuous_loop_sites():
    specs = faults.parse_specs(
        "stream.source:latency=1.5@t2.0,"
        " ckpt.delta:truncate@2,"
        " serving.delta_apply:error=boom@3"
    )
    stall, torn, apply_fail = specs

    # Source stall: schedule-triggered latency the driver converts into
    # stream.stall(arg) — availability shifts, event-time does not.
    assert stall.site == "stream.source"
    assert stall.kind == "latency"
    assert stall.arg == "1.5" and float(stall.arg) == 1.5
    assert stall.at_s == 2.0
    assert stall.triggers_at(1) is False  # schedule path only

    # Torn delta: fires on the Nth publish, after the checksum is
    # manifested — the consumer must prove and quarantine it.
    assert torn.site == "ckpt.delta"
    assert torn.kind == "truncate"
    assert torn.at_s is None and torn.triggers_at(2)
    assert not torn.triggers_at(1) and not torn.triggers_at(3)

    # Failed apply: raises inside apply_delta, forcing the atomic
    # rollback; exhausted after one firing so the retry lands.
    assert apply_fail.site == "serving.delta_apply"
    assert apply_fail.kind == "error"
    assert apply_fail.arg == "boom"
    assert apply_fail.triggers_at(3) and not apply_fail.triggers_at(4)


def test_continuous_loop_sites_fire_independently():
    faults.install(
        "ckpt.delta:truncate@1, serving.delta_apply:error=injected@1"
    )
    assert faults.fire("ckpt.delta").kind == "truncate"
    assert faults.fire("ckpt.delta") is None  # exhausted
    hit = faults.fire("serving.delta_apply")
    assert hit.kind == "error" and hit.arg == "injected"
    assert faults.fire("stream.source") is None  # never installed


def test_parse_quality_plane_sites():
    specs = faults.parse_specs(
        "stream.labels:error=flip@2x3,"
        " stream.labels:truncate@9,"
        " quality.label_join:error@1,"
        " quality.shadow_eval:error=poisoned-eval@1x*"
    )
    poison, outage, drop, shadow = specs

    # Poisoned feed: every label in the fetched range flips — the
    # label-flipped-shard chaos scenario the canary gate must hold.
    assert poison.site == "stream.labels"
    assert poison.kind == "error" and poison.arg == "flip"
    assert poison.triggers_at(2) and poison.triggers_at(4)
    assert not poison.triggers_at(1) and not poison.triggers_at(5)

    # Outage: the range returns None — no labels arrive, quality goes
    # UNKNOWN (the gate's configurable-policy path, never a crash).
    assert outage.site == "stream.labels"
    assert outage.kind == "truncate" and outage.triggers_at(9)

    # Join-side drop and at-least-once duplicate ride the same site.
    assert drop.site == "quality.label_join"
    assert drop.kind == "error" and drop.triggers_at(1)

    # Shadow-eval blowup: forever-firing spec (x*) keeps quality
    # unknown across every poll — the degradation the e2e pins.
    assert shadow.site == "quality.shadow_eval"
    assert shadow.kind == "error" and shadow.arg == "poisoned-eval"
    assert shadow.count == -1 and shadow.triggers_at(500)


def test_quality_sites_fire_independently():
    faults.install(
        "stream.labels:truncate@1, quality.label_join:truncate@1,"
        " quality.shadow_eval:error@1"
    )
    assert faults.fire("stream.labels").kind == "truncate"
    assert faults.fire("stream.labels") is None  # exhausted
    assert faults.fire("quality.label_join").kind == "truncate"
    assert faults.fire("quality.shadow_eval").kind == "error"
    assert faults.fire("quality.shadow_eval") is None


def test_stream_labels_fault_flips_and_blacks_out():
    from elasticdl_tpu.data import stream

    feats = stream.synthetic_click_batch(0, 16, 100)
    clean = stream.click_label_rule(feats)
    faults.install("stream.labels:error@1, stream.labels:truncate@2")
    flipped = stream.feedback_labels(feats)
    assert np.array_equal(flipped, 1.0 - clean)  # poisoned: all flipped
    assert stream.feedback_labels(feats) is None  # outage: no labels
    assert np.array_equal(stream.feedback_labels(feats), clean)  # healthy


# ---------------------------------------------------------------------------
# Integrity manifest helpers
# ---------------------------------------------------------------------------


def test_file_crc32_matches_zlib(tmp_path):
    payload = b"x" * (3 << 20) + b"tail"
    path = tmp_path / "blob"
    path.write_bytes(payload)
    assert file_crc32(str(path)) == zlib.crc32(payload)


def test_verify_integrity_passes_and_detects_each_corruption(tmp_path):
    (tmp_path / "a.bin").write_bytes(b"hello")
    (tmp_path / "b.bin").write_bytes(b"world!")
    write_integrity_manifest(str(tmp_path), ["a.bin", "b.bin"])
    assert verify_integrity(str(tmp_path)) is None

    # Same-size bit flip -> crc mismatch.
    (tmp_path / "a.bin").write_bytes(b"hellO")
    reason = verify_integrity(str(tmp_path))
    assert reason is not None and "a.bin" in reason and "crc32" in reason

    # Truncation -> size mismatch (reported as a torn write).
    (tmp_path / "a.bin").write_bytes(b"hello")
    (tmp_path / "b.bin").write_bytes(b"wor")
    reason = verify_integrity(str(tmp_path))
    assert reason is not None and "b.bin" in reason and "torn write" in reason

    # Inventoried file missing from a committed dir: proven corruption.
    os.unlink(tmp_path / "b.bin")
    assert "missing" in verify_integrity(str(tmp_path))

    # Garbage manifest: proven corruption (a torn manifest write).
    (tmp_path / "b.bin").write_bytes(b"world!")
    (tmp_path / "integrity.json").write_text("{not json")
    assert "garbage" in verify_integrity(str(tmp_path))


def test_verify_integrity_vacuous_without_manifest(tmp_path):
    (tmp_path / "a.bin").write_bytes(b"anything")
    assert verify_integrity(str(tmp_path)) is None  # pre-integrity snapshot
