"""`elasticdl zoo` subcommand tests (reference: elasticdl_client
image_builder).  Everything short of invoking the docker daemon is real:
init scaffolds a loadable zoo module; build renders a self-contained
docker context (framework + zoo + Dockerfile)."""

import os

from elasticdl_tpu.client import zoo


def test_init_scaffolds_loadable_module(tmp_path):
    path = str(tmp_path / "myzoo")
    assert zoo.main(["init", path]) == 0
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.model_utils import load_model_spec

    spec = load_model_spec(
        parse_master_args(
            ["--model_zoo", path, "--model_def", "my_model",
             "--training_data", "t"]
        )
    )
    model = spec.build_model()
    import jax
    import numpy as np

    variables = model.init(jax.random.PRNGKey(0), np.zeros((2, 4), np.float32))
    out = model.apply(variables, np.zeros((2, 4), np.float32))
    assert out.shape == (2, 2)


def test_build_renders_self_contained_context(tmp_path):
    zoo_dir = str(tmp_path / "myzoo")
    zoo.main(["init", zoo_dir])
    context = str(tmp_path / "ctx")
    rc = zoo.main(
        ["build", zoo_dir, "--context", context, "--dockerfile-only",
         "--base-image", "my-jax-base:latest"]
    )
    assert rc == 0
    dockerfile = open(os.path.join(context, "Dockerfile")).read()
    assert "FROM my-jax-base:latest" in dockerfile
    assert "COPY elasticdl_tpu/" in dockerfile
    assert "COPY myzoo/" in dockerfile
    # Context is self-contained: framework package + zoo + no caches.
    assert os.path.exists(
        os.path.join(context, "elasticdl_tpu", "master", "pod_manager.py")
    )
    assert os.path.exists(os.path.join(context, "myzoo", "my_model.py"))
    assert not any(
        "__pycache__" in root for root, _, _ in os.walk(context)
    )


def test_build_missing_zoo_errors(tmp_path, capsys):
    rc = zoo.main(
        ["build", str(tmp_path / "nope"), "--context",
         str(tmp_path / "ctx"), "--dockerfile-only"]
    )
    assert rc == 1
    assert "not found" in capsys.readouterr().err


def test_build_refuses_context_overwriting_source(tmp_path, capsys):
    """`--context` pointing at the source's parent must never rmtree the
    user's real code."""
    zoo_dir = str(tmp_path / "myzoo")
    zoo.main(["init", zoo_dir])
    rc = zoo.main(
        ["build", zoo_dir, "--context", str(tmp_path), "--dockerfile-only"]
    )
    assert rc == 1
    assert "overwrite or nest" in capsys.readouterr().err
    assert os.path.exists(os.path.join(zoo_dir, "my_model.py"))  # intact
    # Nested-inside-source case: context under the zoo dir itself.
    rc = zoo.main(
        ["build", zoo_dir, "--context", os.path.join(zoo_dir, "ctx"),
         "--dockerfile-only"]
    )
    assert rc == 1
    assert os.path.exists(os.path.join(zoo_dir, "my_model.py"))
