"""Test harness configuration.

Emulates an 8-chip TPU slice on CPU (SURVEY.md §4: the fake-device layer) so
pjit/shard_map/psum and mesh re-formation logic are exercised without
hardware.  Must run before the first `import jax` anywhere in the test
process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep XLA compilation single-threaded-friendly on the 1-core CI host.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

# The environment's TPU plugin (sitecustomize) force-updates jax_platforms
# at interpreter start, overriding the env var — pin it back to CPU before
# any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")
