"""Test harness configuration.

Emulates an 8-chip TPU slice on CPU (SURVEY.md §4: the fake-device layer) so
pjit/shard_map/psum and mesh re-formation logic are exercised without
hardware.  Must run before the first `import jax` anywhere in the test
process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep XLA compilation single-threaded-friendly on the 1-core CI host.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

# The environment's TPU plugin (sitecustomize) force-updates jax_platforms
# at interpreter start, overriding the env var — pin it back to CPU before
# any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")


def run_kill_recovery_job(
    args, n_records, worker_env, log_dir, progress_fraction=8,
    wait_timeout=480,
):
    """Shared kill-a-worker elasticity driver (used by the AllReduce and
    context-parallel e2es): start a 2-worker job, wait for real progress,
    SIGKILL the rank-1 worker (restart budget 0), and assert the world
    shrank to ONE fresh worker while every record still trained."""
    import time

    from elasticdl_tpu.master.main import start_master
    from elasticdl_tpu.master.pod_manager import (
        LocalProcessManager,
        worker_argv_from_args,
    )
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous

    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=2,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env=worker_env,
        log_dir=log_dir,
        job_finished_fn=master.task_manager.finished,
    )
    try:
        manager.start()
        deadline = time.time() + 300
        while (
            master.task_manager.finished_record_count
            < n_records // progress_fraction
        ):
            assert time.time() < deadline, "no progress before kill"
            assert not master.task_manager.finished(), "finished too fast"
            time.sleep(0.05)
        victims = manager.current_worker_ids()
        assert len(victims) == 2
        manager.kill_worker(victims[1])
        assert manager.wait(timeout=wait_timeout) is True
        assert master.task_manager.finished()
        assert master.task_manager.finished_record_count == n_records
        # The world actually shrank: a relaunch happened with 1 FRESH
        # worker (not the survivor continuing unperturbed).
        assert manager.current_worker_ids() != victims
        assert len(manager.current_worker_ids()) == 1
    finally:
        manager.stop()
        master.stop()
