"""Test harness configuration.

Emulates an 8-chip TPU slice on CPU (SURVEY.md §4: the fake-device layer) so
pjit/shard_map/psum and mesh re-formation logic are exercised without
hardware.  Must run before the first `import jax` anywhere in the test
process.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep XLA compilation single-threaded-friendly on the 1-core CI host.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "2")

# The environment's TPU plugin (sitecustomize) force-updates jax_platforms
# at interpreter start, overriding the env var — pin it back to CPU before
# any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")

import pytest


@pytest.fixture
def obs_registry_snapshot():
    """Save/restore the process-wide obs registry around a test so
    metrics registered inside it (telemetry aggregators, ad-hoc gauges)
    can't leak into another test's scrape.  RESTORE, not reset(): metric
    objects bound at import time (the RPC retry counters in
    common/grpc_utils) must keep their registry membership — clearing
    would orphan them for the rest of the session.  For the same reason,
    every import-time registrant is imported BEFORE the snapshot: if the
    test itself triggered that first import, restore would silently
    unregister the freshly-bound module constants.  Yields the registry.
    """
    import elasticdl_tpu.common.grpc_utils  # noqa: F401 — import-time metrics
    from elasticdl_tpu import obs

    registry = obs.registry()
    saved = registry.snapshot()
    try:
        yield registry
    finally:
        registry.restore(saved)


def run_kill_recovery_job(
    args, n_records, worker_env, log_dir, progress_fraction=8,
    wait_timeout=480, recovery_bound_s=240.0,
):
    """Shared kill-a-worker elasticity driver (used by the AllReduce and
    context-parallel e2es): start a 2-worker job, wait for real progress,
    SIGKILL the rank-1 worker (restart budget 0), and assert the world
    shrank to ONE fresh worker while every record still trained.

    Quantifies the elasticity claim (BASELINE.md "Elasticity" section):
    returns {"recovery_s": SIGKILL -> first record finished by the
    re-formed world (process start + world re-formation + checkpoint
    restore + compile + first task), "replayed_records": at-least-once
    replay cost (task ranges requeued from the dead worker)} and asserts
    recovery under `recovery_bound_s` — the regression tripwire."""
    import time

    from elasticdl_tpu.master.main import start_master
    from elasticdl_tpu.master.pod_manager import (
        LocalProcessManager,
        worker_argv_from_args,
    )
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous

    rendezvous = ElasticRendezvous()
    master = start_master(args, rendezvous_server=rendezvous)
    manager = LocalProcessManager(
        num_workers=2,
        worker_argv_fn=worker_argv_from_args(args, master.addr),
        rendezvous=rendezvous,
        task_manager=master.task_manager,
        max_restarts=0,
        worker_env=worker_env,
        log_dir=log_dir,
        job_finished_fn=master.task_manager.finished,
    )
    try:
        manager.start()
        deadline = time.time() + 300
        while (
            master.task_manager.finished_record_count
            < n_records // progress_fraction
        ):
            assert time.time() < deadline, "no progress before kill"
            assert not master.task_manager.finished(), "finished too fast"
            time.sleep(0.05)
        victims = manager.current_worker_ids()
        assert len(victims) == 2
        replayed_before = master.task_manager.recovered_record_count
        t_kill = time.monotonic()
        manager.kill_worker(victims[1])
        # Recovery clock: kill -> the re-formed world finishes its first
        # record.  The count baseline is read only AFTER the relaunch is
        # visible (fresh worker ids) — the dying world's stragglers can
        # still report for a few seconds after the SIGKILL, and counting
        # those as "recovery" would fake a ~0s number.  The re-formed
        # workers need seconds to boot, far above the 20 ms poll, so the
        # baseline is race-free in practice.
        probe_deadline = time.time() + wait_timeout
        while time.time() < probe_deadline:
            ids = manager.current_worker_ids()
            if ids and not set(ids) & set(victims):
                break  # all-fresh world: relaunch happened
            time.sleep(0.02)
        count_at_relaunch = master.task_manager.finished_record_count
        recovery_s = None
        while time.time() < probe_deadline:
            if master.task_manager.finished_record_count > count_at_relaunch:
                recovery_s = time.monotonic() - t_kill
                break
            time.sleep(0.02)
        assert recovery_s is not None, "no post-kill progress"
        assert manager.wait(timeout=wait_timeout) is True
        assert master.task_manager.finished()
        assert master.task_manager.finished_record_count == n_records
        # The world actually shrank: a relaunch happened with 1 FRESH
        # worker (not the survivor continuing unperturbed).
        assert manager.current_worker_ids() != victims
        assert len(manager.current_worker_ids()) == 1
        replayed = (
            master.task_manager.recovered_record_count - replayed_before
        )
        # Replay is task-granular (whole ranges requeue; the exact
        # accounting is unit-tested in test_task_manager) and bounded by
        # what the dead world could have held in flight.
        assert replayed % args.records_per_task == 0, replayed
        assert recovery_s < recovery_bound_s, (
            f"recovery took {recovery_s:.1f}s (bound {recovery_bound_s}s) — "
            "the restore path regressed"
        )
        metrics = {
            "recovery_s": recovery_s,
            "replayed_records": replayed,
            "records_done_at_relaunch": count_at_relaunch,
        }
        print(f"ELASTICITY_METRICS {metrics}", flush=True)
        return metrics
    finally:
        manager.stop()
        master.stop()
