"""ODPS table reader tests over a fake TableClient (the real `odps` SDK
is cloud-specific; the reader's sharding/range semantics — what the task
queue depends on — are transport-independent and pinned here)."""

import numpy as np
import pytest

from elasticdl_tpu.data.odps_reader import ODPSDataReader, TableClient
from elasticdl_tpu.data.reader import create_data_reader
from elasticdl_tpu.proto import elasticdl_pb2 as pb


class FakeTableClient(TableClient):
    def __init__(self, rows, columns=("a", "b")):
        self.rows = rows
        self.columns = list(columns)
        self.read_calls = []

    def row_count(self, table, partition):
        assert table == "mytable"
        return len(self.rows)

    def read_rows(self, table, partition, start, count, columns):
        self.read_calls.append((start, count))
        for row in self.rows[start : start + count]:
            yield row

    def column_names(self, table):
        return self.columns


def _task(shard, start, end):
    return pb.Task(task_id=1, shard_name=shard, start=start, end=end)


@pytest.fixture
def fake_client():
    return FakeTableClient([[i, f"v{i}"] for i in range(100)])


def test_shards_and_range_reads(fake_client):
    reader = ODPSDataReader(table="mytable", client=fake_client)
    assert reader.create_shards() == {"mytable": 100}
    rows = list(reader.read_records(_task("mytable", 40, 45)))
    assert rows == [[i, f"v{i}"] for i in range(40, 45)]
    # Range pushdown: only the requested window crossed the transport.
    assert fake_client.read_calls == [(40, 5)]
    assert reader.metadata.column_names == ["a", "b"]


def test_partition_names_shard(fake_client):
    reader = ODPSDataReader(
        table="mytable", partition="dt=20260730", client=fake_client
    )
    assert reader.create_shards() == {"mytable/dt=20260730": 100}


def test_columns_filter_and_empty_range(fake_client):
    reader = ODPSDataReader(
        table="mytable", columns="b;a", client=fake_client
    )
    assert reader.metadata.column_names == ["b", "a"]
    assert list(reader.read_records(_task("mytable", 7, 7))) == []


def test_factory_resolves_odps_scheme(fake_client, monkeypatch):
    import elasticdl_tpu.data.odps_reader as mod

    captured = {}
    original = mod.ODPSDataReader

    def spy(**kwargs):
        captured.update(kwargs)
        kwargs["client"] = fake_client
        return original(**kwargs)

    monkeypatch.setattr(mod, "ODPSDataReader", spy)
    reader = create_data_reader("odps://mytable")
    assert reader.create_shards() == {"mytable": 100}


def test_missing_credentials_fail_clearly(monkeypatch):
    for var in ("ODPS_ACCESS_ID", "ODPS_ACCESS_KEY", "ODPS_PROJECT_NAME"):
        monkeypatch.delenv(var, raising=False)
    with pytest.raises(ValueError, match="ODPS credentials"):
        ODPSDataReader(table="mytable")
