"""ResNet-50 model-zoo config (BASELINE config 5).

Parity surface: model_zoo/resnet50_subclass in the reference.  CPU tests
use small images/classes (the architecture is size-agnostic past the
stem); the bench exercises the real 224x1000 shape on the chip.
"""

import numpy as np
import optax
import pytest

from elasticdl_tpu.worker.trainer import Trainer
from model_zoo import datasets
from model_zoo.resnet50 import resnet50_subclass as zoo


def test_architecture_shapes():
    """50 layers: 1 stem conv + 3*(3+4+6+3) bottleneck convs + fc, with
    4x filter expansion per stage."""
    import jax
    import jax.numpy as jnp

    model = zoo.custom_model(num_classes=10, use_bf16=False)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    out = model.apply(variables, x)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32
    n_conv = sum(1 for k in _flat_keys(variables["params"]) if "Conv" in k)
    # stem + 16 blocks x 3 convs + projection shortcuts (4 stages)
    assert n_conv == 1 + 16 * 3 + 4
    n_params = sum(
        int(np.prod(p.shape)) for p in jax.tree.leaves(variables["params"])
    )
    assert 23_000_000 < n_params < 24_500_000  # ~23.5M at 10 classes


def _flat_keys(tree, prefix=""):
    keys = []
    for name, value in tree.items():
        path = f"{prefix}/{name}"
        if isinstance(value, dict):
            keys.extend(_flat_keys(value, path))
        else:
            keys.append(path)
    return keys


def test_trains_and_bn_state_updates():
    model = zoo.custom_model(num_classes=4, use_bf16=True)
    trainer = Trainer(model, zoo.loss, optax.sgd(0.05, momentum=0.9), seed=0)
    rng = np.random.RandomState(0)
    # Raw uint8 pixels: the input contract since round 5 — the model
    # normalizes (0-255 scale) on device.
    images = rng.randint(0, 256, size=(8, 32, 32, 3)).astype(np.uint8)
    labels = rng.randint(0, 4, size=8).astype(np.int32)
    trainer.ensure_initialized(images)
    bn_before = {
        k: v.copy()
        for k, v in trainer.get_variables_numpy().items()
        if "batch_stats" in k
    }
    assert bn_before, "BatchNorm state must live in model_state"
    losses = [float(trainer.train_step(images, labels)) for _ in range(5)]
    assert losses[-1] < losses[0], losses
    bn_after = trainer.get_variables_numpy()
    assert any(
        np.abs(bn_after[k] - v).max() > 0 for k, v in bn_before.items()
    ), "BN running stats never updated"


def test_synthetic_imagenet_reader_learnable():
    reader = datasets.synthetic_imagenet_reader(
        n=32, image_size=64, num_classes=8, seed=1
    )
    assert reader.create_shards() == {"imagenet-synth": 32}

    class _Task:
        shard_name, start, end = "imagenet-synth", 0, 32

    records = list(reader.read_records(_Task()))
    assert len(records) == 32
    image, label = records[0]
    assert image.shape == (64, 64, 3) and image.dtype == np.uint8
    # Deterministic across readers with the same seed.
    again = list(
        datasets.synthetic_imagenet_reader(
            n=32, image_size=64, num_classes=8, seed=1
        ).read_records(_Task())
    )
    np.testing.assert_array_equal(records[5][0], again[5][0])


def test_custom_data_reader_path_roundtrip():
    reader = zoo.custom_data_reader("synthetic://imagenet?n=16&size=64&classes=8")
    assert reader is not None
    assert reader.create_shards() == {"imagenet-synth": 16}
    assert zoo.custom_data_reader("/real/path.csv") is None
