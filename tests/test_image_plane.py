"""Image data plane (round-5 VERDICT #1): ETRF-packed uint8 images,
vectorized parse, host augmentation, and ResNet-50 training from files
through the task pipeline — the vision twin of the DeepFM record plane.

Parity surface: SURVEY §2.2 data readers + §3.3 worker dataset assembly
(†elasticdl/python/data/reader/, †task_data_service.py) for the vision
configs.
"""

import pytest

# Tier-1 fast gate runs `-m 'not slow'` (see Makefile test-fast).
pytestmark = pytest.mark.slow

import numpy as np
import pytest

from elasticdl_tpu.data import image as image_plane
from model_zoo.resnet50 import resnet50_subclass as zoo


def _synthetic_images(n, size, classes=8, seed=0):
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(n, size, size, 3)).astype(np.uint8)
    labels = rng.integers(0, classes, size=n).astype(np.int32)
    return images, labels


def test_etrf_image_roundtrip(tmp_path):
    path = str(tmp_path / "img.etrf")
    images, labels = _synthetic_images(12, 20)
    image_plane.write_image_etrf(path, images, labels)

    reader = zoo.ImageRecordReader(path)
    assert reader._size == 20  # inferred from the record width
    assert reader.create_shards() == {path: 12}

    class _Task:
        start, end = 3, 9

    cols = next(iter(reader.read_columns(_Task)))
    np.testing.assert_array_equal(
        cols["image"].reshape((6, 20, 20, 3)), images[3:9]
    )
    np.testing.assert_array_equal(cols["label"][:, 0], labels[3:9])

    rows = list(reader.read_records(_Task))
    np.testing.assert_array_equal(rows[0][0], images[3])
    assert rows[0][1] == labels[3]


def test_random_crop_flip_is_window_of_source():
    images, _ = _synthetic_images(16, 24, seed=1)
    rng = np.random.default_rng(3)
    out = image_plane.random_crop_flip(images, 18, rng)
    assert out.shape == (16, 18, 18, 3) and out.dtype == np.uint8
    # Every output is some 18x18 window of its source (possibly flipped).
    for i in range(4):
        found = False
        for flipped in (out[i], out[i, :, ::-1]):
            for dy in range(24 - 18 + 1):
                for dx in range(24 - 18 + 1):
                    if np.array_equal(
                        flipped, images[i, dy:dy + 18, dx:dx + 18]
                    ):
                        found = True
        assert found, f"sample {i} is not a crop/flip of its source"
    # Same-size crop without flip is the identity.
    same = image_plane.random_crop_flip(
        images, 24, np.random.default_rng(0), flip=False
    )
    np.testing.assert_array_equal(same, images)
    with pytest.raises(ValueError):
        image_plane.random_crop_flip(images, 25, rng)


def test_center_crop():
    images, _ = _synthetic_images(3, 21)
    out = image_plane.center_crop(images, 15)
    np.testing.assert_array_equal(out, images[:, 3:18, 3:18])


def test_columnar_dataset_fn_train_and_eval(monkeypatch):
    images, labels = _synthetic_images(10, 16, seed=2)
    columns = {
        "image": images.reshape((10, -1)),
        "label": labels.reshape((10, 1)),
    }
    monkeypatch.setattr(zoo, "IMAGE_SIZE", 12)
    feats, labs = zoo.columnar_dataset_fn(dict(columns), "training", None)
    assert feats.shape == (10, 12, 12, 3) and feats.dtype == np.uint8
    assert labs.shape == (10,)
    # Eval path: deterministic center crop, labels unpermuted.
    feats_e, labs_e = zoo.columnar_dataset_fn(
        dict(columns), "evaluation", None
    )
    np.testing.assert_array_equal(feats_e, images[:, 2:14, 2:14])
    np.testing.assert_array_equal(labs_e, labels)
    # Records smaller than the train size pass through at their own size.
    monkeypatch.setattr(zoo, "IMAGE_SIZE", 224)
    feats_s, _ = zoo.columnar_dataset_fn(dict(columns), "evaluation", None)
    assert feats_s.shape == (10, 16, 16, 3)


def test_resnet_trains_from_etrf_through_task_pipeline(tmp_path):
    """The VERDICT 'Done' gate: ResNet fed from an ETRF image file
    through the real task pipeline (master task queue -> reader ->
    columnar materialization -> trainer), in-process Local mode."""
    from elasticdl_tpu.client import api
    from elasticdl_tpu.common.args import parse_master_args

    path = str(tmp_path / "imagenet.etrf")
    images, labels = _synthetic_images(64, 24, classes=4, seed=4)
    # Make the task learnable: class-dependent bright patch.
    for cls in range(4):
        images[labels == cls, 2 + cls * 5 : 6 + cls * 5, 2:6, cls % 3] = 250
    image_plane.write_image_etrf(path, images, labels)

    args = parse_master_args([
        "--model_zoo", "model_zoo",
        "--model_def", "resnet50.resnet50_subclass",
        "--model_params", "num_classes=4",
        "--distribution_strategy", "Local",
        "--training_data", path,
        "--minibatch_size", "8",
        "--num_epochs", "2",
        "--output", str(tmp_path / "model"),
    ])
    assert api._run_local(args, mode="training") == 0

    # The servable artifact predicts from RAW uint8 (the round-5 input
    # contract: normalization lives in the model, on device).
    from elasticdl_tpu.serving import load_for_serving

    served = load_for_serving(str(tmp_path / "model"))
    out = np.asarray(served.predict(images[:4]))
    assert out.shape == (4, 4) and np.isfinite(out).all()


def test_per_record_dataset_fn_matches_columnar_geometry(monkeypatch):
    """The per-record path (Local mode / non-columnar readers) must feed
    the SAME image geometry as the columnar fast path: train = random
    crop+flip to IMAGE_SIZE, eval = center crop; smaller records pass
    through at their own size."""
    from elasticdl_tpu.data.dataset import Dataset

    monkeypatch.setattr(zoo, "IMAGE_SIZE", 12)
    images, labels = _synthetic_images(6, 16, seed=11)
    records = list(zip(images, labels))

    train = zoo.dataset_fn(Dataset.from_iterable(records), "training", None)
    train_rows = list(train)
    assert all(img.shape == (12, 12, 3) for img, _ in train_rows)

    ev = zoo.dataset_fn(Dataset.from_iterable(records), "evaluation", None)
    ev_rows = list(ev)
    np.testing.assert_array_equal(ev_rows[0][0], images[0][2:14, 2:14])

    small = zoo.dataset_fn(
        Dataset.from_iterable([(images[0][:8, :8], labels[0])]),
        "evaluation", None,
    )
    assert next(iter(small))[0].shape == (8, 8, 3)


def test_image_evaluate_only_from_etrf(tmp_path, monkeypatch):
    """Evaluation mode through the real pipeline.  The metric fn is
    spied on: it must see EVERY record exactly once (the full-set
    metric contract) with finite outputs of the model's class count."""
    from elasticdl_tpu.client import api
    from elasticdl_tpu.common.args import parse_master_args

    path = str(tmp_path / "val.etrf")
    images, labels = _synthetic_images(48, 24, classes=4, seed=9)
    image_plane.write_image_etrf(path, images, labels)

    seen = []

    def spying_metrics():
        def accuracy(outputs, labels_):
            outputs = np.asarray(outputs)
            assert outputs.shape[1] == 4 and np.isfinite(outputs).all()
            seen.append((outputs.shape[0], np.sort(np.asarray(labels_))))
            return float(
                np.mean(np.argmax(outputs, axis=1) == labels_)
            )

        return {"accuracy": accuracy}

    monkeypatch.setattr(zoo, "eval_metrics_fn", spying_metrics)
    args = parse_master_args([
        "--model_zoo", "model_zoo",
        "--model_def", "resnet50.resnet50_subclass",
        "--model_params", "num_classes=4",
        "--distribution_strategy", "Local",
        "--validation_data", path,
        "--records_per_task", "24",
        "--minibatch_size", "8",
    ])
    assert api._run_local(args, mode="evaluation") == 0
    # One finalized round over the WHOLE validation set, every label
    # present (order-independent: eval tasks may interleave).
    assert len(seen) == 1
    n, metric_labels = seen[0]
    assert n == 48
    np.testing.assert_array_equal(metric_labels, np.sort(labels))


def test_sharded_image_dir_reader(tmp_path):
    """A DIRECTORY of .etrf files is the reference's RecordIO-dir
    dataset layout: each file is one shard; tasks address [start, end)
    within their shard (FixedWidthEtrfReader)."""
    d = tmp_path / "shards"
    d.mkdir()
    all_images, all_labels = [], []
    for s in range(3):
        images, labels = _synthetic_images(5, 14, seed=s)
        image_plane.write_image_etrf(
            str(d / f"images-{s:05d}.etrf"), images, labels
        )
        all_images.append(images)
        all_labels.append(labels)

    reader = zoo.ImageRecordReader(str(d))
    shards = reader.create_shards()
    assert len(shards) == 3 and all(n == 5 for n in shards.values())
    assert reader.shard_names() == sorted(shards)

    class _Task:
        shard_name = sorted(shards)[1]
        start, end = 1, 4

    cols = next(iter(reader.read_columns(_Task)))
    np.testing.assert_array_equal(
        cols["image"].reshape((3, 14, 14, 3)), all_images[1][1:4]
    )
    rows = list(reader.read_records(_Task))
    assert rows[0][1] == all_labels[1][1]

    # The model's reader hook resolves a shard directory too.
    assert isinstance(
        zoo.custom_data_reader(str(d)), zoo.ImageRecordReader
    )


def test_pack_images_cli_roundtrip(tmp_path):
    """scripts/pack_images.py: class-tree -> sharded ETRF; exact-size
    PNGs round-trip losslessly through decode (resize is identity)."""
    import importlib.util
    import json
    import os

    from PIL import Image

    spec = importlib.util.spec_from_file_location(
        "pack_images",
        os.path.join(
            os.path.dirname(__file__), os.pardir, "scripts",
            "pack_images.py",
        ),
    )
    pack_images = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pack_images)

    root = tmp_path / "raw"
    rng = np.random.default_rng(7)
    originals = {}
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        for i in range(3):
            img = rng.integers(0, 256, size=(16, 16, 3)).astype(np.uint8)
            Image.fromarray(img).save(root / cls / f"{i}.png")
            originals[(cls, i)] = img

    out = tmp_path / "packed"
    n = pack_images.pack(
        str(root), str(out), size=16, records_per_shard=4
    )
    assert n == 6
    assert json.load(open(out / "labels.json")) == ["cat", "dog"]
    shard_files = sorted(p for p in os.listdir(out) if p.endswith(".etrf"))
    assert len(shard_files) == 2  # 6 records, 4/shard

    reader = zoo.ImageRecordReader(str(out))
    assert sum(reader.create_shards().values()) == 6
    # Every packed record matches one source image exactly, labels
    # consistent with the class mapping.
    matched = 0
    for shard, count in reader.create_shards().items():
        class _Task:
            shard_name = shard
            start, end = 0, count

        for image, label in reader.read_records(_Task):
            cls = ["cat", "dog"][int(label)]
            assert any(
                np.array_equal(image, originals[(cls, i)])
                for i in range(3)
            ), "packed image does not match any source of its class"
            matched += 1
    assert matched == 6
