"""Chaos suite: deterministic fault injection + a real master-outage e2e.

The e2e is the tentpole proof: SIGKILL the master mid-job while two real
workers hold in-flight tasks — the workers ride through the outage on the
RPC retry plane (no worker dies, no restart-the-world), the replacement
master (same port) resumes from the persisted shard-progress snapshot, and
the job completes with every record of every epoch processed at least
once.

The checkpoint-plane tests drive the `ckpt.write:truncate` injection
point: a torn write is detected by the CRC32 integrity manifest, the
snapshot is quarantined (with a logged reason), and restore falls back to
the previous step — it never crashes and never loads garbage.
"""

import contextlib
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import pytest

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.grpc_utils import RetryPolicy
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.master_client import MasterClient

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


@contextlib.contextmanager
def capture_logs(logger_name):
    """The framework root logger doesn't propagate (log_utils); attach a
    recording handler directly."""
    records = []
    handler = logging.Handler()
    handler.emit = records.append
    logger = logging.getLogger(logger_name)
    logger.addHandler(handler)
    try:
        yield records
    finally:
        logger.removeHandler(handler)


# ---------------------------------------------------------------------------
# Tentpole e2e: master SIGKILL mid-job, workers ride through on retries.
# ---------------------------------------------------------------------------

#: Snappy retry plane for a localhost outage measured in seconds.
CHAOS_POLICY = RetryPolicy(
    timeout_s=3.0,
    max_attempts=400,
    base_backoff_s=0.05,
    max_backoff_s=0.25,
    jitter=0.25,
    total_budget_s=120.0,
    wait_for_ready=True,
)


def _free_port() -> int:
    with socket.socket() as sock:
        sock.bind(("localhost", 0))
        return sock.getsockname()[1]


class RecordingClient(MasterClient):
    """MasterClient that records which (epoch, start, end) training ranges
    this worker COMPLETED (result report accepted by a master)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.completed = []
        self._inflight = {}

    def get_task(self, task_type=pb.TRAINING):
        task = super().get_task(task_type)
        if task.task_id >= 0 and task.type == pb.TRAINING:
            self._inflight[task.task_id] = (task.epoch, task.start, task.end)
        return task

    def report_task_result(self, task_id, err_message="", exec_counters=None,
                           trace_id=""):
        super().report_task_result(
            task_id, err_message, exec_counters, trace_id=trace_id
        )
        if not err_message and task_id in self._inflight:
            self.completed.append(self._inflight.pop(task_id))


def _start_master(ckpt_dir, port, shard_name, n_records, rpt, epochs, log_path):
    repo_root = os.path.dirname(TESTS_DIR)
    env = {**os.environ}
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo_root, env.get("PYTHONPATH", "")) if p
    )
    with open(log_path, "ab") as log_file:
        return subprocess.Popen(
            [
                sys.executable,
                os.path.join(TESTS_DIR, "chaos_master.py"),
                str(ckpt_dir), str(port), shard_name,
                str(n_records), str(rpt), str(epochs),
            ],
            stdout=log_file,
            stderr=subprocess.STDOUT,
            env=env,
        )


def test_master_sigkill_midjob_workers_ride_through(tmp_path):
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.model_utils import load_model_spec
    from elasticdl_tpu.data.reader import build_data_reader
    from elasticdl_tpu.worker.worker import Worker

    n_records, rpt, epochs = 1024, 32, 2
    port = _free_port()
    ckpt_dir = tmp_path / "ckpt"
    ckpt_dir.mkdir()
    master_log = str(tmp_path / "master.log")

    args = parse_master_args([
        "--model_zoo=model_zoo",
        "--model_def=mnist.mnist_functional_api",
        f"--training_data=synthetic://mnist?n={n_records}",
        f"--records_per_task={rpt}",
        "--minibatch_size=16",
        f"--num_epochs={epochs}",
    ])
    model_spec = load_model_spec(args)
    # The driver master serves the shard name the workers' reader expects.
    reader = build_data_reader(args, model_spec, args.training_data)
    (shard_name,) = reader.shard_names()

    proc = _start_master(
        ckpt_dir, port, shard_name, n_records, rpt, epochs, master_log
    )
    clients, workers, threads, errors = [], [], [], []
    try:
        for wid in range(2):
            client = RecordingClient(
                f"localhost:{port}", worker_id=wid, retry_policy=CHAOS_POLICY
            )
            clients.append(client)
            workers.append(Worker(
                master_client=client,
                model_spec=model_spec,
                data_reader=build_data_reader(
                    args, model_spec, args.training_data
                ),
                minibatch_size=args.minibatch_size,
                wait_sleep_s=0.1,
            ))

        def run(worker):
            try:
                worker.run()
            except Exception as exc:  # noqa: BLE001 — the assert below
                errors.append(exc)

        for wid, worker in enumerate(workers):
            thread = threading.Thread(
                target=run, args=(worker,),
                name=f"chaos-worker-{wid}", daemon=True,
            )
            thread.start()
            threads.append(thread)

        # Let real progress land — tasks completed AND a progress
        # snapshot holding some of them persisted — with both workers
        # mid-job...
        def persisted_finished_records():
            try:
                with open(ckpt_dir / "task_progress.json") as f:
                    return json.load(f).get("finished_record_count", 0)
            except (OSError, ValueError):
                return 0

        deadline = time.time() + 300
        while (
            sum(len(c.completed) for c in clients) < 5
            or persisted_finished_records() == 0
        ):
            assert time.time() < deadline, "no progress before the kill"
            assert proc.poll() is None, "master died prematurely"
            time.sleep(0.01)

        # ... then SIGKILL the master.  Hold the outage open until both
        # facts are on record: the workers actually RETRIED (an in-flight
        # RPC died with UNAVAILABLE, or a wait_for_ready poll hit its
        # deadline — a too-short outage can be absorbed by a single
        # pending RPC with zero retries), and nothing died.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        outage_deadline = time.time() + 30
        while (
            sum(c.retry_stats.retries for c in clients) == 0
            and time.time() < outage_deadline
        ):
            time.sleep(0.05)
        for thread in threads:
            assert thread.is_alive(), "a worker died during the outage"

        # Replacement master: same port, resumes the persisted snapshot.
        proc = _start_master(
            ckpt_dir, port, shard_name, n_records, rpt, epochs, master_log
        )
        for thread in threads:
            thread.join(timeout=420)
            assert not thread.is_alive(), "worker never finished after resume"
        assert not errors, f"worker(s) crashed: {errors!r}"
        assert proc.wait(timeout=120) == 0

        # The replacement really RESUMED (did not restart the epoch).
        with open(ckpt_dir / "MASTER_DONE") as f:
            done = json.load(f)
        assert done["resumed"] is True
        assert done["resumed_finished_records"] > 0

        # Workers rode through the outage on the retry plane.
        assert sum(c.retry_stats.retries for c in clients) > 0

        # The journal reconstructs the outage post-hoc: both master
        # generations appended to one timeline (events.jsonl survives the
        # SIGKILL), the resume and the training-epoch bump are on record.
        with open(ckpt_dir / "events.jsonl") as f:
            events = [json.loads(line) for line in f if line.strip()]
        assert sum(e["event"] == "master_start" for e in events) == 2
        assert any(e["event"] == "task_progress_resume" for e in events)
        assert any(e["event"] == "train_epoch_done" for e in events)

        # No lost records: every record of BOTH epochs completed at least
        # once across the two master generations (at-least-once).
        for epoch in range(epochs):
            covered = set()
            for client in clients:
                for ep, start, end in client.completed:
                    if ep == epoch:
                        covered.update(range(start, end))
            assert covered == set(range(n_records)), (
                f"gap in epoch {epoch}: "
                f"{sorted(set(range(n_records)) - covered)[:10]}..."
            )

        # Postmortem forensics: the goodput report replays the SAME
        # journal into a timeline whose phase durations cover wall-clock
        # and whose outage (the SIGKILL -> replacement gap) is attributed.
        from elasticdl_tpu.obs import report as report_mod

        summary = report_mod.summarize(
            report_mod.load_events(str(ckpt_dir / "events.jsonl"))
        )
        wall = summary["wall_s"]
        assert wall > 0
        assert abs(sum(summary["phases"].values()) - wall) <= 0.02 * wall
        assert summary["generations"] == 2
        assert summary["outages"], "master outage not attributed"
        assert summary["outage_s"] > 0
        assert 0.0 < summary["goodput_ratio"] <= 1.0
        assert summary["phases"].get("training", 0.0) > 0.0
        assert summary["ledger_summary"]["outcome"] == "job_complete"
        report_mod.render_report(summary)  # must not raise

        # And the journal — including the goodput event types — passes
        # the schema validator (the drift gate's runtime half).
        check = subprocess.run(
            [
                sys.executable,
                os.path.join(
                    os.path.dirname(TESTS_DIR), "scripts",
                    "validate_journal.py",
                ),
                str(ckpt_dir / "events.jsonl"),
            ],
            capture_output=True,
            text=True,
        )
        assert check.returncode == 0, check.stderr
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        for client in clients:
            client.close()
        if os.path.exists(master_log):
            sys.stderr.write(open(master_log).read()[-4000:])


# ---------------------------------------------------------------------------
# Preemption storm: the policy engine beats both baselines on goodput.
# ---------------------------------------------------------------------------

#: Deterministic spot-VM-style storm: at each scheduled time, every live
#: supervised worker except the lowest-id one (the "on-demand" slot) is
#: SIGKILLed.  Schedule-based fault specs (common/faults.py `@t`).
STORM_WAVES = (0.9, 2.3, 3.7, 5.1, 6.5, 7.9)
STORM_SITE = "storm.preempt"
STORM_SPEC = ",".join(f"{STORM_SITE}:crash@t{t}" for t in STORM_WAVES)
#: In-flight tasks assigned to each wave victim right before its kill —
#: the requeue/redo surface a preemption really has.
STORM_TASKS_PER_VICTIM = 2


def _drive_storm(manager, task_manager, stop_event):
    """Apply the armed storm schedule against a live LocalProcessManager:
    poll faults.due() on this thread's own monotonic timeline and turn
    each due spec into one preemption wave."""
    from elasticdl_tpu.common import faults as storm_faults

    t0 = time.monotonic()
    while not stop_event.is_set() and storm_faults.remaining_due(STORM_SITE):
        for _spec in storm_faults.due(STORM_SITE, time.monotonic() - t0):
            victims = sorted(manager.current_worker_ids())[1:]
            for wid in victims:
                for _ in range(STORM_TASKS_PER_VICTIM):
                    task_manager.get(wid)  # in-flight work dies with it
                try:
                    manager.kill_worker(wid, 9)
                except ValueError:
                    pass  # lost a race with churn; the wave moves on
        time.sleep(0.02)


def _run_storm_job(run_dir, *, max_restarts, elastic, policy_config=None,
                   n_tasks=320, task_s=0.035):
    """One full job under the deterministic preemption storm.  Returns
    (goodput_summary fields, full journal event list).

    Configurations compared by the e2e:
      fixed-size       elastic=False, big restart budget (every wave
                       pays a full same-size re-formation)
      always-rescale   elastic=True, restart budget 0 (every wave pays a
                       shrink-churn AND an immediate greedy regrow)
      policy           elastic=True + ElasticPolicyEngine (thrash parks
                       the fleet at the floor, restore + scale-up only
                       once the storm clears and the cost amortizes)
    """
    from elasticdl_tpu import obs
    from elasticdl_tpu.master.pod_manager import LocalProcessManager
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
    from elasticdl_tpu.master.task_manager import TaskManager
    from elasticdl_tpu.obs import goodput

    os.makedirs(run_dir, exist_ok=True)
    journal_path = obs.init_journal(str(run_dir))
    ledger = goodput.reset_ledger()
    faults.install(STORM_SPEC)
    sleeper = os.path.join(run_dir, "sleeper.py")
    with open(sleeper, "w") as f:
        f.write("import time\ntime.sleep(300)\n")
    manager = None
    engine = None
    storm_stop = threading.Event()
    storm_thread = None
    try:
        obs.journal().record("master_start", job_name="storm-e2e", port=0)
        ledger.transition("idle", cause="master_start")
        task_manager = TaskManager(
            training_shards={"shard": n_tasks * 8}, records_per_task=8
        )
        rendezvous = ElasticRendezvous(coordinator_port_fn=lambda host: 29321)
        if policy_config is not None:
            from elasticdl_tpu.master.policy import ElasticPolicyEngine

            engine = ElasticPolicyEngine(policy_config, ledger=ledger)
        oracle = None
        if elastic:
            oracle = (
                (lambda needed: engine.gate_scale_up(needed, needed))
                if engine is not None
                else (lambda needed: needed)
            )
        manager = LocalProcessManager(
            num_workers=3,
            worker_argv_fn=lambda wid: [sys.executable, sleeper],
            rendezvous=rendezvous,
            task_manager=task_manager,
            max_restarts=max_restarts,
            job_finished_fn=task_manager.finished,
            poll_interval_s=0.05,
            scale_up_check_fn=oracle,
        )
        if engine is not None:
            engine.bind(manager)
        manager.start()
        if engine is not None:
            engine.start()
        storm_thread = threading.Thread(
            target=_drive_storm, args=(manager, task_manager, storm_stop),
            name="storm-driver", daemon=True,
        )
        storm_thread.start()

        # The in-process trainer (worker 99 — never supervised, so churn
        # never requeues ITS tasks) works the queue at a fixed rate; the
        # supervised sleepers are the storm's preemption surface.
        from elasticdl_tpu.proto import elasticdl_pb2 as pb

        deadline = time.time() + 120
        while not task_manager.finished():
            assert time.time() < deadline, "storm job never finished"
            task = task_manager.get(99)
            if task.task_id == -1:
                if task.type == pb.WAIT:
                    time.sleep(0.01)
                    continue
                break
            time.sleep(task_s)
            task_manager.report(task.task_id, True, worker_id=99)
        assert task_manager.finished()
        storm_stop.set()
        storm_thread.join(timeout=10)
        if engine is not None:
            engine.stop()
        manager.stop()
        ledger.finish("job_complete")
        with open(journal_path) as f:
            events = [json.loads(line) for line in f if line.strip()]
        (summary,) = [
            e for e in events if e["event"] == "goodput_summary"
        ]
        return summary, events
    finally:
        storm_stop.set()
        if storm_thread is not None:
            storm_thread.join(timeout=10)
        if engine is not None:
            engine.stop()
        if manager is not None:
            manager.stop()
        faults.clear()
        obs.journal().configure(None)
        goodput.reset_ledger()


def test_preemption_storm_policy_beats_both_baselines(
    tmp_path, obs_registry_snapshot
):
    """Acceptance (ISSUE 7): under one deterministic preemption-storm
    schedule, the policy engine's end-of-job goodput_summary strictly
    beats the fixed-size AND the naive always-rescale baselines on the
    goodput ledger's own accounting, and every scale action it took has
    a matching policy_decision journal event with evidence."""
    from elasticdl_tpu.master.policy import PolicyConfig

    fixed, _fixed_events = _run_storm_job(
        str(tmp_path / "fixed"), max_restarts=30, elastic=False,
    )
    naive, naive_events = _run_storm_job(
        str(tmp_path / "naive"), max_restarts=0, elastic=True,
    )
    policy_config = PolicyConfig(
        tick_interval_s=0.1,
        amortize_horizon_s=600.0,
        min_workers=1,
        cooldown_factor=1.0,
        min_cooldown_s=1.6,
        thrash_window_s=6.0,
        thrash_rescales=2,
        thrash_overhead_frac=0.02,
        scale_down_after=2,
        hold_journal_interval_s=0.5,
    )
    policy, policy_events = _run_storm_job(
        str(tmp_path / "policy"), max_restarts=30, elastic=True,
        policy_config=policy_config,
    )

    # Both baselines paid the storm in full; the policy rode it out at
    # the floor.  Strict inequality on the ledger's own accounting is
    # the paper's claim: elasticity that pays for itself.
    assert policy["goodput_ratio"] > fixed["goodput_ratio"], (policy, fixed)
    assert policy["goodput_ratio"] > naive["goodput_ratio"], (policy, naive)
    # The policy avoided rescales instead of buying them: strictly fewer
    # than the always-rescale baseline, and less redone work than either.
    assert policy["rescales"] < naive["rescales"]
    assert policy["records_redone"] < fixed["records_redone"]
    assert policy["records_redone"] < naive["records_redone"]

    # Every scale/evict ACTION in the policy run has a matching
    # policy_decision with evidence; the baselines made none.
    decisions = [
        e for e in policy_events if e["event"] == "policy_decision"
    ]
    downs = [d for d in decisions if d["action"] == "scale_down"]
    ups = [d for d in decisions if d["action"] == "scale_up"]
    scale_events = [e for e in policy_events if e["event"] == "scale"]
    scale_up_events = [e for e in policy_events if e["event"] == "scale_up"]
    # The storm parked the fleet once, and the loop closed with an
    # approved, amortized regrow after the storm.
    assert len(scale_events) == 1 and scale_events[0]["direction"] == "down"
    assert len(downs) == len(scale_events)
    assert downs[0]["reason"] == "rescale_thrash"
    assert downs[0]["window_rescales"] >= 2
    assert len(scale_up_events) >= 1
    assert len(ups) >= len(scale_up_events)
    assert all(u["reason"] == "amortized" for u in ups)
    assert all("required_horizon_s" in u for u in ups)
    # Thrash holds were journaled while scale-ups were being denied.
    assert any(
        d["action"] == "hold" and d["reason"] == "rescale_thrash"
        for d in decisions
    )
    assert not any(
        e["event"] == "policy_decision" for e in naive_events
    )

    # The policy journal passes the schema validator (policy_decision is
    # a registered event type).
    check = subprocess.run(
        [
            sys.executable,
            os.path.join(
                os.path.dirname(TESTS_DIR), "scripts", "validate_journal.py"
            ),
            os.path.join(str(tmp_path / "policy"), "events.jsonl"),
        ],
        capture_output=True,
        text=True,
    )
    assert check.returncode == 0, check.stderr


# ---------------------------------------------------------------------------
# Event journal: a rescale is reconstructable from the JSONL timeline.
# ---------------------------------------------------------------------------


def test_journal_reconstructs_rescale(tmp_path):
    """Acceptance: a worker-death rescale leaves journal records that
    reconstruct it — the rendezvous epoch bump AND the churn requeues, in
    order — without consulting any log file."""
    from elasticdl_tpu import obs
    from elasticdl_tpu.master.rendezvous_server import ElasticRendezvous
    from elasticdl_tpu.master.task_manager import TaskManager

    journal_path = obs.init_journal(str(tmp_path))
    try:
        manager = TaskManager(
            training_shards={"shard": 256}, records_per_task=64
        )
        rendezvous = ElasticRendezvous(
            coordinator_port_fn=lambda host: 12345
        )
        rendezvous.set_worker_hosts([(0, "127.0.0.1"), (1, "127.0.0.1")])
        task0 = manager.get(0)
        task1 = manager.get(1)
        assert task0.task_id >= 0 and task1.task_id >= 0
        # Worker 1 dies: its in-flight task requeues and the world
        # re-forms one smaller under a fresh rendezvous id.
        manager.recover_tasks(1)
        rendezvous.set_worker_hosts([(0, "127.0.0.1")])

        with open(journal_path) as f:
            events = [json.loads(line) for line in f if line.strip()]
        declarations = [
            (i, e) for i, e in enumerate(events) if e["event"] == "rendezvous"
        ]
        assert [e["rendezvous_id"] for _, e in declarations] == [1, 2]
        assert [e["world_size"] for _, e in declarations] == [2, 1]
        requeues = [
            (i, e) for i, e in enumerate(events) if e["event"] == "task_requeue"
        ]
        assert len(requeues) == 1
        index, requeue = requeues[0]
        assert requeue["reason"] == "worker_churn"
        assert requeue["worker_id"] == 1
        assert requeue["task_ids"] == [task1.task_id]
        # Order on the timeline: world declared, worker died (requeue),
        # shrunk world declared.
        assert declarations[0][0] < index < declarations[1][0]
    finally:
        obs.journal().configure(None)


# ---------------------------------------------------------------------------
# Checkpoint plane: torn writes are quarantined, restore falls back.
# ---------------------------------------------------------------------------


def test_torn_checkpoint_write_quarantined_and_falls_back(tmp_path):
    from elasticdl_tpu.checkpoint.saver import CheckpointSaver

    saver = CheckpointSaver(str(tmp_path), keep_max=5)
    saver.save({"w": [1, 2, 3], "step": 1}, step=1)
    faults.install("ckpt.write:truncate@1")  # tear the NEXT save
    saver.save({"w": [4, 5, 6], "step": 2}, step=2)
    faults.clear()

    with capture_logs("elasticdl_tpu.checkpoint.saver") as records:
        state, step = saver.load_latest()
    # Fell back exactly one step; the torn snapshot never loaded.
    assert step == 1
    assert state == {"w": [1, 2, 3], "step": 1}
    quarantined = [
        n for n in os.listdir(tmp_path) if n.endswith(".quarantined")
    ]
    assert quarantined == ["step_000000000002.quarantined"]
    messages = [r.getMessage() for r in records]
    assert any("Quarantin" in m and "falling back" in m for m in messages)
    # The quarantined snapshot is invisible to future restores/GC.
    assert saver.steps() == [1]
    # And a fresh save at the same step works (the dir name is free).
    saver.save({"w": [7], "step": 2}, step=2)
    state, step = saver.load_latest()
    assert (step, state) == (2, {"w": [7], "step": 2})


def test_sharded_torn_write_falls_back_one_step(tmp_path):
    from elasticdl_tpu.checkpoint.sharded import ShardedCheckpointSaver

    saver = ShardedCheckpointSaver(str(tmp_path), keep_max=5)
    saver.save(1, {"dense": [1.0]}, sharded={})
    faults.install("ckpt.write:truncate@1")
    saver.save(2, {"dense": [2.0]}, sharded={})
    faults.clear()

    with capture_logs("elasticdl_tpu.checkpoint.saver") as records:
        assert saver.latest_step() == 1
    assert saver.load_dense(1) == {"dense": [1.0]}
    assert any(
        "Quarantin" in r.getMessage() for r in records
    )
    assert any(
        n.endswith(".quarantined") for n in os.listdir(tmp_path)
    )


def test_unreadable_and_empty_step_dirs_are_skipped(tmp_path):
    """Satellite: steps()/restore skip junk step dirs with a warning
    instead of raising mid-listing."""
    from elasticdl_tpu.checkpoint.saver import CheckpointSaver

    saver = CheckpointSaver(str(tmp_path), keep_max=5)
    saver.save({"ok": True}, step=3)
    os.makedirs(tmp_path / "step_000000000009")  # empty: no state file
    (tmp_path / "step_000000000010").mkdir()
    (tmp_path / "step_000000000010" / "state.pkl").write_bytes(b"")  # empty
    (tmp_path / "step_notanumber").mkdir()

    with capture_logs("elasticdl_tpu.checkpoint.saver") as records:
        assert saver.steps() == [3]
    assert sum(
        "incomplete/unreadable" in r.getMessage() for r in records
    ) == 2
    state, step = saver.load_latest()
    assert (step, state) == (3, {"ok": True})


def test_crashed_save_tmp_dir_swept_at_startup(tmp_path):
    """Satellite: stale .tmp dirs from crashed saves are garbage-collected
    by the startup sweep; fresh ones (a live peer's save) are kept."""
    from elasticdl_tpu.checkpoint.saver import CheckpointSaver

    stale = tmp_path / "step_000000000004.tmpabc"
    stale.mkdir()
    (stale / "state.pkl").write_bytes(b"partial")
    old = time.time() - 7200
    os.utime(stale, (old, old))
    fresh = tmp_path / "step_000000000005.tmpdef"
    fresh.mkdir()

    saver = CheckpointSaver(str(tmp_path), keep_max=5)
    assert not stale.exists(), "stale crashed-save tmp dir not swept"
    assert fresh.exists(), "in-flight peer save must not be swept"
    assert saver.steps() == []
