"""Kubernetes substrate tests: client, pod manager, submission.

The fake API server (tests/fake_k8s.py) speaks the real wire protocol, so
these tests exercise K8sClient's HTTP/watch code and the pod manager's full
churn -> recover -> re-form sequence — the same lifecycle
tests/test_allreduce_e2e.py proves with real subprocesses.
"""

import os
import textwrap
import time

import pytest

from elasticdl_tpu.master.k8s_client import (
    K8sClient,
    K8sConfig,
    job_label_selector,
    pod_exit_code,
    pod_name,
    pod_phase,
    render_pod,
)
from elasticdl_tpu.master.k8s_pod_manager import (
    PREEMPTED_EXIT_CODE,
    KubernetesPodManager,
)

from fake_k8s import FakeK8sApiServer


@pytest.fixture()
def fake_k8s():
    server = FakeK8sApiServer().start()
    yield server
    server.stop()


@pytest.fixture()
def client(fake_k8s):
    return K8sClient(K8sConfig(host=fake_k8s.host, namespace="testns"))


def _wait_for(predicate, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return
        time.sleep(0.05)
    raise AssertionError(f"Timed out waiting for {msg}")


class RecordingTaskManager:
    def __init__(self):
        self.recovered = []
        self._finished = False

    def recover_tasks(self, worker_id):
        self.recovered.append(worker_id)

    def finished(self):
        return self._finished


def _manager(client, fake_k8s, n=2, **kwargs):
    tm = RecordingTaskManager()
    kwargs.setdefault("poll_interval_s", 0.05)
    kwargs.setdefault("pod_startup_timeout_s", 0)
    manager = KubernetesPodManager(
        num_workers=n,
        worker_argv_fn=lambda wid: ["python", "-m", "worker", str(wid)],
        k8s_client=client,
        job_name="testjob",
        image="elasticdl:test",
        task_manager=tm,
        job_finished_fn=tm.finished,
        **kwargs,
    )
    return manager, tm


# ----------------------------------------------------------------------
# K8sClient against the fake API server
# ----------------------------------------------------------------------


def test_client_pod_crud(client):
    manifest = render_pod(
        job_name="crud", replica_type="worker", index=0,
        image="img", command=["run"], namespace="testns",
        resources={"cpu": "2"},
    )
    created = client.create_pod(manifest)
    assert created["metadata"]["name"] == "elasticdl-crud-worker-0"
    assert pod_phase(created) in ("Pending", "Running")

    got = client.get_pod("elasticdl-crud-worker-0")
    assert got is not None
    assert got["spec"]["containers"][0]["resources"]["requests"] == {"cpu": "2"}

    assert client.get_pod("nope") is None

    pods = client.list_pods(job_label_selector("crud"))
    assert [p["metadata"]["name"] for p in pods] == ["elasticdl-crud-worker-0"]
    assert client.list_pods(job_label_selector("otherjob")) == []

    assert client.delete_pod("elasticdl-crud-worker-0")
    assert not client.delete_pod("elasticdl-crud-worker-0")


def test_client_watch_stream(client, fake_k8s):
    manifest = render_pod(
        job_name="w", replica_type="worker", index=0,
        image="img", command=["run"], namespace="testns",
    )
    client.create_pod(manifest)
    name = manifest["metadata"]["name"]
    events = []
    for etype, pod in client.watch_pods(
        job_label_selector("w"), timeout_s=5.0
    ):
        events.append((etype, pod_phase(pod)))
        if etype == "ADDED":
            fake_k8s.fail_pod(name, exit_code=3)
        if etype == "MODIFIED":
            assert pod_exit_code(pod) == 3
            fake_k8s.delete_pod(name)
        if etype == "DELETED":
            break
    assert [e[0] for e in events] == ["ADDED", "MODIFIED", "DELETED"]


def test_kubeconfig_parsing(tmp_path):
    ca = tmp_path / "ca.pem"
    ca.write_text("CERT")
    cfg = tmp_path / "config"
    cfg.write_text(
        textwrap.dedent(
            f"""
            apiVersion: v1
            current-context: dev
            clusters:
            - name: devcluster
              cluster:
                server: https://10.1.2.3:6443
                certificate-authority: {ca}
            users:
            - name: devuser
              user:
                token: sekrit
            contexts:
            - name: dev
              context:
                cluster: devcluster
                user: devuser
                namespace: ml
            """
        )
    )
    config = K8sConfig.from_kubeconfig(str(cfg))
    assert config.host == "https://10.1.2.3:6443"
    assert config.token == "sekrit"
    assert config.ca_file == str(ca)
    assert config.namespace == "ml"


# ----------------------------------------------------------------------
# KubernetesPodManager lifecycle
# ----------------------------------------------------------------------


def test_pod_manager_clean_completion(client, fake_k8s):
    manager, _ = _manager(client, fake_k8s, n=2)
    manager.start()
    try:
        _wait_for(
            lambda: len(fake_k8s.pod_names()) == 2, msg="2 worker pods"
        )
        assert fake_k8s.pod_names() == [
            pod_name("testjob", "worker", 0),
            pod_name("testjob", "worker", 1),
        ]
        fake_k8s.succeed_all()
        assert manager.wait(timeout=10)
    finally:
        manager.stop()


def test_pod_manager_churn_reform_recover(client, fake_k8s):
    """A pod failure re-forms the world: tasks of BOTH workers recovered,
    the survivor deleted, a fresh world launched with new worker ids —
    the same sequence the subprocess e2e proves."""
    manager, tm = _manager(client, fake_k8s, n=2)
    manager.start()
    try:
        _wait_for(lambda: len(fake_k8s.pod_names()) == 2, msg="world 1")
        fake_k8s.fail_pod(pod_name("testjob", "worker", 0), exit_code=1)
        _wait_for(
            lambda: sorted(manager.current_worker_ids()) == [2, 3],
            msg="world 2 with fresh ids",
        )
        # Both members of the dead world had their tasks recovered.
        assert sorted(tm.recovered) == [0, 1]
        # The survivor was deleted with the world.
        assert pod_name("testjob", "worker", 1) not in fake_k8s.pod_names()
        assert fake_k8s.create_log.count(pod_name("testjob", "worker", 2)) == 1
        fake_k8s.succeed_all()
        assert manager.wait(timeout=10)
    finally:
        manager.stop()


def test_pod_manager_preemption_via_delete(client, fake_k8s):
    """A pod deleted out from under us (node preemption / kubectl delete)
    reads as churn with exit 137, not as clean completion."""
    manager, tm = _manager(client, fake_k8s, n=2)
    manager.start()
    try:
        _wait_for(lambda: len(fake_k8s.pod_names()) == 2, msg="world 1")
        fake_k8s.delete_pod(pod_name("testjob", "worker", 1))
        _wait_for(
            lambda: sorted(manager.current_worker_ids()) == [2, 3],
            msg="world re-formed after preemption",
        )
        assert sorted(tm.recovered) == [0, 1]
        fake_k8s.succeed_all()
        assert manager.wait(timeout=10)
    finally:
        manager.stop()


def test_pod_manager_kill_worker(client, fake_k8s):
    """kill_worker (fault injection) deletes the pod and the death counts
    as churn — the manager's own teardowns don't."""
    manager, tm = _manager(client, fake_k8s, n=2)
    manager.start()
    try:
        _wait_for(lambda: len(fake_k8s.pod_names()) == 2, msg="world 1")
        manager.kill_worker(0)
        _wait_for(
            lambda: sorted(manager.current_worker_ids()) == [2, 3],
            msg="world re-formed after kill",
        )
        fake_k8s.succeed_all()
        assert manager.wait(timeout=10)
    finally:
        manager.stop()


def test_pod_manager_budget_shrinks_world(client, fake_k8s):
    manager, _ = _manager(client, fake_k8s, n=2, max_restarts=0)
    manager.start()
    try:
        _wait_for(lambda: len(fake_k8s.pod_names()) == 2, msg="world 1")
        fake_k8s.fail_pod(pod_name("testjob", "worker", 0))
        _wait_for(
            lambda: manager.current_worker_ids() == [2],
            msg="world shrunk to 1",
        )
        fake_k8s.succeed_all()
        assert manager.wait(timeout=10)
    finally:
        manager.stop()


def test_pod_manager_scale_up_when_capacity_returns(client, fake_k8s):
    """Elastic rejoin, two-phase: budget-exhausted churn shrinks 2 -> 1;
    when the oracle grants a slot, a probe pod schedules (world untouched),
    goes Running (capacity proven), and only then does the world re-form
    at size 2."""
    capacity = {"slots": 0}
    manager, tm = _manager(
        client,
        fake_k8s,
        n=2,
        max_restarts=0,
        scale_up_check_fn=lambda needed: min(needed, capacity["slots"]),
    )
    manager.start()
    try:
        _wait_for(lambda: len(fake_k8s.pod_names()) == 2, msg="world 1")
        fake_k8s.fail_pod(pod_name("testjob", "worker", 0))
        _wait_for(
            lambda: manager.current_worker_ids() == [2], msg="shrunk world"
        )
        capacity["slots"] = 1
        # Probe pod (id 3) schedules and runs -> commit re-forms at ids 4,5.
        _wait_for(
            lambda: sorted(manager.current_worker_ids()) == [4, 5],
            msg="world grown back to 2",
        )
        # The shrunk world's tasks were recovered before regrowth, and the
        # probe pod did not survive into the new world.
        assert 2 in tm.recovered
        assert pod_name("testjob", "worker", 3) not in fake_k8s.pod_names()
        fake_k8s.succeed_all()
        assert manager.wait(timeout=10)
    finally:
        manager.stop()


def test_pod_manager_scale_up_probe_backs_off_without_capacity(
    client, fake_k8s
):
    """A capacity-starved cluster: the probe pod sits Pending, the probe
    aborts after the startup timeout, the healthy world is NEVER torn
    down, no restart budget is burned, and the oracle backs off."""
    calls = {"failed": 0}

    class Oracle:
        granted = False

        def __call__(self, needed):
            return needed if self.granted else 0

        def failed(self):
            calls["failed"] += 1

        def succeeded(self):
            pass

    oracle = Oracle()
    manager, _ = _manager(
        client,
        fake_k8s,
        n=2,
        max_restarts=0,
        target_num_workers=3,
        scale_up_check_fn=oracle,
        pod_startup_timeout_s=0.3,
    )
    manager.start()
    try:
        _wait_for(lambda: len(fake_k8s.pod_names()) == 2, msg="world 1")
        fake_k8s.schedulable = False  # probe pods will stay Pending
        oracle.granted = True
        _wait_for(lambda: calls["failed"] >= 1, msg="probe abort + backoff")
        # Healthy world untouched; probe pod cleaned up.
        assert sorted(manager.current_worker_ids()) == [0, 1]
        assert pod_name("testjob", "worker", 2) not in fake_k8s.pod_names()
        fake_k8s.succeed_all()
        assert manager.wait(timeout=10)
    finally:
        manager.stop()


def test_pod_manager_resync_marks_vanished_pods(client, fake_k8s):
    """_resync after a watch outage marks cached pods missing from the
    re-list as deleted, so their churn still surfaces."""
    manager, _ = _manager(client, fake_k8s, n=1)
    handles = manager._substrate_launch([0])
    manager._handles = handles  # as _launch_world would
    name = handles[0].name
    manager._resync()
    assert manager._substrate_poll(handles[0]) is None
    # Pod vanishes while the watch is down (no watcher running here).
    fake_k8s.delete_pod(name)
    manager._resync()
    assert manager._substrate_poll(handles[0]) == PREEMPTED_EXIT_CODE
    assert manager._resource_version  # list RV captured for watch resume


def test_pod_manager_pending_timeout_is_churn(client, fake_k8s):
    """Unschedulable pods (capacity starvation) convert to churn via the
    startup timeout instead of wedging the job forever."""
    fake_k8s.schedulable = False
    manager, _ = _manager(
        client, fake_k8s, n=1, max_restarts=0, pod_startup_timeout_s=0.3
    )
    manager.start()
    try:
        assert not manager.wait(timeout=15)
        assert "restart budget exhausted" in manager.failed_reason
    finally:
        manager.stop()


@pytest.mark.parametrize(
    "event_log_cap",
    [
        pytest.param(0, id="streams-drop-resume-from-rv"),
        pytest.param(1, id="resume-gets-410-re-list"),
    ],
)
def test_pod_manager_survives_watch_stream_chaos(event_log_cap):
    """Every watch connection dies after ONE event: the manager must keep
    reconnecting and still see churn, re-form, and finish — the lifecycle
    must never depend on a long-lived stream.  With the server's event
    log capped at 1, most resumes additionally get 410 Gone, forcing the
    WatchExpired -> full re-list recovery path every time."""
    server = FakeK8sApiServer(watch_max_events=1).start()
    if event_log_cap:
        server.event_log_cap = event_log_cap
    try:
        chaos_client = K8sClient(
            K8sConfig(host=server.host, namespace="testns")
        )
        manager, tm = _manager(chaos_client, server, n=2)
        manager.start()
        try:
            _wait_for(lambda: len(server.pod_names()) == 2, msg="world 1")
            server.fail_pod(pod_name("testjob", "worker", 0))
            _wait_for(
                lambda: sorted(manager.current_worker_ids()) == [2, 3],
                msg="re-formed world despite dropping watches",
                timeout=30,
            )
            assert sorted(tm.recovered) == [0, 1]
            server.succeed_all()
            assert manager.wait(timeout=15)
        finally:
            manager.stop()
    finally:
        server.stop()


def test_pod_manager_sweeps_leftover_pods(client, fake_k8s):
    """A new master incarnation deletes its predecessor's worker pods
    before world 1 — pod names would otherwise collide and 409s would be
    misread as churn (master-restart resume on k8s depends on this)."""
    stale = render_pod(
        job_name="testjob", replica_type="worker", index=0,
        image="old", command=["run"], namespace="testns",
    )
    client.create_pod(stale)
    manager, _ = _manager(client, fake_k8s, n=2)
    manager.start()
    try:
        _wait_for(
            lambda: sorted(manager.current_worker_ids()) == [0, 1],
            msg="fresh world despite name collision",
        )
        pod = client.get_pod(pod_name("testjob", "worker", 0))
        assert pod["spec"]["containers"][0]["image"] == "elasticdl:test"
        fake_k8s.succeed_all()
        assert manager.wait(timeout=10)
    finally:
        manager.stop()


def test_parse_volume_spec():
    from elasticdl_tpu.master.k8s_client import parse_volume_spec

    volumes, mounts = parse_volume_spec(
        "claim_name=ckpt-pvc,mount_path=/ckpt;"
        "host_path=/mnt/nfs,mount_path=/data,read_only=true"
    )
    assert volumes[0]["persistentVolumeClaim"]["claimName"] == "ckpt-pvc"
    assert mounts[0]["mountPath"] == "/ckpt"
    assert volumes[1]["hostPath"]["path"] == "/mnt/nfs"
    assert mounts[1]["readOnly"] is True
    assert volumes[0]["name"] == mounts[0]["name"]
    with pytest.raises(ValueError):
        parse_volume_spec("claim_name=x")  # no mount_path
    with pytest.raises(ValueError):
        parse_volume_spec("mount_path=/x")  # no source


# ----------------------------------------------------------------------
# Submission
# ----------------------------------------------------------------------


def test_submit_job_creates_master_pod(client, fake_k8s):
    from elasticdl_tpu.client.submit import submit_job
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.constants import Mode

    argv = [
        "--job_name=subjob",
        "--image_name=elasticdl:test",
        "--namespace=testns",
        "--model_zoo=/zoo",
        "--model_def=mnist.custom_model",
        "--training_data=/data/train",
        "--num_workers=3",
        "--master_resource_request=cpu=1,memory=2Gi",
        "--distribution_strategy=AllreduceStrategy",
        "--volume=claim_name=ckpt-pvc,mount_path=/ckpt",
        "--checkpoint_dir=/ckpt/subjob",
    ]
    args = parse_master_args(argv)
    assert submit_job(args, Mode.TRAINING, k8s_client=client) == 0
    pods = fake_k8s.pod_names()
    assert pods == ["elasticdl-subjob-master-0"]
    pod = client.get_pod("elasticdl-subjob-master-0")
    command = pod["spec"]["containers"][0]["command"]
    assert command[:3] == ["python", "-m", "elasticdl_tpu.master.main"]
    assert "--job_type=training_only" in command
    # Flags round-trip so the master pod can re-render worker pods.
    joined = " ".join(command)
    assert "--num_workers 3" in joined
    assert "--image_name=elasticdl:test" in joined
    assert pod["spec"]["containers"][0]["resources"]["requests"] == {
        "cpu": "1",
        "memory": "2Gi",
    }
    labels = pod["metadata"]["labels"]
    assert labels["elasticdl-job-name"] == "subjob"
    assert labels["elasticdl-replica-type"] == "master"
    # The shared checkpoint volume is mounted into the master pod.
    assert pod["spec"]["volumes"][0]["persistentVolumeClaim"][
        "claimName"
    ] == "ckpt-pvc"


def test_submit_rejects_elastic_job_without_shared_checkpoint(client):
    """Pre-flight: a config that would kill the master pod on arrival
    (elastic training, no shared checkpoint_dir) fails in the client's
    terminal, before anything is created in the cluster."""
    from elasticdl_tpu.client.submit import submit_job
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.constants import Mode

    args = parse_master_args(
        [
            "--job_name=badjob",
            "--image_name=elasticdl:test",
            "--model_zoo=/zoo",
            "--model_def=m.f",
            "--training_data=/data",
            "--distribution_strategy=AllreduceStrategy",
        ]
    )
    with pytest.raises(ValueError, match="checkpoint_dir"):
        submit_job(args, Mode.TRAINING, k8s_client=client)
    assert client.list_pods() == []


def test_tpu_slice_worker_pods_rendered(client, fake_k8s):
    """--tpu_slice=v5e-16 (round-5 VERDICT #7): one worker pod per TPU VM
    host — 4 pods, each requesting the host's 4 chips via google.com/tpu
    and pinned to the slice's accelerator/topology node labels, with the
    MY_POD_IP coordinator plumbing intact."""
    manager, _ = _manager(client, fake_k8s, n=4, tpu_slice="v5e-16")
    manager._substrate_launch([0, 1, 2, 3])
    pods = client.list_pods(job_label_selector("testjob", "worker"))
    assert len(pods) == 4
    for pod in pods:
        res = pod["spec"]["containers"][0]["resources"]
        assert res["requests"]["google.com/tpu"] == "4"
        assert res["limits"]["google.com/tpu"] == "4"
        sel = pod["spec"]["nodeSelector"]
        assert sel["cloud.google.com/gke-tpu-accelerator"] == (
            "tpu-v5-lite-podslice"
        )
        assert sel["cloud.google.com/gke-tpu-topology"] == "4x4"
        env_names = {
            e["name"] for e in pod["spec"]["containers"][0]["env"]
        }
        # Workers advertise their pod IP to the master rendezvous; the
        # jax.distributed coordinator address resolves from it.
        assert "MY_POD_IP" in env_names


def test_tpu_slice_explicit_resources_merge(client, fake_k8s):
    """--worker_resource_request composes with the slice overlay (cpu and
    memory requests ride alongside the chip request)."""
    manager, _ = _manager(
        client, fake_k8s, n=2, tpu_slice="v5e-8",
        worker_resources={"memory": "100Gi"},
    )
    manager._substrate_launch([0])
    (pod,) = client.list_pods(job_label_selector("testjob", "worker"))
    requests = pod["spec"]["containers"][0]["resources"]["requests"]
    assert requests == {"memory": "100Gi", "google.com/tpu": "4"}
    assert pod["spec"]["nodeSelector"][
        "cloud.google.com/gke-tpu-topology"
    ] == "2x4"


def test_tpu_slice_validation():
    """Wrong worker count or unknown shape fails loudly — at manager
    construction in-cluster and at submit time client-side."""
    from elasticdl_tpu.client.submit import validate_cluster_args
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.constants import Mode
    from elasticdl_tpu.master.tpu_slice import slice_spec

    with pytest.raises(ValueError, match="4 host"):
        from elasticdl_tpu.master.tpu_slice import validate_worker_count

        validate_worker_count(slice_spec("v5e-16"), 3)
    with pytest.raises(ValueError, match="known shapes"):
        slice_spec("v9z-1")

    args = parse_master_args(
        [
            "--job_name=tpujob",
            "--image_name=elasticdl:test",
            "--model_zoo=/zoo",
            "--model_def=m.f",
            "--training_data=/data",
            "--num_workers=3",
            "--tpu_slice=v5e-16",
        ]
    )
    with pytest.raises(ValueError, match="num_workers"):
        validate_cluster_args(args, Mode.TRAINING)
