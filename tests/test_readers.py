"""Data reader tests (parity: data_reader_test.py in the reference)."""

import numpy as np

from elasticdl_tpu.data import recordfile
from elasticdl_tpu.data import reader as reader_mod
from elasticdl_tpu.data.reader import (
    CSVDataReader,
    NumpyDataReader,
    RecordIODataReader,
    TextLineDataReader,
    create_data_reader,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb


def make_task(shard_name, start, end):
    return pb.Task(task_id=1, shard_name=shard_name, start=start, end=end)


class TestNumpyReader:
    def test_shards_and_records(self):
        features = np.arange(20).reshape(10, 2)
        labels = np.arange(10)
        reader = NumpyDataReader(features, labels, shard_name="mem")
        assert reader.create_shards() == {"mem": 10}
        records = list(reader.read_records(make_task("mem", 3, 6)))
        assert len(records) == 3
        np.testing.assert_array_equal(records[0][0], [6, 7])
        assert records[0][1] == 3


class TestCSVReader:
    def test_shards_and_range(self, tmp_path):
        for name, rows in (("a.csv", 5), ("b.csv", 3)):
            with open(tmp_path / name, "w") as f:
                f.write("x,y\n")
                for i in range(rows):
                    f.write(f"{i},{i * 2}\n")
        reader = CSVDataReader(data_dir=str(tmp_path))
        shards = reader.create_shards()
        assert shards == {str(tmp_path / "a.csv"): 5, str(tmp_path / "b.csv"): 3}
        assert reader.metadata.column_names == ["x", "y"]
        rows = list(reader.read_records(make_task(str(tmp_path / "a.csv"), 2, 4)))
        assert rows == [["2", "4"], ["3", "6"]]


class TestTextLineReader:
    def test_range(self, tmp_path):
        path = tmp_path / "lines.txt"
        path.write_text("".join(f"line{i}\n" for i in range(10)))
        reader = TextLineDataReader(data_dir=str(path))
        assert reader.create_shards() == {str(path): 10}
        assert list(reader.read_records(make_task(str(path), 8, 12))) == [
            "line8",
            "line9",
        ]


class TestRecordIOReader:
    def test_shards_and_range(self, tmp_path):
        path = str(tmp_path / "part-0.rio")
        recordfile.write_records(path, [f"r{i}".encode() for i in range(25)])
        reader = RecordIODataReader(data_dir=str(tmp_path))
        assert reader.create_shards() == {path: 25}
        got = list(reader.read_records(make_task(path, 20, 25)))
        assert got[0] == b"r20" and got[-1] == b"r24"


class TestFactory:
    def test_infer_csv(self, tmp_path):
        (tmp_path / "data.csv").write_text("x\n1\n")
        reader = create_data_reader(str(tmp_path))
        assert isinstance(reader, CSVDataReader)

    def test_infer_recordio(self, tmp_path):
        recordfile.write_records(str(tmp_path / "d.rio"), [b"x"])
        reader = create_data_reader(str(tmp_path))
        assert isinstance(reader, RecordIODataReader)

    def test_explicit_prefix(self, tmp_path):
        reader = create_data_reader(f"textline:{tmp_path}")
        assert isinstance(reader, TextLineDataReader)


class TestCSVQuotedNewlines:
    def test_shard_count_matches_parsed_rows(self, tmp_path):
        """Quoted fields containing newlines are one record, not two:
        create_shards must agree with what read_records yields."""
        path = tmp_path / "q.csv"
        path.write_text('x,y\na,"multi\nline"\nb,c\n')
        reader = CSVDataReader(data_dir=str(tmp_path))
        shards = reader.create_shards()
        assert shards == {str(path): 2}
        rows = list(reader.read_records(make_task(str(path), 0, 2)))
        assert rows == [["a", "multi\nline"], ["b", "c"]]


class TestStridedOffsetIndex:
    """Round-1 weak #6: CSV/text readers re-scanned from byte 0 for every
    task (O(n^2) per epoch).  The strided offset index built during the
    counting pass makes task reads seek near the target record."""

    def _task(self, shard, start, end):
        from elasticdl_tpu.proto import elasticdl_pb2 as pb

        return pb.Task(task_id=1, shard_name=shard, start=start, end=end)

    def test_csv_mid_file_task_seeks(self, tmp_path):
        path = tmp_path / "big.csv"
        with open(path, "w") as f:
            f.write("id,value\n")
            for i in range(1000):
                f.write(f"{i},v{i}\n")
        reader = CSVDataReader(data_dir=str(path))
        shards = reader.create_shards()
        assert shards[str(path)] == 1000
        rows = list(reader.read_records(self._task(str(path), 900, 910)))
        assert rows == [[str(i), f"v{i}"] for i in range(900, 910)]
        # The read started from a strided offset, not byte 0: it consumed
        # at most STRIDE + range records, far fewer than 900.
        consumed = []

        class Probe(reader_mod._ByteLines):
            def __next__(probe_self):
                line = super(Probe, probe_self).__next__()
                consumed.append(line)
                return line

        original = reader_mod._ByteLines
        reader_mod._ByteLines = Probe
        try:
            list(reader.read_records(self._task(str(path), 900, 910)))
        finally:
            reader_mod._ByteLines = original
        assert len(consumed) <= reader_mod._StridedOffsetIndex.STRIDE + 10

    def test_csv_quoted_newlines_survive_sharded_reads(self, tmp_path):
        path = tmp_path / "quoted.csv"
        with open(path, "w", newline="") as f:
            for i in range(200):
                f.write(f'{i},"line one\nline two {i}"\r\n')
        reader = CSVDataReader(data_dir=str(path), with_header=False)
        shards = reader.create_shards()
        assert shards[str(path)] == 200  # parsed rows, not raw lines
        rows = list(reader.read_records(self._task(str(path), 130, 133)))
        assert rows == [
            [str(i), f"line one\nline two {i}"] for i in range(130, 133)
        ]

    def test_textline_mid_file_task(self, tmp_path):
        path = tmp_path / "lines.txt"
        path.write_text("".join(f"line-{i}\n" for i in range(500)))
        reader = TextLineDataReader(data_dir=str(path))
        assert reader.create_shards()[str(path)] == 500
        got = list(reader.read_records(self._task(str(path), 450, 455)))
        assert got == [f"line-{i}" for i in range(450, 455)]

    def test_index_invalidates_on_file_change(self, tmp_path):
        path = tmp_path / "data.txt"
        path.write_text("a\nb\nc\n")
        reader = TextLineDataReader(data_dir=str(path))
        reader.create_shards()
        # File replaced with different content: the index must not serve
        # stale offsets.
        import time as _time

        _time.sleep(0.01)
        path.write_text("".join(f"x{i}\n" for i in range(100)))
        got = list(reader.read_records(self._task(str(path), 64, 66)))
        assert got == ["x64", "x65"]
