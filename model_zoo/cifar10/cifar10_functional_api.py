"""CIFAR-10 ResNet-20 — model-zoo contract, JAX/flax body.

Parity: model_zoo/cifar10_functional_api.py in the reference (a Keras
functional-API ResNet-20-style CNN for CIFAR-10; BASELINE config 2).  Same
contract functions, TPU-first body: 3x3 convs lower onto the MXU, batch
norm state rides the TrainState's mutable collections, bfloat16 compute
with float32 params/accumulators (the standard TPU mixed-precision recipe).
"""

from __future__ import annotations

from functools import partial
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from model_zoo import datasets

Dtype = Any


class ResidualBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            dtype=jnp.float32,
        )
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3))(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(
                self.filters, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet20(nn.Module):
    """Classic 6n+2 CIFAR ResNet with n=3 (16/32/64 filters)."""

    num_classes: int = 10
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(16, (3, 3), use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         dtype=jnp.float32)(x)
        x = nn.relu(x)
        for filters, strides in ((16, 1), (32, 2), (64, 2)):
            for block_index in range(3):
                x = ResidualBlock(
                    filters, strides if block_index == 0 else 1, self.dtype
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def custom_model(num_classes: int = 10, use_bf16: bool = True):
    return ResNet20(
        num_classes=num_classes,
        dtype=jnp.bfloat16 if use_bf16 else jnp.float32,
    )


def loss(labels, predictions):
    return optax.softmax_cross_entropy_with_integer_labels(
        predictions.astype(jnp.float32), labels.astype(jnp.int32)
    ).mean()


def optimizer(lr: float = 0.1):
    return optax.sgd(lr, momentum=0.9, nesterov=True)


def dataset_fn(dataset, mode, metadata):
    def parse(record):
        image, label = record
        image = np.asarray(image, np.float32) / 255.0
        # Per-channel CIFAR-10 normalization constants.
        image = (image - np.asarray([0.4914, 0.4822, 0.4465], np.float32)) / (
            np.asarray([0.247, 0.243, 0.261], np.float32)
        )
        return image, np.int32(label)

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(2048, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda outputs, labels: np.mean(
            np.argmax(outputs, axis=1) == labels.astype(np.int64)
        ),
        "loss": lambda outputs, labels: float(
            loss(jnp.asarray(labels), jnp.asarray(outputs))
        ),
    }


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name is None:
        return None
    return datasets.synthetic_cifar10_reader(
        n=params.get("n", 4096), seed=params.get("seed", 0)
    )
