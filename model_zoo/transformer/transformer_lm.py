"""Transformer causal LM — the long-context / context-parallel config.

Net-new scope beyond the reference (SURVEY.md §5: the reference predates
long-context training and has none; this framework treats it as
first-class).  A pre-LN decoder-only transformer whose attention runs:

- single-device: `blockwise_attention` (flash numerics; KV processed in
  chunks so score slabs are [T, kv_chunk], never the full [T, T]), or
- context-parallel: `ring_attention` under shard_map — the sequence dim
  shards over the mesh's `model` axis, K/V blocks rotate over ICI
  (parallel/ring_attention.py) — when built with `custom_model(mesh=...)`
  and the mesh's model axis is > 1.

Everything else is ordinary flax the DataParallelTrainer already handles:
params replicated (f32), bf16 compute, batch sharded over `data`, XLA
psums the grads.  Model-zoo contract functions at the bottom; synthetic
`synthetic://lm?n=N&len=T&vocab=V` data (model_zoo/datasets.py) makes
next-token loss genuinely learnable in tests.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from elasticdl_tpu.parallel.ring_attention import (
    blockwise_attention,
    make_ring_attention,
)
from model_zoo import datasets

VOCAB = 256
SEQ_LEN = 128


def _tp_active(mesh, model_axis_mode: str) -> bool:
    return (
        model_axis_mode == "tp"
        and mesh is not None
        and mesh.shape.get(MODEL_AXIS, 1) > 1
    )


def _constrain(mesh, x, *spec):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(*spec))
    )


class CausalSelfAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    mesh: Any = None  # jax.sharding.Mesh -> ring attention over `model`
    # "auto": the Pallas flash kernel on TPU when the shape qualifies,
    # XLA blockwise otherwise.  "pallas"/"xla" force one implementation.
    attn_impl: str = "auto"
    # Context-parallel sequence layout: "contiguous" or "zigzag" (the
    # balanced causal ring; see parallel/ring_attention.py).
    cp_layout: str = "contiguous"
    # What the mesh's `model` axis carries: "cp" (ring attention over the
    # sequence) or "tp" (Megatron-style tensor parallelism: heads and MLP
    # hidden sharded over the axis via sharding constraints; GSPMD splits
    # the matmuls and inserts the reduce).
    model_axis_mode: str = "cp"

    def _single_device_attend(self, t: int, head_dim: int):
        from elasticdl_tpu.ops import flash_attention
        from elasticdl_tpu.ops.flash_attention import (
            supports,
            warn_if_vmem_is_sole_blocker,
        )

        use_pallas = self.attn_impl == "pallas" or (
            self.attn_impl == "auto"
            and jax.default_backend() == "tpu"
            and supports(t, head_dim)
        )
        if use_pallas:
            return partial(flash_attention, causal=True)
        if self.attn_impl == "auto" and jax.default_backend() == "tpu":
            warn_if_vmem_is_sole_blocker("model_zoo.transformer", t, head_dim)
        return partial(blockwise_attention, causal=True)

    @nn.compact
    def __call__(self, x):
        if self.attn_impl not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"attn_impl must be 'auto', 'pallas' or 'xla', "
                f"got {self.attn_impl!r}"
            )
        if self.model_axis_mode not in ("cp", "tp"):
            raise ValueError(
                f"model_axis_mode must be 'cp' or 'tp', "
                f"got {self.model_axis_mode!r}"
            )
        b, t, e = x.shape
        head_dim = e // self.num_heads
        sharded_axis = (
            self.mesh is not None
            and self.mesh.shape.get(MODEL_AXIS, 1) > 1
        )
        cp = sharded_axis and self.model_axis_mode == "cp"
        tp = sharded_axis and self.model_axis_mode == "tp"
        zigzag = cp and self.cp_layout == "zigzag"
        inv = None
        if zigzag:
            # Balanced causal ring: permute the sequence into the zigzag
            # shard layout around the attention only (hidden states stay
            # in natural order for pos-emb / loss).  Permuting x ONCE
            # here — the qkv projection is position-wise — instead of
            # q/k/v separately cuts the cross-shard permute traffic 3x.
            from elasticdl_tpu.parallel.ring_attention import zigzag_orders

            order, inv = (
                jnp.asarray(o)
                for o in zigzag_orders(t, self.mesh.shape[MODEL_AXIS])
            )
            x = x[:, order]
        qkv = nn.DenseGeneral(
            (3, self.num_heads, head_dim), dtype=self.dtype, name="qkv"
        )(x)
        if tp:
            # Column-parallel qkv: heads shard over the model axis, so
            # each device computes its heads' attention locally (the
            # single-device kernels below partition head-wise under
            # GSPMD; pallas custom calls don't, hence the xla path).
            qkv = _constrain(
                self.mesh, qkv, DATA_AXIS, None, None, MODEL_AXIS, None
            )
        q, k, v = (qkv[:, :, i] for i in range(3))  # [B, T, H, D] each
        if cp:
            # The ring's per-step block engine: 'auto' runs the Pallas
            # flash kernels whenever the local shard shape fits (round 3
            # — the ring previously always used the XLA block math and
            # forfeited the measured 2.4x kernel win exactly where long
            # context matters; see ring_attention_pallas).
            attend = make_ring_attention(
                self.mesh, causal=True, layout=self.cp_layout,
                impl=self.attn_impl,
            )
        elif tp:
            if self.attn_impl == "pallas":
                raise ValueError(
                    "attn_impl='pallas' cannot partition over the model "
                    "axis (custom calls are opaque to GSPMD); tensor-"
                    "parallel attention runs the XLA blockwise engine"
                )
            attend = partial(blockwise_attention, causal=True)
        else:
            attend = self._single_device_attend(t, head_dim)
        out = attend(q, k, v)  # [B, T, H, D]
        if zigzag:
            out = out[:, inv]
        out = out.reshape(b, t, e)
        out = nn.Dense(e, dtype=self.dtype, name="proj")(out)
        if tp:
            # Row-parallel proj closes the TP block: output replicated
            # over the model axis (GSPMD inserts the partial-sum reduce).
            out = _constrain(self.mesh, out, DATA_AXIS, None, None)
        return out


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    mesh: Any = None
    attn_impl: str = "auto"
    cp_layout: str = "contiguous"
    model_axis_mode: str = "cp"

    @nn.compact
    def __call__(self, x):
        e = x.shape[-1]
        attn = CausalSelfAttention(
            self.num_heads, self.dtype, self.mesh, self.attn_impl,
            self.cp_layout, self.model_axis_mode, name="attn",
        )
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + attn(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(e * self.mlp_ratio, dtype=self.dtype)(h)
        if _tp_active(self.mesh, self.model_axis_mode):
            # Column-parallel up-projection / row-parallel down-projection
            # (the Megatron MLP): hidden shards over the model axis
            # (batch stays on `data`), the residual add below stays
            # replicated over `model`.
            h = _constrain(self.mesh, h, DATA_AXIS, None, MODEL_AXIS)
        h = nn.gelu(h)
        return x + nn.Dense(e, dtype=self.dtype)(h)


class _Bf16AccF32Head(nn.Module):
    """LM head with bf16 operands and f32 accumulation/output: params
    stay f32 and use nn.Dense's names (kernel/bias), so checkpoints are
    interchangeable with the f32 head; only the matmul INPUTS round to
    bf16 (the MXU's native mode — same numerics as the bf16 blocks),
    while logits and the loss softmax stay full precision."""

    vocab: int

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (x.shape[-1], self.vocab),
            jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (self.vocab,), jnp.float32
        )
        logits = jax.lax.dot_general(
            x.astype(jnp.bfloat16),
            kernel.astype(jnp.bfloat16),
            (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return logits + bias


class TransformerLM(nn.Module):
    vocab: int = VOCAB
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    max_len: int = 4096
    dtype: Any = jnp.bfloat16
    mesh: Any = None
    attn_impl: str = "auto"
    cp_layout: str = "contiguous"
    model_axis_mode: str = "cp"
    # Rematerialize each block's activations in backward (jax.checkpoint)
    # — trades ~30% more FLOPs for O(layers) less activation memory, the
    # standard long-context lever.
    remat: bool = False
    # LM-head matmul precision.  "f32": f32 x f32 (the conservative
    # default).  "bf16": bf16 operands on the MXU with f32 ACCUMULATION
    # and f32 logits out (preferred_element_type) — the same numerics as
    # every other matmul in the bf16 blocks; the head is ~half the
    # model's FLOPs at this vocab/d_model, so its matmul rate moves the
    # headline (BASELINE.md long-context section).
    logits_compute: str = "f32"

    @nn.compact
    def __call__(self, tokens, train: bool = False):
        if self.logits_compute not in ("f32", "bf16"):
            raise ValueError(
                f"logits_compute must be 'f32' or 'bf16', "
                f"got {self.logits_compute!r}"
            )
        b, t = tokens.shape
        tok = nn.Embed(self.vocab, self.d_model, dtype=self.dtype)(tokens)
        pos = nn.Embed(self.max_len, self.d_model, dtype=self.dtype)(
            jnp.arange(t)[None, :]
        )
        x = tok + pos
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.num_layers):
            x = block_cls(
                self.num_heads, dtype=self.dtype, mesh=self.mesh,
                attn_impl=self.attn_impl, cp_layout=self.cp_layout,
                model_axis_mode=self.model_axis_mode,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        if self.logits_compute == "bf16":
            return _Bf16AccF32Head(self.vocab, name="lm_head")(x)
        # Logits in f32: the loss softmax wants full precision.
        return nn.Dense(self.vocab, dtype=jnp.float32, name="lm_head")(x)


def custom_model(
    vocab: int = VOCAB,
    d_model: int = 128,
    num_heads: int = 4,
    num_layers: int = 2,
    max_len: int = 4096,
    use_bf16: bool = True,
    mesh: Optional[Any] = None,
    attn_impl: str = "auto",
    cp_layout: str = "contiguous",
    model_axis_mode: str = "cp",
    remat: bool = False,
    logits_compute: str = "f32",
):
    """`mesh=None` -> single-device attention (Pallas flash kernel on
    TPU).  With the trainer's mesh and model axis > 1, `model_axis_mode`
    picks what that axis carries: "cp" (default) runs ring-attention
    context parallelism — the model-axis size must then divide the
    sequence length (each device holds T / model_axis positions) — and
    "tp" runs Megatron-style tensor parallelism (heads and MLP hidden
    shard over the axis; no sequence-divisibility requirement, though
    num_heads should divide the axis size for an even split)."""
    return TransformerLM(
        vocab=vocab,
        d_model=d_model,
        num_heads=num_heads,
        num_layers=num_layers,
        max_len=max_len,
        dtype=jnp.bfloat16 if use_bf16 else jnp.float32,
        mesh=mesh,
        attn_impl=attn_impl,
        cp_layout=cp_layout,
        model_axis_mode=model_axis_mode,
        remat=remat,
        logits_compute=logits_compute,
    )


def loss(labels, predictions):
    """Mean next-token cross-entropy; labels [B, T], logits [B, T, V]."""
    return optax.softmax_cross_entropy_with_integer_labels(
        predictions.astype(jnp.float32), labels.astype(jnp.int32)
    ).mean()


def optimizer(lr: float = 3e-3):
    return optax.adamw(lr, weight_decay=0.01)


def dataset_fn(dataset, mode, metadata):
    def parse(record):
        tokens, next_tokens = record
        return np.asarray(tokens, np.int32), np.asarray(
            next_tokens, np.int32
        )

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(1024, seed=0)
    return dataset


def eval_metrics_fn():
    def perplexity(outputs, labels):
        ce = float(loss(jnp.asarray(labels), jnp.asarray(outputs)))
        return float(np.exp(min(ce, 20.0)))

    return {
        "perplexity": perplexity,
        "accuracy": lambda outputs, labels: float(
            np.mean(np.argmax(outputs, axis=-1) == labels)
        ),
    }


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name != "lm":
        return None
    return datasets.synthetic_lm_reader(
        n=params.get("n", 2048),
        seq_len=params.get("len", SEQ_LEN),
        vocab=params.get("vocab", VOCAB),
        seed=params.get("seed", 0),
    )
