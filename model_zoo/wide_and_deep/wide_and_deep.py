"""Wide-and-Deep (census) — model-zoo contract, JAX/flax body.

Parity: the reference's census wide-and-deep
(model_zoo/census_model_sqlflow / wide_and_deep; BASELINE config 3).  The
categorical path uses the framework's sharded Embedding layer
(elasticdl_tpu.layers.Embedding — the `elasticdl.layers.Embedding`
equivalent), so in ParameterServerStrategy the tables shard across every
chip's HBM and updates run through the sparse row-wise optimizers.

Wide part: per-field dim-1 embeddings (a sharded linear-in-one-hot, the
feature-column 'wide' column); deep part: per-field dim-8 embeddings
concatenated with the dense features into an MLP.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.parallel import sparse_optim
from model_zoo import datasets

NUM_DENSE = 13
NUM_CAT = 26
VOCAB = 1000


class WideAndDeep(nn.Module):
    vocab_size: int = VOCAB
    embedding_dim: int = 8
    hidden: int = 64

    @nn.compact
    def __call__(self, features, train: bool = False):
        dense = jnp.asarray(features["dense"], jnp.float32)
        # Offset each field into a disjoint id range of one shared table
        # (the reference's embedding_column with one table per feature
        # group; a single offset table keeps lookups to one gather).
        cats = jnp.asarray(features["cat"], jnp.int32)
        offsets = jnp.arange(cats.shape[-1], dtype=jnp.int32) * self.vocab_size
        flat_ids = cats + offsets[None, :]
        total_vocab = self.vocab_size * cats.shape[-1]

        wide = Embedding(
            total_vocab, 1, combiner="sum", name="wide_embedding"
        )(flat_ids)[..., 0]

        deep_emb = Embedding(
            total_vocab, self.embedding_dim, name="deep_embedding"
        )(flat_ids)
        deep_in = jnp.concatenate(
            [deep_emb.reshape((deep_emb.shape[0], -1)), dense], axis=-1
        )
        x = nn.relu(nn.Dense(self.hidden)(deep_in))
        x = nn.relu(nn.Dense(self.hidden // 2)(x))
        deep = nn.Dense(1)(x)[..., 0]
        return wide + deep  # logit


def custom_model(vocab_size: int = VOCAB, embedding_dim: int = 8, hidden: int = 64):
    return WideAndDeep(
        vocab_size=vocab_size, embedding_dim=embedding_dim, hidden=hidden
    )


def loss(labels, predictions):
    return optax.sigmoid_binary_cross_entropy(
        predictions, labels.astype(jnp.float32)
    ).mean()


def optimizer(lr: float = 0.005):
    return optax.adam(lr)


def embedding_optimizer(lr: float = 0.005):
    return sparse_optim.adam(lr)


def dataset_fn(dataset, mode, metadata):
    def parse(record):
        features, label = record
        return (
            {
                "dense": np.asarray(features["dense"], np.float32),
                "cat": np.asarray(features["cat"], np.int32),
            },
            np.int32(label),
        )

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(2048, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda outputs, labels: np.mean(
            (outputs > 0).astype(np.int64) == labels.astype(np.int64)
        ),
        "auc": _auc,
    }


def _auc(outputs, labels):
    order = np.argsort(outputs)
    ranks = np.empty_like(order, dtype=np.float64)
    ranks[order] = np.arange(1, len(outputs) + 1)
    pos = labels.astype(bool)
    n_pos = int(pos.sum())
    n_neg = len(labels) - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    return float(
        (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)
    )


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name is None:
        return None
    return datasets.synthetic_ctr_reader(
        n=params.get("n", 4096),
        num_dense=NUM_DENSE,
        num_categorical=NUM_CAT,
        vocab_size=params.get("vocab", VOCAB),
        seed=params.get("seed", 0),
        shard_name="census-synth",
    )
