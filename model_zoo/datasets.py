"""Shared dataset helpers for model-zoo modules.

The environment has no network egress, so each zoo config can fall back to a
deterministic synthetic dataset with the same shapes/dtypes as the real one
(`synthetic://<name>?n=<records>` data paths).  Real data works through the
standard readers (csv/recordio) when a path is given.
"""

from __future__ import annotations

import urllib.parse

import numpy as np

from elasticdl_tpu.data.reader import AbstractDataReader, NumpyDataReader


def parse_synthetic_path(data_path: str):
    """'synthetic://mnist?n=4096&seed=3' -> ('mnist', {'n': 4096, 'seed': 3})."""
    parsed = urllib.parse.urlparse(data_path)
    if parsed.scheme != "synthetic":
        return None, {}
    params = {
        key: int(values[0])
        for key, values in urllib.parse.parse_qs(parsed.query).items()
    }
    return parsed.netloc, params


def synthetic_mnist_reader(n: int = 4096, seed: int = 0, shard_name="mnist-synth"):
    """MNIST-shaped learnable synthetic data: 28x28 uint8 images whose label
    is recoverable from the image content (class-dependent mean patches), so
    training loss genuinely decreases."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    # Class template: a distinct bright 7x7 patch position per class.
    images = rng.integers(0, 64, size=(n, 28, 28)).astype(np.uint8)
    for cls in range(10):
        rows = (cls // 5) * 14 + 3
        cols = (cls % 5) * 5 + 1
        mask = labels == cls
        images[mask, rows : rows + 7, cols : cols + 5] = 200
    return NumpyDataReader(images, labels, shard_name=shard_name)


def synthetic_cifar10_reader(n: int = 4096, seed: int = 0, shard_name="cifar-synth"):
    """CIFAR-shaped learnable synthetic data: 32x32x3 uint8 images with a
    class-dependent colored patch, so accuracy is genuinely learnable."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=n).astype(np.int32)
    images = rng.integers(0, 64, size=(n, 32, 32, 3)).astype(np.uint8)
    for cls in range(10):
        rows = (cls // 5) * 16 + 3
        cols = (cls % 5) * 6 + 1
        channel = cls % 3
        mask = labels == cls
        images[mask, rows : rows + 8, cols : cols + 6, channel] = 220
    return NumpyDataReader(images, labels, shard_name=shard_name)


def synthetic_imagenet_reader(
    n: int = 1024,
    seed: int = 0,
    image_size: int = 224,
    num_classes: int = 1000,
    shard_name: str = "imagenet-synth",
):
    """ImageNet-shaped learnable synthetic data: image_size^2 x3 uint8
    images with a class-dependent bright patch (position/channel derived
    from the label), so accuracy genuinely moves.  Images are generated
    lazily per record to keep memory bounded at 224x224x3."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    seeds = rng.integers(0, 2**31 - 1, size=n)

    grid = max(1, image_size // 16)

    def make_image(i: int) -> np.ndarray:
        r = np.random.default_rng(int(seeds[i]))
        image = r.integers(0, 64, size=(image_size, image_size, 3)).astype(
            np.uint8
        )
        cls = int(labels[i])
        row = (cls // grid) % grid * 16
        col = (cls % grid) * 16
        channel = cls % 3
        image[row : row + 12, col : col + 12, channel] = 220
        return image

    class _ImagenetReader(AbstractDataReader):
        def create_shards(self):
            return {shard_name: n}

        def read_records(self, task):
            for i in range(task.start, min(task.end, n)):
                yield make_image(i), labels[i]

    return _ImagenetReader()


def synthetic_ctr_reader(
    n: int = 4096,
    num_dense: int = 13,
    num_categorical: int = 26,
    vocab_size: int = 1000,
    seed: int = 0,
    shard_name: str = "ctr-synth",
):
    """Criteo/census-shaped learnable CTR data.

    A record is ({'dense': float32[num_dense], 'cat': int32[num_categorical]},
    label in {0,1}).  The label depends on a sparse set of (field, id)
    weights plus a linear term on the dense features, so both the embedding
    path and the dense path must learn for accuracy to move.
    """
    rng = np.random.default_rng(seed)
    dense = rng.standard_normal((n, num_dense)).astype(np.float32)
    cats = rng.integers(0, vocab_size, size=(n, num_categorical)).astype(np.int32)
    field_weights = rng.standard_normal((num_categorical, vocab_size)).astype(
        np.float32
    )
    dense_weights = rng.standard_normal((num_dense,)).astype(np.float32)
    cat_logit = np.zeros((n,), np.float32)
    for field in range(num_categorical):
        cat_logit += field_weights[field, cats[:, field]]
    logits = dense @ dense_weights + cat_logit / np.sqrt(num_categorical)
    labels = (logits > np.median(logits)).astype(np.int32)
    records = [
        ({"dense": dense[i], "cat": cats[i]}, labels[i]) for i in range(n)
    ]

    class _CTRReader(AbstractDataReader):
        def create_shards(self):
            return {shard_name: len(records)}

        def read_records(self, task):
            for i in range(task.start, min(task.end, len(records))):
                yield records[i]

    return _CTRReader()


def synthetic_ctr_columns(
    n: int,
    num_dense: int = 13,
    num_categorical: int = 26,
    vocab_size: int = 1000,
    weights_seed: int = 0,
    draw_seed: int = 1,
    zipf_s: float = 0.0,
):
    """Vectorized, ground-truth CTR columns at benchmark scale.

    The columnar counterpart of `synthetic_ctr_reader` for experiments
    that need millions of rows (the per-record list there is host-bound):
    returns `(dense [n, D] f32, cats [n, C] i32, labels [n] i32)` drawn
    from a fixed ground-truth model — per-(field, id) embedding effects
    plus a dense linear term — so train and held-out splits generated
    with the SAME `weights_seed` but different `draw_seed`s share one
    learnable distribution (the convergence-A/B contract,
    scripts/convergence_ab.py).

    Labels are Bernoulli(sigmoid(logit)) with both logit terms scaled to
    ~unit variance: the Bayes AUC sits near 0.84, Criteo-like, so metric
    differences between optimizer configs are visible above a
    deterministic-label ceiling.

    `zipf_s > 0` draws category ids from a truncated Zipf(s) instead of
    uniform — hot rows are touched many times per step/window, which is
    the adversarial case for windowed sparse apply (a hot row gets ONE
    summed-gradient Adam update per window instead of W sequential ones).
    """
    wrng = np.random.default_rng(weights_seed)
    field_weights = wrng.standard_normal(
        (num_categorical, vocab_size)
    ).astype(np.float32)
    dense_weights = wrng.standard_normal((num_dense,)).astype(np.float32)

    rng = np.random.default_rng(draw_seed)
    dense = rng.standard_normal((n, num_dense)).astype(np.float32)
    if zipf_s > 0.0:
        # Truncated-Zipf inverse-CDF sampling: rank r gets mass
        # 1/(r+1)^s; ids are rank-ordered (id 0 hottest), which is fine —
        # the table is offset per field, so per-field hot sets are
        # disjoint rows exactly as with a permuted mapping.
        pmf = 1.0 / np.power(np.arange(1, vocab_size + 1), zipf_s)
        cdf = np.cumsum(pmf / pmf.sum())
        # Float error can leave cdf[-1] slightly below 1.0, and a uniform
        # draw landing above it would searchsorted to vocab_size (OOB).
        cdf[-1] = 1.0
        u = rng.random(size=(n, num_categorical))
        cats = np.searchsorted(cdf, u).astype(np.int32)
    else:
        cats = rng.integers(
            0, vocab_size, size=(n, num_categorical)
        ).astype(np.int32)
    cat_logit = np.take_along_axis(
        field_weights.T, cats, axis=0
    ).sum(axis=1, dtype=np.float64)
    logits = (
        dense @ dense_weights / np.sqrt(num_dense)
        + cat_logit / np.sqrt(num_categorical)
    ).astype(np.float32)
    labels = (
        rng.random(size=n) < 1.0 / (1.0 + np.exp(-logits))
    ).astype(np.int32)
    return dense, cats, labels


def synthetic_classification_reader(
    n: int, num_features: int, num_classes: int, seed: int = 0, shard_name="synth"
):
    """Generic learnable tabular classification data (float32 features)."""
    rng = np.random.default_rng(seed)
    weights = rng.standard_normal((num_features, num_classes)).astype(np.float32)
    features = rng.standard_normal((n, num_features)).astype(np.float32)
    logits = features @ weights + 0.1 * rng.standard_normal((n, num_classes)).astype(
        np.float32
    )
    labels = np.argmax(logits, axis=1).astype(np.int32)
    return NumpyDataReader(features, labels, shard_name=shard_name)


# Census raw-feature vocabularies (reference: the census dataset the
# elasticdl_preprocessing layers were built for — strings + floats).
CENSUS_EDUCATION = [
    "Bachelors", "HS-grad", "11th", "Masters", "9th", "Some-college",
    "Assoc-acdm", "Assoc-voc", "7th-8th", "Doctorate", "Prof-school",
    "5th-6th", "10th", "1st-4th", "Preschool", "12th",
]
CENSUS_WORKCLASS = [
    "Private", "Self-emp-not-inc", "Self-emp-inc", "Federal-gov",
    "Local-gov", "State-gov", "Without-pay", "Never-worked",
]
CENSUS_OCCUPATIONS = [
    f"occupation-{i}" for i in range(40)  # high-cardinality: gets hashed
]


def synthetic_census_reader(n: int = 4096, seed: int = 0,
                            shard_name: str = "census-synth"):
    """Census-shaped RAW records: strings + unscaled floats, exactly what
    the preprocessing layers exist to consume.  A record is
    ({'age': f32, 'capital_gain': f32, 'hours_per_week': f32,
      'education': str, 'workclass': str, 'occupation': str}, label) with
    a label genuinely dependent on every feature family, so training only
    learns if the preprocessing (lookup/hash/discretize/normalize) wires
    the features through correctly."""
    rng = np.random.default_rng(seed)
    age = rng.uniform(17, 90, size=n).astype(np.float32)
    gain = np.abs(rng.normal(3000, 8000, size=n)).astype(np.float32)
    hours = rng.uniform(1, 99, size=n).astype(np.float32)
    edu_idx = rng.integers(0, len(CENSUS_EDUCATION), size=n)
    work_idx = rng.integers(0, len(CENSUS_WORKCLASS), size=n)
    occ_idx = rng.integers(0, len(CENSUS_OCCUPATIONS), size=n)

    w_edu = rng.standard_normal(len(CENSUS_EDUCATION)).astype(np.float32)
    w_work = rng.standard_normal(len(CENSUS_WORKCLASS)).astype(np.float32)
    w_occ = rng.standard_normal(len(CENSUS_OCCUPATIONS)).astype(np.float32)
    logits = (
        w_edu[edu_idx]
        + w_work[work_idx]
        + w_occ[occ_idx]
        + 0.03 * (hours - 40.0)
        + 0.02 * (age - 40.0)
        + gain / 20000.0
    )
    labels = (logits > np.median(logits)).astype(np.int32)
    records = [
        (
            {
                "age": age[i],
                "capital_gain": gain[i],
                "hours_per_week": hours[i],
                "education": CENSUS_EDUCATION[edu_idx[i]],
                "workclass": CENSUS_WORKCLASS[work_idx[i]],
                "occupation": CENSUS_OCCUPATIONS[occ_idx[i]],
            },
            labels[i],
        )
        for i in range(n)
    ]

    class _CensusReader(AbstractDataReader):
        def create_shards(self):
            return {shard_name: len(records)}

        def read_records(self, task):
            for i in range(task.start, min(task.end, len(records))):
                yield records[i]

    return _CensusReader()


def synthetic_lm_reader(
    n: int = 2048,
    seq_len: int = 128,
    vocab: int = 256,
    seed: int = 0,
    shard_name: str = "lm-synth",
):
    """Language-modeling-shaped learnable synthetic data: token sequences
    from a deterministic affine bigram chain (next = 3*tok + 7 mod vocab)
    with 10% uniform noise — a next-token structure a small transformer
    learns quickly, so training loss genuinely decreases.  A record is
    (tokens [seq_len] int32, next_tokens [seq_len] int32)."""
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, vocab, size=n)
    noise = rng.random(size=(n, seq_len)) < 0.1
    noise_tok = rng.integers(0, vocab, size=(n, seq_len))
    seqs = np.empty((n, seq_len + 1), np.int32)
    seqs[:, 0] = starts
    for t in range(seq_len):
        nxt = (3 * seqs[:, t] + 7) % vocab
        seqs[:, t + 1] = np.where(noise[:, t], noise_tok[:, t], nxt)
    return NumpyDataReader(
        seqs[:, :-1].copy(), seqs[:, 1:].copy(), shard_name=shard_name
    )
