"""DeepFM (Criteo/DAC click-through) — model-zoo contract, JAX/flax body.

Parity: model_zoo/deepfm_functional_api in the reference (BASELINE config
4, the north-star workload).  TPU-first body:

- 26 categorical fields share one offset embedding table through the
  framework's sharded Embedding layer — in ParameterServerStrategy the
  table (vocab 26M+ at Criteo scale) spreads over every chip's HBM and is
  updated sparsely, never materializing a dense gradient.
- FM second-order term uses the sum-square trick (one elementwise fuse, no
  pairwise blowup); all matmuls are MXU-shaped.
- Numeric features get per-field linear + embedding projections.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.parallel import sparse_optim
from model_zoo import datasets

NUM_DENSE = 13
NUM_CAT = 26
VOCAB = 1000


class DeepFM(nn.Module):
    vocab_size: int = VOCAB
    embedding_dim: int = 8
    hidden: int = 128

    @nn.compact
    def __call__(self, features, train: bool = False):
        dense = jnp.asarray(features["dense"], jnp.float32)  # [B, 13]
        cats = jnp.asarray(features["cat"], jnp.int32)       # [B, 26]
        batch = cats.shape[0]
        offsets = jnp.arange(cats.shape[-1], dtype=jnp.int32) * self.vocab_size
        flat_ids = cats + offsets[None, :]
        total_vocab = self.vocab_size * cats.shape[-1]

        # First-order terms: dim-1 embedding per categorical id + linear on
        # the numeric fields.
        first_cat = Embedding(
            total_vocab, 1, combiner="sum", name="linear_embedding"
        )(flat_ids)[..., 0]
        first_dense = nn.Dense(1, name="linear_dense")(dense)[..., 0]

        # Field embeddings for FM + deep: categorical via the sharded
        # table, numeric projected per-field to the same dim.
        cat_emb = Embedding(
            total_vocab, self.embedding_dim, name="fm_embedding"
        )(flat_ids)                                          # [B, 26, d]
        dense_emb = nn.DenseGeneral(
            (NUM_DENSE, self.embedding_dim), axis=-1, name="dense_projection"
        )(dense[:, None, :])[:, 0]                           # [B, 13, d]
        fields = jnp.concatenate([cat_emb, dense_emb], axis=1)  # [B, 39, d]

        # FM second order: 0.5 * (sum^2 - sum-of-squares).
        sum_fields = jnp.sum(fields, axis=1)
        second = 0.5 * jnp.sum(
            sum_fields * sum_fields - jnp.sum(fields * fields, axis=1), axis=-1
        )

        # Deep tower over the flattened field embeddings.
        x = fields.reshape((batch, -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden // 2)(x))
        deep = nn.Dense(1)(x)[..., 0]

        return first_cat + first_dense + second + deep  # logit


def custom_model(vocab_size: int = VOCAB, embedding_dim: int = 8, hidden: int = 128):
    return DeepFM(vocab_size=vocab_size, embedding_dim=embedding_dim, hidden=hidden)


def loss(labels, predictions):
    return optax.sigmoid_binary_cross_entropy(
        predictions, labels.astype(jnp.float32)
    ).mean()


def optimizer(lr: float = 0.001):
    return optax.adam(lr)


def embedding_optimizer(lr: float = 0.001):
    return sparse_optim.adam(lr)


def dataset_fn(dataset, mode, metadata):
    def parse(record):
        features, label = record
        return (
            {
                "dense": np.asarray(features["dense"], np.float32),
                "cat": np.asarray(features["cat"], np.int32),
            },
            np.int32(label),
        )

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(4096, seed=0)
    return dataset


def eval_metrics_fn():
    from model_zoo.wide_and_deep.wide_and_deep import _auc

    return {
        "accuracy": lambda outputs, labels: np.mean(
            (outputs > 0).astype(np.int64) == labels.astype(np.int64)
        ),
        "auc": _auc,
    }


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name is None:
        return None
    return datasets.synthetic_ctr_reader(
        n=params.get("n", 4096),
        num_dense=NUM_DENSE,
        num_categorical=NUM_CAT,
        vocab_size=params.get("vocab", VOCAB),
        seed=params.get("seed", 0),
        shard_name="criteo-synth",
    )
