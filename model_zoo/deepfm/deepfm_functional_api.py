"""DeepFM (Criteo/DAC click-through) — model-zoo contract, JAX/flax body.

Parity: model_zoo/deepfm_functional_api in the reference (BASELINE config
4, the north-star workload).  TPU-first body:

- 26 categorical fields share one offset embedding table through the
  framework's sharded Embedding layer — in ParameterServerStrategy the
  table (vocab 26M+ at Criteo scale) spreads over every chip's HBM and is
  updated sparsely, never materializing a dense gradient.
- FM second-order term uses the sum-square trick (one elementwise fuse, no
  pairwise blowup); all matmuls are MXU-shaped.
- Numeric features get per-field linear + embedding projections.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.data.reader import FixedWidthEtrfReader
from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.parallel import sparse_optim
from model_zoo import datasets

NUM_DENSE = 13
NUM_CAT = 26
VOCAB = 1000


# Auto table-layout crossover (see DeepFM.split_tables): measured on the
# v5e at the 26M-row probe (BASELINE.md "table-scale probe").  Strict
# per-step mode pays table-sized streaming passes whose cost scales with
# DESTINATION BLOCKS; merging the dim-1 linear into a dim-9 (pad 16)
# table doubled those blocks (1.83M -> 3.25M) and strict throughput fell
# 192k -> 157k.  Windowed mode (sparse_apply_every > 1) amortizes the
# passes, so the merged table's halved count-bound cost wins there.
SPLIT_TABLE_ROWS = 10_000_000


class DeepFM(nn.Module):
    vocab_size: int = VOCAB
    embedding_dim: int = 8
    hidden: int = 128
    # Table layout: the combined 1+dim table is the default — one
    # lookup gather + one grad scatter per step where the reference's
    # split linear+fm layout paid two (the dual-lookup waste the old
    # comment documented).  `split_tables` stays as the COMPAT FLAG:
    # checkpoints saved under the split layout restore only into a
    # split build (ps_trainer's manifest check names this flag), so
    # pass --model_params split_tables=true to keep reading them.
    # None = auto: merged everywhere EXCEPT the one measured exception
    # — strict per-step apply at >SPLIT_TABLE_ROWS rows under the XLA
    # sparse path, where the per-step table-sized streaming pass
    # charges by destination blocks (merged doubles them; 192k->157k,
    # BASELINE.md table-scale probe).  The fused kernel path
    # (--sparse_kernel=fused) is touched-row-bound with no streaming
    # pass, so it keeps the merged layout at every scale.
    split_tables: bool | None = None
    sparse_apply_every: int = 1
    # 'xla' | 'fused' | 'auto' | None (process default) — threaded into
    # the Embedding layers (lookup/FM kernels) and the auto layout rule.
    sparse_kernel: str | None = None
    # The job mesh (model_utils forwards it to mesh-aware models):
    # under the fused kernel on a multi-device mesh the Embedding ops
    # dispatch per-shard bodies through shard_map (tables over the
    # `model` axis — ops/sparse_embedding.py "Sharded dispatch").
    mesh: Any = None

    def _resolved_kernel(self) -> str:
        from elasticdl_tpu.ops import sparse_embedding as ske

        return ske.resolve_kernel(self.sparse_kernel)

    def _split(self, total_vocab: int) -> bool:
        if self.split_tables is not None:
            return self.split_tables
        return (
            self.sparse_apply_every <= 1
            and total_vocab > SPLIT_TABLE_ROWS
            and self._resolved_kernel() != "fused"
        )

    @nn.compact
    def __call__(self, features, train: bool = False):
        dense = jnp.asarray(features["dense"], jnp.float32)  # [B, 13]
        cats = jnp.asarray(features["cat"], jnp.int32)       # [B, 26]
        batch = cats.shape[0]
        offsets = jnp.arange(cats.shape[-1], dtype=jnp.int32) * self.vocab_size
        flat_ids = cats + offsets[None, :]
        total_vocab = self.vocab_size * cats.shape[-1]

        first_dense = nn.Dense(1, name="linear_dense")(dense)[..., 0]
        dense_emb = nn.DenseGeneral(
            (NUM_DENSE, self.embedding_dim), axis=-1, name="dense_projection"
        )(dense[:, None, :])[:, 0]                           # [B, 13, d]
        if self._split(total_vocab):
            # TWO tables (the reference's layout: linear + fm) — the
            # xla-strict->10M-row exception only (see split_tables):
            # a second lookup gather + grad scatter (~25 ns/row each)
            # buys halved destination blocks for the per-step streaming
            # passes (1.83M vs 3.25M at the 26M probe).
            linear = Embedding(
                total_vocab, 1, name="linear_embedding",
                sparse_kernel=self.sparse_kernel, mesh=self.mesh,
            )(flat_ids)                                      # [B, 26, 1]
            first_cat = jnp.sum(linear[..., 0], axis=-1)     # [B]
            cat_emb = Embedding(
                total_vocab, self.embedding_dim, name="fm_embedding",
                sparse_kernel=self.sparse_kernel, mesh=self.mesh,
            )(flat_ids)                                      # [B, 26, d]
            # FM second order: 0.5 * (sum^2 - sum-of-squares) over all
            # 39 fields at once.
            fields = jnp.concatenate([cat_emb, dense_emb], axis=1)
            sum_fields = jnp.sum(fields, axis=1)
            second = 0.5 * jnp.sum(
                sum_fields * sum_fields
                - jnp.sum(fields * fields, axis=1),
                axis=-1,
            )
        else:
            # ONE merged table of dim 1+d (the default layout): lane 0
            # is the first-order (linear) weight, lanes 1..d the
            # FM/deep field vector — one gather + one scatter per step
            # instead of two.  fm_interaction returns the activations
            # (deep tower input) AND the categorical FM partial sums
            # from the same pass — under the fused kernel those sums
            # accumulate in VMEM during the lookup, so the FM term
            # never re-reads [B, 26, 1+d] from HBM.  The dense fields'
            # sums compose algebraically:
            #   (S_cat + S_dense)^2 - (SS_cat + SS_dense)
            cat_acts, first_cat, sum_v, sum_sq = Embedding(
                total_vocab, 1 + self.embedding_dim, name="fm_embedding",
                sparse_kernel=self.sparse_kernel, fm_interaction=True,
                mesh=self.mesh,
            )(flat_ids)                                      # [B, 26, 1+d]
            cat_emb = cat_acts[..., 1:]                      # [B, 26, d]
            fields = jnp.concatenate([cat_emb, dense_emb], axis=1)
            sum_dense = jnp.sum(dense_emb, axis=1)           # [B, d]
            sumsq_dense = jnp.sum(dense_emb * dense_emb, axis=1)
            total_sum = sum_v + sum_dense
            second = 0.5 * jnp.sum(
                total_sum * total_sum - (sum_sq + sumsq_dense), axis=-1
            )

        # Deep tower over the flattened field embeddings.
        x = fields.reshape((batch, -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden // 2)(x))
        deep = nn.Dense(1)(x)[..., 0]

        return first_cat + first_dense + second + deep  # logit


def custom_model(
    vocab_size: int = VOCAB,
    embedding_dim: int = 8,
    hidden: int = 128,
    split_tables: bool | None = None,
    sparse_apply_every: "int | str" = 1,
    sparse_kernel: "str | None" = None,
    mesh: Any = None,
):
    """`sparse_apply_every` arrives from the job flag (model_utils
    forwards it to models declaring the parameter) and drives the auto
    table layout; `--model_params split_tables=...` overrides.  The
    flag's 'auto' resolves here from the model's own vocabulary using
    the SAME threshold the trainer resolves with at init
    (ps_trainer.AUTO_APPLY_TABLE_ROWS == SPLIT_TABLE_ROWS), so layout
    and apply mode can't diverge: auto at <=10M rows is strict+merged,
    above it windowed+merged — auto never reaches the strict-large
    regime the split layout exists for."""
    if sparse_apply_every == "auto":
        from elasticdl_tpu.parallel.ps_trainer import (
            AUTO_APPLY_TABLE_ROWS,
            AUTO_APPLY_W,
        )

        # Count the rows the TRAINER will count: it sums rows over the
        # actual tables at init, so a forced split layout
        # (--model_params split_tables=true) holds 2x total_vocab rows
        # (linear + fm).  Resolving from the same count keeps layout
        # and apply mode consistent in every configuration.
        total_rows = vocab_size * NUM_CAT * (2 if split_tables else 1)
        sparse_apply_every = (
            1 if total_rows <= AUTO_APPLY_TABLE_ROWS else AUTO_APPLY_W
        )
    return DeepFM(
        vocab_size=vocab_size,
        embedding_dim=embedding_dim,
        hidden=hidden,
        split_tables=split_tables,
        sparse_apply_every=sparse_apply_every,
        sparse_kernel=sparse_kernel,
        mesh=mesh,
    )


def loss(labels, predictions):
    return optax.sigmoid_binary_cross_entropy(
        predictions, labels.astype(jnp.float32)
    ).mean()


def optimizer(lr: float = 0.001):
    return optax.adam(lr)


def embedding_optimizer(lr: float = 0.001):
    return sparse_optim.adam(lr)


def dataset_fn(dataset, mode, metadata):
    def parse(record):
        features, label = record
        return (
            {
                "dense": np.asarray(features["dense"], np.float32),
                "cat": np.asarray(features["cat"], np.int32),
            },
            np.int32(label),
        )

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(4096, seed=0)
    return dataset


def columnar_dataset_fn(columns, mode, metadata, seed: int = 0):
    """Vectorized counterpart of dataset_fn for the columnar task path
    (data/columnar.py): whole-column casts + one deterministic
    permutation instead of per-record map + buffered shuffle.  `seed`
    arrives task/epoch-derived (same on every rank) so the shuffle
    order varies across epochs instead of replaying."""
    from elasticdl_tpu.data.columnar import training_permutation

    features = {
        "dense": np.ascontiguousarray(columns["dense"], np.float32),
        "cat": np.ascontiguousarray(columns["cat"], np.int32),
    }
    labels = columns["label"][:, 0].astype(np.int32)
    if mode == "training":
        perm = training_permutation(len(labels), seed=seed)
        features = {k: v[perm] for k, v in features.items()}
        labels = labels[perm]
    return features, labels


def eval_metrics_fn():
    from model_zoo.wide_and_deep.wide_and_deep import _auc

    return {
        "accuracy": lambda outputs, labels: np.mean(
            (outputs > 0).astype(np.int64) == labels.astype(np.int64)
        ),
        "auc": _auc,
    }


# Fixed-width binary layout of one Criteo record in an ETRF file —
# written by `pack`, parsed by the vectorized columnar path (~1.9M rec/s
# per host; BASELINE.md data-plane section).
def criteo_record_layout():
    from elasticdl_tpu.data.vectorized import RecordLayout

    return RecordLayout([
        ("dense", np.float32, NUM_DENSE),
        ("cat", np.int32, NUM_CAT),
        ("label", np.uint8, 1),
    ])


class CriteoRecordReader(FixedWidthEtrfReader):
    """Shard-addressable reader over Criteo-layout ETRF (one file or a
    directory of shard files — the reference's RecordIO-dir layout)
    using the vectorized buffer path: whole chunks parse into columnar
    numpy in one pass, records yield as cheap row views — no per-record
    byte objects or struct unpacking."""

    def __init__(self, path: str, **kwargs):
        super().__init__(path, **kwargs)
        self._layout = criteo_record_layout()

    def layout(self):
        return self._layout

    def _row(self, cols, i):
        return (
            {"dense": cols["dense"][i], "cat": cols["cat"][i]},
            np.int32(cols["label"][i, 0]),
        )


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name is not None:
        return datasets.synthetic_ctr_reader(
            n=params.get("n", 4096),
            num_dense=NUM_DENSE,
            num_categorical=NUM_CAT,
            vocab_size=params.get("vocab", VOCAB),
            seed=params.get("seed", 0),
            shard_name="criteo-synth",
        )
    from elasticdl_tpu.data.reader import is_etrf_dir

    path = data_path.removeprefix("recordio:")
    if path.endswith(".etrf") or is_etrf_dir(path):
        return CriteoRecordReader(path)
    return None
