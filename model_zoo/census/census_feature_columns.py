"""Census Wide&Deep declared through the feature-column glue.

Parity: the reference's census_model_sqlflow variant, which builds the
same model from feature columns (numeric_column / bucketized_column /
categorical_column_with_* / crossed_column / embedding_column) instead of
hand-wired preprocessing calls — the schema is declared ONCE and both the
input pipeline and the embedding-table sizes fall out of it.

The sibling `census_wide_deep.py` is the hand-wired version of the same
model; this module is the declarative one.  Both consume the same raw
synthetic census records.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.parallel import sparse_optim
from elasticdl_tpu.preprocessing import Normalizer
from elasticdl_tpu.preprocessing.feature_column import (
    FeatureLayer,
    bucketized_column,
    categorical_column_with_hash_bucket,
    categorical_column_with_vocabulary_list,
    crossed_column,
    embedding_column,
    numeric_column,
)
from model_zoo import datasets

# ---- the schema, declared once ----------------------------------------

AGE = numeric_column("age", Normalizer.from_stats(40.0, 15.0))
GAIN = numeric_column("capital_gain", Normalizer.from_stats(3000.0, 8000.0))
HOURS = numeric_column("hours_per_week", Normalizer.from_stats(40.0, 12.0))

EDUCATION = categorical_column_with_vocabulary_list(
    "education", datasets.CENSUS_EDUCATION, num_oov_indices=1
)
WORKCLASS = categorical_column_with_vocabulary_list(
    "workclass", datasets.CENSUS_WORKCLASS, num_oov_indices=1
)
OCCUPATION = categorical_column_with_hash_bucket("occupation", 64)
AGE_BUCKETS = bucketized_column(
    AGE, [18, 25, 30, 35, 40, 45, 50, 55, 60, 65]
)
EDU_X_OCC = crossed_column(["education", "occupation"], 128)

FEATURES = FeatureLayer(
    [
        AGE,
        GAIN,
        HOURS,
        embedding_column(EDUCATION, 8),
        embedding_column(WORKCLASS, 8),
        embedding_column(OCCUPATION, 8),
        embedding_column(AGE_BUCKETS, 8),
        embedding_column(EDU_X_OCC, 8),
    ]
)


class CensusFeatureColumnModel(nn.Module):
    hidden: int = 32

    @nn.compact
    def __call__(self, features, train: bool = False):
        vocab, dim = FEATURES.embedding_specs()["default"]
        wide = Embedding(vocab, 1, combiner="sum", name="wide_embedding")(
            features["cat"]
        )[..., 0]
        deep_emb = Embedding(vocab, dim, name="deep_embedding")(
            features["cat"]
        )
        deep_in = jnp.concatenate(
            [deep_emb.reshape((deep_emb.shape[0], -1)), features["dense"]],
            axis=-1,
        )
        x = nn.relu(nn.Dense(self.hidden)(deep_in))
        return wide + nn.Dense(1)(x)[..., 0]  # logit


def custom_model(hidden: int = 32):
    return CensusFeatureColumnModel(hidden=hidden)


def loss(labels, predictions):
    return optax.sigmoid_binary_cross_entropy(
        predictions, labels.astype(jnp.float32)
    ).mean()


def optimizer(lr: float = 0.01):
    return optax.adam(lr)


def embedding_optimizer(lr: float = 0.01):
    return sparse_optim.adam(lr)


def dataset_fn(dataset, mode, metadata):
    def parse(record):
        raw, label = record
        batch = {k: np.asarray([v]) for k, v in raw.items()}
        inputs = FEATURES(batch)
        return (
            {k: v[0] for k, v in inputs.items()},
            np.int32(label),
        )

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(2048, seed=0)
    return dataset


def eval_metrics_fn():
    from model_zoo.wide_and_deep.wide_and_deep import _auc

    return {
        "accuracy": lambda outputs, labels: np.mean(
            (outputs > 0).astype(np.int64) == labels.astype(np.int64)
        ),
        "auc": _auc,
    }


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name != "census":
        return None
    return datasets.synthetic_census_reader(
        n=params.get("n", 4096), seed=params.get("seed", 0)
    )
