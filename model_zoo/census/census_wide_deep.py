"""Census Wide&Deep over RAW features — the preprocessing-layer showcase.

Parity: the reference's census model built on elasticdl_preprocessing
(model_zoo/census_model_sqlflow: feature-column glue over Hashing /
IndexLookup / Discretization / Normalizer / ConcatenateWithOffset /
RoundIdentity).  Records arrive as raw strings + unscaled floats
(datasets.synthetic_census_reader) and every transform the reference
library provides runs on the way in:

HOST (dataset_fn — strings can't enter a TPU program):
  education -> IndexLookup(vocab)      workclass -> IndexLookup(vocab)
  occupation -> Hashing(64 bins)
DEVICE (inside the jitted model — pure jnp, fuses with the matmuls):
  age -> Discretization(bins)          hours -> RoundIdentity(100)
  capital_gain -> Normalizer           all ids -> ConcatenateWithOffset
                                       -> ONE shared sharded Embedding

The same transform objects serve both training's dataset_fn and serving
(train==serve consistency — asserted in tests/test_preprocessing.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.layers import Embedding
from elasticdl_tpu.parallel import sparse_optim
from elasticdl_tpu.preprocessing import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    Normalizer,
    RoundIdentity,
)
from model_zoo import datasets

# ---- HOST transforms (module-level singletons: one source of truth for
# training AND serving) ------------------------------------------------

EDUCATION_LOOKUP = IndexLookup(datasets.CENSUS_EDUCATION, num_oov_indices=1)
WORKCLASS_LOOKUP = IndexLookup(datasets.CENSUS_WORKCLASS, num_oov_indices=1)
OCCUPATION_HASH = Hashing(num_bins=64)

# ---- DEVICE transforms ------------------------------------------------

AGE_BUCKETS = Discretization(
    [18, 25, 30, 35, 40, 45, 50, 55, 60, 65]
)
HOURS_ID = RoundIdentity(max_value=100)
GAIN_NORM = Normalizer.from_stats(mean=3000.0, std=8000.0)

# One shared table: each feature family offset into a disjoint id range.
ID_SPACES = ConcatenateWithOffset(
    [
        EDUCATION_LOOKUP.vocab_size,
        WORKCLASS_LOOKUP.vocab_size,
        OCCUPATION_HASH.num_bins,
        AGE_BUCKETS.num_bins,
        HOURS_ID.max_value,
    ]
)


class CensusWideDeep(nn.Module):
    embedding_dim: int = 8
    hidden: int = 32

    @nn.compact
    def __call__(self, features, train: bool = False):
        # Device-side preprocessing: traced into the same XLA program as
        # the model body.
        age_ids = AGE_BUCKETS(features["age"])
        hour_ids = HOURS_ID(features["hours_per_week"])
        gain = GAIN_NORM(features["capital_gain"])[:, None]
        ids = ID_SPACES(
            [
                features["edu_id"],
                features["work_id"],
                features["occ_id"],
                age_ids,
                hour_ids,
            ]
        )
        total = ID_SPACES.total_id_space

        wide = Embedding(total, 1, combiner="sum", name="wide_embedding")(
            ids
        )[..., 0]
        deep_emb = Embedding(
            total, self.embedding_dim, name="deep_embedding"
        )(ids)
        deep_in = jnp.concatenate(
            [deep_emb.reshape((deep_emb.shape[0], -1)), gain], axis=-1
        )
        x = nn.relu(nn.Dense(self.hidden)(deep_in))
        deep = nn.Dense(1)(x)[..., 0]
        return wide + deep  # logit


def custom_model(embedding_dim: int = 8, hidden: int = 32):
    return CensusWideDeep(embedding_dim=embedding_dim, hidden=hidden)


def preprocess_record(raw: dict) -> dict:
    """Raw census dict -> model features (host transforms applied).  Used
    by dataset_fn for training and directly by serving callers — the SAME
    code path, which is the whole point of the preprocessing library."""
    return {
        "edu_id": EDUCATION_LOOKUP(np.asarray([raw["education"]]))[0],
        "work_id": WORKCLASS_LOOKUP(np.asarray([raw["workclass"]]))[0],
        "occ_id": OCCUPATION_HASH(np.asarray([raw["occupation"]], object))[0],
        "age": np.float32(raw["age"]),
        "hours_per_week": np.float32(raw["hours_per_week"]),
        "capital_gain": np.float32(raw["capital_gain"]),
    }


def loss(labels, predictions):
    return optax.sigmoid_binary_cross_entropy(
        predictions, labels.astype(jnp.float32)
    ).mean()


def optimizer(lr: float = 0.01):
    return optax.adam(lr)


def embedding_optimizer(lr: float = 0.01):
    return sparse_optim.adam(lr)


def dataset_fn(dataset, mode, metadata):
    def parse(record):
        raw, label = record
        return preprocess_record(raw), np.int32(label)

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(2048, seed=0)
    return dataset


def eval_metrics_fn():
    from model_zoo.wide_and_deep.wide_and_deep import _auc

    return {
        "accuracy": lambda outputs, labels: np.mean(
            (outputs > 0).astype(np.int64) == labels.astype(np.int64)
        ),
        "auc": _auc,
    }


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name != "census":
        return None
    return datasets.synthetic_census_reader(
        n=params.get("n", 4096), seed=params.get("seed", 0)
    )
