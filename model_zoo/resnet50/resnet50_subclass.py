"""ResNet-50 ImageNet — model-zoo contract, JAX/flax body.

Parity: model_zoo/resnet50_subclass/ in the reference (a Keras subclass
ResNet-50 for ImageNet; BASELINE config 5 and the second headline metric,
`resnet50_images_per_sec_per_chip`).  Same contract functions, TPU-first
body:

- Bottleneck v1.5 architecture (stride-2 on the 3x3 conv, the variant
  every published ImageNet benchmark uses).
- bfloat16 compute / float32 params+BN statistics — the standard TPU
  mixed-precision recipe; all convs lower onto the MXU.
- Batch-norm state rides the TrainState's mutable collections exactly
  like the CIFAR-10 config (worker/trainer.py handles any mutable
  collection generically).
- `synthetic://imagenet?n=N` data paths serve shape-correct learnable
  synthetic ImageNet (no network egress in CI), matching the reference's
  practice of benchmarking config 5 with synthetic inputs.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.data.reader import FixedWidthEtrfReader
from model_zoo import datasets

Dtype = Any

IMAGE_SIZE = 224
NUM_CLASSES = 1000


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (v1.5:
    stride lives on the 3x3)."""

    filters: int
    strides: int = 1
    dtype: Dtype = jnp.bfloat16
    norm_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.norm_dtype,
        )
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale so each block starts as identity
        # (the standard ResNet-50 trainability trick).
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


# ImageNet channel statistics on the 0-255 uint8 scale (device-side
# normalization — see ResNet50.normalize).
IMAGENET_MEAN = (0.485 * 255, 0.456 * 255, 0.406 * 255)
IMAGENET_STD = (0.229 * 255, 0.224 * 255, 0.225 * 255)


class ResNet50(nn.Module):
    num_classes: int = NUM_CLASSES
    dtype: Dtype = jnp.bfloat16
    norm_dtype: Dtype = jnp.float32
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    # Device-side input normalization (round 5): the host stages RAW
    # uint8 pixels — half the host->device bytes of bf16, a quarter of
    # f32, and no per-pixel float math on the host — and the
    # (x - mean)/std here runs in compute dtype, fusing into the first
    # conv's input cast (XLA; cost is one elementwise pass the input
    # read already pays).  Inputs are expected on the 0-255 scale.
    normalize: bool = True
    # Stem note: the standard TPU space-to-depth transform (fold 2x2
    # patches -> [B,112,112,12], 4x4 unstrided conv) was MEASURED on the
    # v5e in round 3 and LOST: 2,102 img/s vs 2,665 for the plain 7x7/s2
    # stem (BASELINE.md roofline section).  The step is activation-
    # bandwidth-bound, not stem-bound, so the extra fold relayout costs
    # more than the lane-packing saves.  Don't re-add without new
    # evidence.

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.normalize:
            mean = jnp.asarray(IMAGENET_MEAN, self.dtype)
            std = jnp.asarray(IMAGENET_STD, self.dtype)
            x = (x - mean) / std
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.norm_dtype,
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, blocks in enumerate(self.stage_sizes):
            filters = 64 * (2 ** stage)
            for block in range(blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    filters, strides, self.dtype, self.norm_dtype
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def custom_model(num_classes: int = NUM_CLASSES, use_bf16: bool = True):
    dtype = jnp.bfloat16 if use_bf16 else jnp.float32
    # norm_dtype follows the compute dtype: flax BatchNorm keeps scale/
    # bias/running-stats in f32 regardless (verified), and bf16 BN compute
    # measured +22% step throughput on the v5e (BASELINE.md) — the
    # standard TPU recipe.
    return ResNet50(num_classes=num_classes, dtype=dtype, norm_dtype=dtype)


def loss(labels, predictions):
    return optax.softmax_cross_entropy_with_integer_labels(
        predictions.astype(jnp.float32), labels.astype(jnp.int32)
    ).mean()


def optimizer(lr: float = 0.1):
    return optax.sgd(lr, momentum=0.9, nesterov=True)


# Per-call seed counter for the per-record path's augmentation: every
# dataset_fn call (one per task materialization) draws a fresh seed, so
# crops/flips vary across tasks and epochs — the per-record twin of the
# columnar path's task/epoch-derived seed.  Deterministic across ranks
# because lockstep workers materialize the same broadcast tasks in the
# same order (and reset together on world re-formation).
_DATASET_FN_CALLS = [0]


def dataset_fn(dataset, mode, metadata):
    # The host stays in uint8: normalization happens on device (the
    # model's `normalize` head).  SQUARE records stored larger than the
    # train size get the SAME crop semantics as the columnar fast path
    # (random crop+flip in training, center crop in eval) — the two
    # paths must feed identical shapes or a job would silently change
    # geometry with its reader's capabilities.  Non-square images (a
    # custom reader's) pass through untouched, as before round 5.
    from elasticdl_tpu.data import image as image_plane

    _DATASET_FN_CALLS[0] += 1
    rng = np.random.default_rng(_DATASET_FN_CALLS[0])

    def parse(record):
        image, label = record
        image = np.ascontiguousarray(image, np.uint8)
        square = image.ndim == 3 and image.shape[0] == image.shape[1]
        if square:
            crop = min(IMAGE_SIZE, image.shape[0])
            if mode == "training":
                image = image_plane.random_crop_flip(
                    image[None], crop, rng
                )[0]
            elif image.shape[0] > crop:
                image = image_plane.center_crop(image[None], crop)[0]
        return image, np.int32(label)

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda outputs, labels: np.mean(
            np.argmax(outputs, axis=1) == labels.astype(np.int64)
        ),
        "loss": lambda outputs, labels: float(
            loss(jnp.asarray(labels), jnp.asarray(outputs))
        ),
    }


# Stored record size for the ETRF image plane: images are packed
# slightly larger than the train crop (the record-cache equivalent of
# ImageNet's train-time crop jitter); random_crop_flip takes 256 -> 224.
IMAGE_STORE_SIZE = 256


def columnar_dataset_fn(columns, mode, metadata, seed: int = 0):
    """Vectorized counterpart of dataset_fn for the columnar task path:
    the ETRF buffer parse hands [n, S*S*3] uint8 rows; reshape is a
    view, training applies one permutation + the uint8 crop/flip
    augmentation (elasticdl_tpu/data/image.py) for the whole task, eval
    center-crops deterministically.  Everything stays uint8 — the model
    normalizes on device.  `seed` arrives task/epoch-derived from
    materialize_columnar_task (identical on every rank, different per
    task and epoch) so crops/flips don't replay bit-identically across
    epochs."""
    from elasticdl_tpu.data import image as image_plane

    flat = columns["image"]
    n = len(flat)
    size = int(round((flat.shape[1] // 3) ** 0.5))
    images = flat.reshape((n, size, size, 3))
    labels = columns["label"][:, 0].astype(np.int32)
    # Records smaller than the train size pass through at their own
    # size (the architecture is size-agnostic; tiny CI fixtures rely on
    # this) — production 256-records crop to 224.
    crop = min(IMAGE_SIZE, size)
    if mode == "training":
        from elasticdl_tpu.data.columnar import training_permutation

        perm = training_permutation(n, seed=seed)
        # The permutation rides the crop's per-sample gather (`order=`)
        # — a separate images[perm] pass would copy the full stored-size
        # array (hundreds of MB per task) just to reorder it.
        images = image_plane.random_crop_flip(
            images, crop, np.random.default_rng(seed), order=perm
        )
        labels = labels[perm]
    elif size != crop:
        images = image_plane.center_crop(images, crop)
    return images, labels


class ImageRecordReader(FixedWidthEtrfReader):
    """Shard-addressable reader over image-ETRF (one file or a
    directory of shard files; fixed-size uint8 records, data/image.py
    layout) using the vectorized buffer path — the vision twin of
    deepfm's CriteoRecordReader, so the collective worker's task
    pipeline (shards, columnar fast path, per-record fallback) works
    unchanged.

    copy_columns=False: image columns go straight into the crop's
    gather (columnar_dataset_fn), so the defensive parse copy would be
    a wasted full pass over ~150 KB/record.  A 1 GiB chunk budget
    (matching the worker's staged-bytes cap scale) delivers a whole
    task as one buffer — no concatenate pass, half the peak memory."""

    copy_columns = False
    columnar_chunk_bytes = 1 << 30

    def __init__(self, path: str, size: int = 0, **kwargs):
        super().__init__(path, **kwargs)
        # Self-describing: the fixed record width encodes the stored
        # image size (S*S*3 + 4 label bytes), so readers on any host
        # (cluster worker pods included) need no side-channel config.
        # All shards of a directory must share one stored size — a
        # mismatched shard fails loudly in parse_buffer's width check.
        self._size = size or self._infer_size(self._files()[0])
        from elasticdl_tpu.data.image import image_record_layout

        self._layout = image_record_layout(self._size)

    @staticmethod
    def _infer_size(path: str) -> int:
        from elasticdl_tpu.data import recordfile

        first = next(iter(recordfile.read_range(path, 0, 1)))
        size = int(round(((len(first) - 4) // 3) ** 0.5))
        if size * size * 3 + 4 != len(first):
            raise ValueError(
                f"{path}: {len(first)}B records are not square uint8 "
                "HWC images + int32 label (data/image.py layout)"
            )
        return size

    def layout(self):
        return self._layout

    def _row(self, cols, i):
        s = self._size
        return (
            cols["image"][i].reshape((s, s, 3)),
            np.int32(cols["label"][i, 0]),
        )


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name is not None:
        return datasets.synthetic_imagenet_reader(
            n=params.get("n", 1024),
            seed=params.get("seed", 0),
            image_size=params.get("size", IMAGE_SIZE),
            num_classes=params.get("classes", NUM_CLASSES),
        )
    from elasticdl_tpu.data.reader import is_etrf_dir

    path = data_path.removeprefix("recordio:")
    if path.endswith(".etrf") or is_etrf_dir(path):
        return ImageRecordReader(path, **kwargs)
    return None
