"""ResNet-50 ImageNet — model-zoo contract, JAX/flax body.

Parity: model_zoo/resnet50_subclass/ in the reference (a Keras subclass
ResNet-50 for ImageNet; BASELINE config 5 and the second headline metric,
`resnet50_images_per_sec_per_chip`).  Same contract functions, TPU-first
body:

- Bottleneck v1.5 architecture (stride-2 on the 3x3 conv, the variant
  every published ImageNet benchmark uses).
- bfloat16 compute / float32 params+BN statistics — the standard TPU
  mixed-precision recipe; all convs lower onto the MXU.
- Batch-norm state rides the TrainState's mutable collections exactly
  like the CIFAR-10 config (worker/trainer.py handles any mutable
  collection generically).
- `synthetic://imagenet?n=N` data paths serve shape-correct learnable
  synthetic ImageNet (no network egress in CI), matching the reference's
  practice of benchmarking config 5 with synthetic inputs.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from model_zoo import datasets

Dtype = Any

IMAGE_SIZE = 224
NUM_CLASSES = 1000


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck with projection shortcut (v1.5:
    stride lives on the 3x3)."""

    filters: int
    strides: int = 1
    dtype: Dtype = jnp.bfloat16
    norm_dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.norm_dtype,
        )
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale so each block starts as identity
        # (the standard ResNet-50 trainability trick).
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(
                self.filters * 4, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet50(nn.Module):
    num_classes: int = NUM_CLASSES
    dtype: Dtype = jnp.bfloat16
    norm_dtype: Dtype = jnp.float32
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    # Stem note: the standard TPU space-to-depth transform (fold 2x2
    # patches -> [B,112,112,12], 4x4 unstrided conv) was MEASURED on the
    # v5e in round 3 and LOST: 2,102 img/s vs 2,665 for the plain 7x7/s2
    # stem (BASELINE.md roofline section).  The step is activation-
    # bandwidth-bound, not stem-bound, so the extra fold relayout costs
    # more than the lane-packing saves.  Don't re-add without new
    # evidence.

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-5,
            dtype=self.norm_dtype,
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, blocks in enumerate(self.stage_sizes):
            filters = 64 * (2 ** stage)
            for block in range(blocks):
                strides = 2 if stage > 0 and block == 0 else 1
                x = BottleneckBlock(
                    filters, strides, self.dtype, self.norm_dtype
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def custom_model(num_classes: int = NUM_CLASSES, use_bf16: bool = True):
    dtype = jnp.bfloat16 if use_bf16 else jnp.float32
    # norm_dtype follows the compute dtype: flax BatchNorm keeps scale/
    # bias/running-stats in f32 regardless (verified), and bf16 BN compute
    # measured +22% step throughput on the v5e (BASELINE.md) — the
    # standard TPU recipe.
    return ResNet50(num_classes=num_classes, dtype=dtype, norm_dtype=dtype)


def loss(labels, predictions):
    return optax.softmax_cross_entropy_with_integer_labels(
        predictions.astype(jnp.float32), labels.astype(jnp.int32)
    ).mean()


def optimizer(lr: float = 0.1):
    return optax.sgd(lr, momentum=0.9, nesterov=True)


def dataset_fn(dataset, mode, metadata):
    mean = np.asarray([0.485, 0.456, 0.406], np.float32)
    std = np.asarray([0.229, 0.224, 0.225], np.float32)

    def parse(record):
        image, label = record
        image = (np.asarray(image, np.float32) / 255.0 - mean) / std
        return image, np.int32(label)

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda outputs, labels: np.mean(
            np.argmax(outputs, axis=1) == labels.astype(np.int64)
        ),
        "loss": lambda outputs, labels: float(
            loss(jnp.asarray(labels), jnp.asarray(outputs))
        ),
    }


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name is None:
        return None
    return datasets.synthetic_imagenet_reader(
        n=params.get("n", 1024),
        seed=params.get("seed", 0),
        image_size=params.get("size", IMAGE_SIZE),
        num_classes=params.get("classes", NUM_CLASSES),
    )
