"""MNIST CNN — subclass-style model-zoo variant.

Parity: model_zoo/mnist/mnist_subclass.py in the reference (the Keras
model-SUBCLASSING counterpart of the functional-API DNN: a small conv
net, custom `call`).  Flax's analogue of subclassing is an explicit
`setup()` module (vs the functional `@nn.compact` the sibling uses) —
the contract functions are identical, so both import paths work
anywhere `mnist.mnist_functional_api` does.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from model_zoo.mnist.mnist_functional_api import (  # noqa: F401
    custom_data_reader,
    dataset_fn,
    eval_metrics_fn,
    loss,
    optimizer,
)


class MnistCNN(nn.Module):
    """Conv net in setup() style (the reference subclass model was a
    conv/pool stack, unlike the functional DNN)."""

    hidden_dim: int = 64

    def setup(self):
        self.conv1 = nn.Conv(16, kernel_size=(3, 3))
        self.conv2 = nn.Conv(32, kernel_size=(3, 3))
        self.dense1 = nn.Dense(self.hidden_dim)
        self.head = nn.Dense(10)

    def __call__(self, x, train: bool = False):
        if x.ndim == 3:
            x = x[..., None]  # [B, 28, 28] -> [B, 28, 28, 1]
        x = nn.relu(self.conv1(x))
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = nn.relu(self.conv2(x))
        x = nn.avg_pool(x, window_shape=(2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(self.dense1(x))
        return self.head(x)


def custom_model(hidden_dim: int = 64):
    return MnistCNN(hidden_dim=hidden_dim)
