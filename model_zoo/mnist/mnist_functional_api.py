"""MNIST DNN — model-zoo contract, JAX/flax body.

Parity: model_zoo/mnist/mnist_functional_api.py in the reference (a Keras
functional-API DNN with the contract functions custom_model / loss /
optimizer / dataset_fn / eval_metrics_fn).  Same function names, TPU-first
bodies: a flax module compiled by XLA, optax optimizer, numpy host pipeline.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from model_zoo import datasets


class MnistDNN(nn.Module):
    hidden_dim: int = 128

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden_dim)(x)
        x = nn.relu(x)
        x = nn.Dense(self.hidden_dim // 2)(x)
        x = nn.relu(x)
        return nn.Dense(10)(x)


def custom_model(hidden_dim: int = 128):
    return MnistDNN(hidden_dim=hidden_dim)


def loss(labels, predictions):
    return optax.softmax_cross_entropy_with_integer_labels(
        predictions, labels.astype(jnp.int32)
    ).mean()


def optimizer(lr: float = 0.1):
    return optax.sgd(lr, momentum=0.9)


def dataset_fn(dataset, mode, metadata):
    def parse(record):
        image, label = record
        return np.asarray(image, np.float32) / 255.0, np.int32(label)

    dataset = dataset.map(parse)
    if mode == "training":
        dataset = dataset.shuffle(1024, seed=0)
    return dataset


def eval_metrics_fn():
    return {
        "accuracy": lambda outputs, labels: np.mean(
            np.argmax(outputs, axis=1) == labels.astype(np.int64)
        ),
        "loss": lambda outputs, labels: float(
            loss(jnp.asarray(labels), jnp.asarray(outputs))
        ),
    }


def custom_data_reader(data_path: str, **kwargs):
    name, params = datasets.parse_synthetic_path(data_path)
    if name is None:
        return None  # fall through to the standard readers
    return datasets.synthetic_mnist_reader(
        n=params.get("n", 4096), seed=params.get("seed", 0)
    )
