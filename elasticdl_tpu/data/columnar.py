"""Columnar task materialization — the no-per-record-Python data path.

Parity: the reference's worker materializes each dynamic-sharding task as
a tf.data pipeline of per-record parses (†worker/worker.py task loop over
†data/reader/).  On a 1-core TPU host that per-record interpreter layer
caps the whole job: the device consumes ~1M samples/s (BASELINE.md) while
a Python `for record in task` loop tops out at a few hundred k/s.

This module keeps the task contract (same [task.start, task.end) range,
deterministic per (task, mode) on every rank — the lockstep requirement
of the collective worker) but carries the data as COLUMN arrays end to
end: readers that implement `read_columns(task)` hand back columnar
chunks straight from the file codec (e.g. ETRF parse_buffer output), the
model's `columnar_dataset_fn` transforms whole columns (vectorized
shuffle included), and batches are row-range VIEWS — zero per-record
work anywhere on the hot path.

Both layers are optional: a reader without `read_columns` or a model
without `columnar_dataset_fn` falls back to the per-record path
unchanged (reference-parity behaviour).
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

Tree = Any  # nested dict/tuple of np.ndarray, all sharing axis-0 length


def _tree_len(tree: Tree) -> int:
    if isinstance(tree, dict):
        return _tree_len(next(iter(tree.values())))
    if isinstance(tree, (tuple, list)):
        return _tree_len(tree[0])
    return len(tree)


def _tree_slice(tree: Tree, lo: int, hi: int) -> Tree:
    if isinstance(tree, dict):
        return {k: _tree_slice(v, lo, hi) for k, v in tree.items()}
    if isinstance(tree, (tuple, list)):
        return type(tree)(_tree_slice(v, lo, hi) for v in tree)
    return tree[lo:hi]


class ColumnarTask:
    """One task's records as (features_tree, labels_or_None), columnar."""

    def __init__(self, features: Tree, labels: Optional[np.ndarray]):
        self.features = features
        self.labels = labels
        self.n = _tree_len(features)
        if labels is not None and len(labels) != self.n:
            raise ValueError(
                f"labels length {len(labels)} != features length {self.n}"
            )

    def slice(self, lo: int, hi: int) -> Tuple[Tree, Optional[np.ndarray]]:
        """Row-range views [lo, hi) (no copies)."""
        return (
            _tree_slice(self.features, lo, hi),
            None if self.labels is None else self.labels[lo:hi],
        )


def materialize_columnar_task(
    reader,
    task,
    columnar_dataset_fn: Optional[Callable],
    mode: str,
    metadata,
    parse_pool=None,
) -> Optional[ColumnarTask]:
    """Build a ColumnarTask, or None when either side lacks the columnar
    surface (caller falls back to the per-record dataset path).  A
    `parse_pool` (data/pipeline.ParsePool) fans chunk parsing across
    host cores for readers that accept it — older readers without the
    parameter are called the classic way."""
    read_columns = getattr(reader, "read_columns", None)
    if read_columns is None or columnar_dataset_fn is None:
        return None
    if (
        parse_pool is not None
        and "parse_pool" in inspect.signature(read_columns).parameters
    ):
        chunks = list(read_columns(task, parse_pool=parse_pool))
    else:
        chunks = list(read_columns(task))
    if not chunks:
        return None
    if len(chunks) == 1:
        columns: Dict[str, np.ndarray] = chunks[0]
    else:
        columns = {
            k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]
        }
    kwargs = {}
    if "seed" in inspect.signature(columnar_dataset_fn).parameters:
        # Task-identity-derived randomness for transforms that opt in
        # (shuffle order, image crop/flip): deterministic across ranks
        # (every rank sees identical task fields — lockstep collectives
        # require it) but VARIES across tasks and epochs — a fixed seed
        # would replay bit-identical augmentation every epoch.
        kwargs["seed"] = (
            1_000_003 * int(getattr(task, "epoch", 0))
            + 31 * int(getattr(task, "start", 0))
            + int(getattr(task, "end", 0))
        ) % (2**31)
    features, labels = columnar_dataset_fn(columns, mode, metadata, **kwargs)
    return ColumnarTask(features, labels)


def training_permutation(n: int, seed: int = 0) -> np.ndarray:
    """Deterministic full-range shuffle for columnar training transforms
    (the per-record path's buffered dataset.shuffle equivalent) — same
    permutation on every rank, which lockstep collectives require."""
    return np.random.RandomState(seed).permutation(n)
