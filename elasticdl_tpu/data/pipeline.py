"""Async staging engine — the host-bound killer (ROADMAP item 4).

BENCH_r04's e2e decomposition shows the strict-mode DeepFM chip number
(~973k samples/s/chip) collapsing to ~276k end to end with `bound:
host-core` and `host_parse_frac 0.685`: parse, stage, and H2D all
serialize with device compute.  This module is the shared machinery that
breaks the serialization, used by both the training step loops
(worker/collective_worker.py, worker/worker.py) and the serving
micro-batcher (serving/batcher.py):

  ParsePool        multi-core host parse: `parse_buffer` (and any other
                   pure chunk->columns fn) runs on worker threads off the
                   step loop's critical path.  numpy releases the GIL for
                   the big copies/casts, so threads scale with cores
                   without the pickling tax of processes.  Ordering is
                   deterministic (results reassemble by submission index)
                   and errors propagate in submission order, so a
                   jittered pool is indistinguishable from serial `map`.

  Prefetcher       bounded background readahead over any batch iterator:
                   the producer thread runs parse + batch slicing for
                   item N+1..N+k while the step loop dispatches N.  The
                   queue bound is the backpressure contract — a slow
                   device stalls the producer instead of growing host
                   memory without limit.  Per-item production time and
                   consumer blocked time are both clocked so step anatomy
                   can book the *hidden* portion as overlap credit
                   instead of silently vanishing it.

  StagingPipeline  double-buffered device staging: while window N's
                   dispatch is outstanding on the device queue, window
                   N+1's `stage_window`/`stage_batch` (non-blocking
                   `device_put` under JAX async dispatch) books as
                   `overlap_s`, not `stage` — the ledger tells the truth
                   about what actually serialized with compute.

  pad_and_stage    the serving pad-to-bucket + optional stage step, so
                   training and serving share one staging implementation
                   (`bucket_for`/`pad_features` live here now; the
                   batcher re-exports them).

Elastic discipline: pipelines are scoped to ONE task.  Churn, rescale,
and checkpoint all happen at task/rendezvous boundaries in this
codebase, and `Prefetcher.close()` / `ParsePool.close()` drain
synchronously — no stale in-flight batch ever crosses a rendezvous
generation (tests/test_pipeline.py exercises the churn path).

Donation note: staged buffers feed `train_window`/`train_step_staged`,
which donate only the STATE argument (position 0); batches are never
donated, so read-ahead staging cannot alias a donated buffer.  The
analyzer's `async-staging-discipline` rule (analysis/jax_rules.py)
machine-checks the hazard for code that *does* stage into a donated
position.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import (
    Any, Callable, Dict, Iterable, Iterator, Optional, Sequence, Tuple,
)

import numpy as np

PIPELINE_MODES = ("sync", "async")


class PipelineConfig:
    """Knobs for the async staging engine, threadable from CLI args.

    mode            "sync" keeps the reference-parity serial step loop;
                    "async" turns on parse pool + prefetch + overlap
                    booking.
    parse_workers   host parse pool size (0 = parse inline on the
                    producer thread; the pool is still bypassed
                    entirely in sync mode).
    max_inflight    bounded lookahead: max batches buffered between the
                    producer and the step loop (backpressure bound).
    dispatch_depth  how many windows may be in flight on the device
                    queue before staging stops earning overlap credit.
    """

    def __init__(
        self,
        mode: str = "sync",
        parse_workers: int = 0,
        max_inflight: int = 2,
        dispatch_depth: int = 2,
    ):
        if mode not in PIPELINE_MODES:
            raise ValueError(
                f"pipeline mode {mode!r} not in {PIPELINE_MODES}"
            )
        self.mode = mode
        self.parse_workers = max(0, int(parse_workers))
        self.max_inflight = max(1, int(max_inflight))
        self.dispatch_depth = max(1, int(dispatch_depth))

    @property
    def is_async(self) -> bool:
        return self.mode == "async"

    @classmethod
    def from_args(cls, args) -> "PipelineConfig":
        return cls(
            mode=getattr(args, "pipeline", "sync"),
            parse_workers=getattr(args, "parse_pool_workers", 0),
            max_inflight=getattr(args, "pipeline_inflight", 2),
            dispatch_depth=getattr(args, "dispatch_depth", 2),
        )

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return (
            f"PipelineConfig(mode={self.mode!r}, "
            f"parse_workers={self.parse_workers}, "
            f"max_inflight={self.max_inflight}, "
            f"dispatch_depth={self.dispatch_depth})"
        )


class _ImapState:
    """Per-imap reassembly buffer shared between submitter and workers."""

    __slots__ = ("cond", "results")

    def __init__(self):
        self.cond = threading.Condition()
        self.results: Dict[int, Any] = {}


class ParsePool:
    """Ordered, bounded thread-pool map for host parse work.

    `imap(fn, iterable)` yields `fn(item)` in submission order while up
    to `lookahead` items execute concurrently on `workers` threads.
    Exceptions re-raise at the yield position of the item that failed —
    exactly where serial `map` would have raised — so downstream code
    cannot observe reordering even under failure.  With `workers == 0`
    the pool degrades to plain serial `map` (no threads at all).
    """

    _CLOSE = object()

    def __init__(self, workers: int):
        self.workers = max(0, int(workers))
        self._tasks: "queue.Queue" = queue.Queue()
        self._threads = [
            threading.Thread(
                target=self._worker, name=f"parse-pool-{i}", daemon=True
            )
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()
        self._closed = False

    def _worker(self) -> None:
        while True:
            task = self._tasks.get()
            if task is self._CLOSE:
                return
            seq, fn, item, state = task
            try:
                out = (True, fn(item))
            except BaseException as exc:  # propagated to the consumer
                out = (False, exc)
            with state.cond:
                state.results[seq] = out
                state.cond.notify_all()

    def imap(
        self,
        fn: Callable[[Any], Any],
        iterable: Iterable[Any],
        lookahead: Optional[int] = None,
    ) -> Iterator[Any]:
        if self.workers == 0:
            yield from map(fn, iterable)
            return
        if self._closed:
            raise RuntimeError("ParsePool is closed")
        if lookahead is None:
            lookahead = 2 * self.workers
        lookahead = max(1, int(lookahead))
        state = _ImapState()
        it = iter(iterable)
        submitted = 0
        next_yield = 0
        exhausted = False
        while True:
            # Keep the pool fed up to the lookahead bound; the bound is
            # what keeps host memory flat when the consumer is slow.
            while not exhausted and submitted - next_yield < lookahead:
                try:
                    item = next(it)
                except StopIteration:
                    exhausted = True
                    break
                self._tasks.put((submitted, fn, item, state))
                submitted += 1
            if next_yield >= submitted and exhausted:
                return
            with state.cond:
                while next_yield not in state.results:
                    state.cond.wait()
                ok, value = state.results.pop(next_yield)
            next_yield += 1
            if not ok:
                raise value
            yield value

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for _ in self._threads:
            self._tasks.put(self._CLOSE)
        for t in self._threads:
            t.join()

    def __enter__(self) -> "ParsePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class Prefetcher:
    """Bounded background readahead over an iterator.

    The producer thread pulls from `source` and buffers up to
    `max_inflight` items; `__next__` hands them out in order.  The
    consumer's blocked time (`wait_s`) and the producer's total
    production time (`prod_s`) are both clocked: the step loop books
    `wait_s` as `data_wait` (it really stalled) and
    `max(0, prod_s - wait_s)` as overlap credit (host work that hid
    behind device execution).  `close()` drains synchronously — after it
    returns no producer thread is running and no buffered item will
    ever be observed, which is what lets a churn/rescale/checkpoint
    boundary guarantee no stale batch crosses a rendezvous generation.
    """

    _DONE = object()

    def __init__(self, source: Iterable[Any], max_inflight: int = 2):
        self._queue: "queue.Queue" = queue.Queue(
            maxsize=max(1, int(max_inflight))
        )
        self._source = iter(source)
        self._stop = threading.Event()
        self._exc: Optional[BaseException] = None
        self.prod_s = 0.0
        self.wait_s = 0.0
        self.produced = 0
        self.consumed = 0
        self._finished = False
        self._thread = threading.Thread(
            target=self._produce, name="prefetcher", daemon=True
        )
        self._thread.start()

    def _put(self, item: Any) -> bool:
        """Queue.put that aborts promptly when close() is racing us."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self) -> None:
        try:
            while not self._stop.is_set():
                t0 = time.perf_counter()
                try:
                    item = next(self._source)
                except StopIteration:
                    break
                self.prod_s += time.perf_counter() - t0
                self.produced += 1
                if not self._put(item):
                    return
        except BaseException as exc:  # re-raised at the consumer
            self._exc = exc
        self._put(self._DONE)

    def __iter__(self) -> "Prefetcher":
        return self

    def __next__(self) -> Any:
        if self._finished:
            raise StopIteration
        t0 = time.perf_counter()
        item = self._queue.get()
        self.wait_s += time.perf_counter() - t0
        if item is self._DONE:
            self._finished = True
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            raise StopIteration
        self.consumed += 1
        return item

    @property
    def overlap_s(self) -> float:
        """Producer time hidden behind the consumer's own work."""
        return max(0.0, self.prod_s - self.wait_s)

    def close(self) -> None:
        """Synchronous drain: stop the producer, discard buffered items,
        join.  Safe to call multiple times and mid-iteration."""
        self._stop.set()
        # Unblock a producer stuck on a full queue / a consumer racing.
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._thread.join()
        # Drop anything the producer flushed while we were joining.
        while True:
            try:
                self._queue.get_nowait()
            except queue.Empty:
                break
        self._finished = True

    def __enter__(self) -> "Prefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class StagingPipeline:
    """Double-buffered device staging with honest anatomy booking.

    Under JAX async dispatch, `stage_window`/`stage_batch` issued while
    a previous window is still executing on the device costs no
    step-loop latency — it overlaps.  This wrapper books such staging
    time as overlap credit (`StepAnatomy.note_overlap_seconds`) instead
    of the exclusive `stage` phase whenever at least one dispatch is
    outstanding.  The outstanding count is CAPPED at `dispatch_depth`:
    JAX's own dispatch queue bounds host runahead (a dispatch past the
    queue bound blocks inside the jit call, which the `execute` phase
    clock already books), so older windows beyond the depth are assumed
    retired rather than tracked — `note_synced()` resets the count at
    real host/device sync points (blocking readbacks, task boundaries).
    """

    def __init__(self, anatomy=None, dispatch_depth: int = 2):
        self._anatomy = anatomy
        self._depth = max(1, int(dispatch_depth))
        self._outstanding = 0

    @property
    def outstanding(self) -> int:
        return self._outstanding

    def stage(self, fn: Callable[..., Any], *args: Any) -> Any:
        """Run a trainer staging fn, booking its host time truthfully."""
        t0 = time.perf_counter()
        out = fn(*args)
        dt = time.perf_counter() - t0
        if self._anatomy is not None:
            if self._outstanding > 0:
                self._anatomy.note_overlap_seconds(dt)
            else:
                self._anatomy.note_phase_seconds("stage", dt)
        return out

    def note_dispatched(self) -> None:
        """A window/step was dispatched to the device queue."""
        self._outstanding = min(self._outstanding + 1, self._depth)

    def note_synced(self) -> None:
        """The host observed a device result (blocking readback): the
        device queue is drained, nothing is outstanding."""
        self._outstanding = 0

    def drain(self) -> None:
        """Task/rendezvous boundary: forget in-flight accounting."""
        self._outstanding = 0


# ---------------------------------------------------------------------------
# Shared pad-and-stage step (serving's bucket padding lives here so the
# training and serving planes use one implementation — the batcher
# re-exports these names for its existing callers).


def bucket_sizes(max_batch_size: int) -> Tuple[int, ...]:
    """Power-of-two padding buckets up to (and including) the max batch
    size — the fixed shape set the compiled step may see."""
    if max_batch_size < 1:
        raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
    sizes = []
    size = 1
    while size < max_batch_size:
        sizes.append(size)
        size *= 2
    sizes.append(max_batch_size)
    return tuple(sizes)


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """The smallest bucket holding n rows."""
    for size in buckets:
        if n <= size:
            return size
    return buckets[-1]


def pad_features(features: Dict[str, np.ndarray], rows: int) -> Dict[str, np.ndarray]:
    """Zero-pad every array of a features dict to `rows` along axis 0.
    Id 0 is a valid embedding row, but pad rows' outputs are sliced off
    before any request sees them and model rows are independent."""
    out = {}
    for key, array in features.items():
        array = np.asarray(array)
        if array.shape[0] == rows:
            out[key] = array
            continue
        pad = np.zeros((rows - array.shape[0],) + array.shape[1:], array.dtype)
        out[key] = np.concatenate([array, pad], axis=0)
    return out


def pad_and_stage(
    features: Dict[str, np.ndarray],
    rows: int,
    buckets: Sequence[int],
    stage_fn: Optional[Callable[[Dict[str, np.ndarray]], Any]] = None,
):
    """Serving's pad-to-bucket + optional non-blocking stage step.

    Pads `features` (stacked live rows) to the smallest admitting
    bucket, then — when `stage_fn` is given (typically a partial of
    `jax.device_put` or a trainer/replica stage method) — hands the
    padded batch to it so the H2D transfer is already in flight when
    the execute fn runs.  Returns (staged_or_padded, bucket).
    """
    bucket = bucket_for(rows, buckets)
    padded = pad_features(features, bucket)
    if stage_fn is not None:
        padded = stage_fn(padded)
    return padded, bucket
