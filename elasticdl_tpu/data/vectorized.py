"""Vectorized fixed-width record parsing — the data-plane throughput
lever for binary (ETRF/recordio) datasets.

The per-record Python hop caps a host reader below 1M records/s
(BASELINE.md data-plane section: 828k rec/s through the per-record API
vs 1.94M vectorized); CTR-scale jobs need millions.  For fixed-width
binary records the whole fix is one numpy structured-dtype view: take a
contiguous payload chunk (`recordfile.read_range_buffers`) and view it
as columnar arrays in a single pass — no per-record Python.

Usage (see model_zoo/deepfm's CriteoRecordReader for the production
wiring):

    LAYOUT = RecordLayout([
        ("dense", np.float32, 13),
        ("cat", np.int32, 26),
        ("label", np.uint8, 1),
    ])
    for buf, lengths in recordfile.read_range_buffers(path, start, end):
        columns = LAYOUT.parse_buffer(buf, lengths)  # dict of [n, k]
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


class RecordLayout:
    """Schema of one fixed-width binary record: ordered
    (name, dtype, count) fields, little-endian, packed."""

    def __init__(self, fields: Sequence[Tuple[str, type, int]]):
        if not fields:
            raise ValueError("RecordLayout needs at least one field")
        self.fields = [
            (name, np.dtype(dtype).newbyteorder("<"), int(count))
            for name, dtype, count in fields
        ]
        self._struct = np.dtype(
            [(name, dt, (count,)) for name, dt, count in self.fields]
        )

    @property
    def record_bytes(self) -> int:
        return self._struct.itemsize

    def pack(self, **values) -> bytes:
        """One record dict -> bytes (the writer-side inverse; tests and
        data generators use it)."""
        row = np.zeros((), dtype=self._struct)
        for name, dt, count in self.fields:
            arr = np.asarray(values[name], dt).reshape(count)
            row[name] = arr
        return row.tobytes()

    def parse_batch(self, raw_records: List[bytes]) -> Dict[str, np.ndarray]:
        """Raw payload list -> {field: [n, count] array}, one numpy pass."""
        buf = b"".join(raw_records)
        n, rem = divmod(len(buf), self.record_bytes)
        if rem or n != len(raw_records):
            raise ValueError(
                f"records are not fixed-width {self.record_bytes}B "
                f"(got {len(buf)}B for {len(raw_records)} records)"
            )
        return self.parse_buffer(np.frombuffer(buf, np.uint8))

    def parse_buffer(self, buf, lengths=None, copy=True) -> Dict[str, np.ndarray]:
        """Contiguous payload buffer (np.uint8) -> columnar arrays.

        The zero-Python-per-record path: feed chunks straight from
        `data.recordfile.read_range_buffers`.  `lengths` (when given) is
        validated against the fixed record width.  `copy=False` returns
        views aliasing the (possibly read-only) buffer — for consumers
        that immediately gather into fresh arrays anyway (the image
        plane's crop does), skipping the copy saves a full pass over
        data that can be hundreds of MB per task."""
        buf = np.ascontiguousarray(buf, np.uint8)
        n, rem = divmod(buf.size, self.record_bytes)
        if rem:
            raise ValueError(
                f"buffer size {buf.size} is not a multiple of the "
                f"record width {self.record_bytes}"
            )
        if lengths is not None and (
            len(lengths) != n
            or not (np.asarray(lengths) == self.record_bytes).all()
        ):
            raise ValueError(
                f"records are not fixed-width {self.record_bytes}B"
            )
        table = buf.view(self._struct)
        # Default copies so downstream may mutate.
        wrap = np.array if copy else np.asarray
        return {
            name: wrap(table[name]) for name, _, _ in self.fields
        }
