"""Unbounded stream sources for the continuous train->serve loop.

A *stream* is an append-only record log: offsets are dense integers,
each record carries an **event time** (when the click/impression
happened), and production never ends.  The master's streaming task
dispatcher (master/stream.py) cuts the log into the same shard-task
ranges the bounded dispatcher uses — the stream is the dataset, the
offsets are the shard.

`SyntheticClickStream` is the deterministic test double: production
follows a piecewise-constant **rate schedule** on a virtual timeline the
driver owns (`advance(dt)` — no wall clock anywhere, so a chaos run
replays exactly), and `event_time(offset)` inverts the schedule.  A
mid-run rate spike is one extra schedule phase; a stalled source
(`stream.source` fault site, kind `latency`) shifts *production* without
shifting event times — exactly how a wedged upstream pipe manifests as
event-time lag.

Reading a task's range rides the PR-14 Prefetcher (bounded lookahead,
synchronous close-drain), so worker churn never leaks a stale window
across a rendezvous generation.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.pipeline import Prefetcher

logger = get_logger("data.stream")


class SyntheticClickStream:
    """Deterministic unbounded click stream on a driver-owned timeline.

    `schedule` is a sequence of ``(duration_s, records_per_s)`` phases;
    the LAST phase's rate continues forever (a stream has no end).  All
    timing is virtual: the driver calls `advance(dt)` to move the
    production clock, so availability, event times, and stalls replay
    bit-exactly regardless of host speed.
    """

    def __init__(
        self,
        schedule: Sequence[Tuple[float, float]],
        name: str = "stream",
        label_delay_s: float = 0.0,
    ):
        if not schedule:
            raise ValueError("stream schedule needs at least one phase")
        for duration, rate in schedule:
            if duration < 0 or rate < 0:
                raise ValueError(f"bad schedule phase ({duration}, {rate})")
        if schedule[-1][1] <= 0:
            raise ValueError("final schedule phase must have rate > 0")
        if label_delay_s < 0:
            raise ValueError("label_delay_s must be >= 0")
        self.name = name
        self._schedule: List[Tuple[float, float]] = [
            (float(d), float(r)) for d, r in schedule
        ]
        self._label_delay_s = float(label_delay_s)
        self._elapsed = 0.0
        self._stall_s = 0.0
        self._closed = False

    # -- the driver-owned clock -----------------------------------------

    def advance(self, dt_s: float) -> None:
        """Move the virtual production clock forward."""
        if dt_s < 0:
            raise ValueError("time only moves forward")
        self._elapsed += dt_s
        # Call-count-triggered stall (`stream.source:latency=SECONDS@N`):
        # the Nth advance wedges the source for SECONDS of virtual time.
        spec = faults.fire("stream.source")
        if spec is not None and spec.kind == "latency":
            self.stall(float(spec.arg or 1.0))

    def stall(self, seconds: float) -> None:
        """A wedged upstream pipe: production stops for `seconds` of
        virtual time.  Event times are unaffected — the records were
        already minted upstream, they just arrive late (that is what
        event-time lag measures).  Drivers applying schedule-based
        `stream.source` specs (`faults.due`) call this directly."""
        self._stall_s += float(seconds)
        logger.warning(
            "FAULT INJECTION: stream %s stalled %.3fs (total stall %.3fs)",
            self.name, seconds, self._stall_s,
        )

    @property
    def elapsed_s(self) -> float:
        return self._elapsed

    def close(self) -> None:
        """Bounded-test escape hatch: no records beyond the current
        availability; the dispatcher may then drain and finish."""
        self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    # -- production / event-time math -----------------------------------

    def records_until(self, elapsed_s: float) -> int:
        """Records produced by `elapsed_s` on an unstalled timeline
        (the integral of the rate schedule)."""
        remaining = max(0.0, float(elapsed_s))
        records = 0.0
        for i, (duration, rate) in enumerate(self._schedule):
            last = i == len(self._schedule) - 1
            span = remaining if last else min(remaining, duration)
            records += span * rate
            remaining -= span
            if remaining <= 0:
                break
        return int(records)

    def available(self) -> int:
        """Records that have ARRIVED by now: production shifted by every
        stall so far.  Monotone in elapsed time."""
        return self.records_until(self._elapsed - self._stall_s)

    @property
    def label_delay_s(self) -> float:
        return self._label_delay_s

    def labels_available(self) -> int:
        """Records whose delayed feedback label has ARRIVED by now: the
        label for record `o` lands `label_delay_s` of virtual time after
        the record itself (clicks are attributed late), and a stalled
        source delays the labels with the records.  Monotone, and always
        <= `available()` — the label watermark trails the record
        watermark by construction."""
        return self.records_until(
            self._elapsed - self._stall_s - self._label_delay_s
        )

    def labels_for(
        self,
        lo: int,
        hi: int,
        vocab_size: int,
        fields: Sequence[str] = ("user", "item"),
    ) -> Optional[np.ndarray]:
        """Delayed-feedback labels for offsets [lo, hi): the same
        offset-pure generator family as `synthetic_click_batch`, routed
        through the `stream.labels` fault site (`feedback_labels`) so a
        chaos run can poison (flip) or black out the label feed.  The
        caller owns the watermark discipline — only ask for ranges below
        `labels_available()`."""
        return feedback_labels(
            synthetic_click_batch(lo, hi, vocab_size, fields)
        )

    def event_time(self, offset: int) -> float:
        """Event time (virtual seconds since stream start) of record
        `offset` — the schedule's inverse, stall-independent."""
        offset = max(0, int(offset))
        produced = 0.0
        start = 0.0
        for i, (duration, rate) in enumerate(self._schedule):
            last = i == len(self._schedule) - 1
            phase_records = float("inf") if last else duration * rate
            if offset < produced + phase_records:
                if rate <= 0:
                    return start + duration
                return start + (offset - produced) / rate
            produced += phase_records
            start += duration
        return start

    # -- serialisation (master resume) ----------------------------------

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "schedule": [list(p) for p in self._schedule],
            "label_delay_s": self._label_delay_s,
            "elapsed": self._elapsed,
            "stall_s": self._stall_s,
            "closed": self._closed,
        }

    @classmethod
    def from_json(cls, obj: dict) -> "SyntheticClickStream":
        stream = cls(
            [tuple(p) for p in obj["schedule"]],
            name=obj.get("name", "stream"),
            label_delay_s=float(obj.get("label_delay_s", 0.0)),
        )
        stream._elapsed = float(obj.get("elapsed", 0.0))
        stream._stall_s = float(obj.get("stall_s", 0.0))
        stream._closed = bool(obj.get("closed", False))
        return stream


def synthetic_click_batch(
    lo: int,
    hi: int,
    vocab_size: int,
    fields: Sequence[str] = ("user", "item"),
) -> dict:
    """Deterministic feature batch for offsets [lo, hi): each record's
    ids are a pure function of its offset, so any worker that replays a
    requeued range trains on the identical batch (the at-least-once
    replay contract extends to the data)."""
    offsets = np.arange(int(lo), int(hi), dtype=np.int64)
    return {
        name: ((offsets * (31 + 17 * i) + 7 * i) % vocab_size).astype(
            np.int64
        )
        for i, name in enumerate(fields)
    }


def click_label_rule(features: dict) -> np.ndarray:
    """Deterministic ground-truth click label per row: a pure function
    of the integer feature ids, so it is learnable from the embeddings,
    replayable offline, and IDENTICAL wherever it is evaluated — the
    stream's delayed-feedback channel, `scripts/loadgen.py --labels`,
    and an offline AUC audit of the same joined set all agree
    element-wise.  ~31% positive rate (the `< 30 of 97` residue)."""
    acc = None
    for i, name in enumerate(sorted(features)):
        arr = np.asarray(features[name])
        if not np.issubdtype(arr.dtype, np.integer):
            continue
        ids = arr.astype(np.int64)
        if ids.ndim == 1:
            ids = ids[:, None]
        weights = 13 + 7 * np.arange(ids.shape[-1], dtype=np.int64)
        contrib = (ids * weights).sum(axis=-1) * (1 + i)
        acc = contrib if acc is None else acc + contrib
    if acc is None:
        raise ValueError(
            "click_label_rule needs at least one integer feature array"
        )
    return ((acc % 97) < 30).astype(np.float32)


def feedback_labels(features: dict) -> Optional[np.ndarray]:
    """The label FEED: `click_label_rule` routed through the
    ``stream.labels`` fault site.  kind ``truncate`` -> outage (None:
    no labels arrive for this range this poll); kind ``error`` ->
    poisoned feed (flipped labels — the canary-gate chaos scenario, a
    label-flipped shard entering training)."""
    spec = faults.fire("stream.labels")
    if spec is not None and spec.kind == "truncate":
        logger.warning("FAULT INJECTION: label feed outage (range withheld)")
        return None
    labels = click_label_rule(features)
    if spec is not None and spec.kind == "error":
        logger.warning(
            "FAULT INJECTION: label feed poisoned (labels flipped, %s)",
            spec.arg or "flip",
        )
        labels = (1.0 - labels).astype(labels.dtype)
    return labels


def iter_stream_batches(
    make_batch: Callable[[int, int], object],
    lo: int,
    hi: int,
    batch_size: int,
    prefetch: int = 2,
) -> Iterator[object]:
    """One task range [lo, hi) as a prefetched batch iterator: the
    stream-worker analogue of the bounded pipeline's readahead.  The
    Prefetcher's synchronous close() drain runs on generator close, so a
    churned worker abandoning the range leaves no producer thread and no
    buffered window behind."""

    def windows():
        for start in range(int(lo), int(hi), int(batch_size)):
            yield make_batch(start, min(start + batch_size, int(hi)))

    prefetcher = Prefetcher(windows(), max_inflight=prefetch)
    try:
        for batch in prefetcher:
            yield batch
    finally:
        prefetcher.close()
