"""Image data plane: fixed-width uint8 image records + vectorized
host-side augmentation (round-5 VERDICT #1 — the last BASELINE config
without a file->device proof).

Parity: the reference trains its vision configs from shard-addressable
RecordIO files through the same reader stack as CTR (SURVEY §2.2
†elasticdl/python/data/reader/, §3.3 worker dataset assembly).  The
TPU-first layout decisions, measured against the v5e device rate
(~2,665 img/s => ~390 MB/s of 224^2 uint8 the host must source):

- **Images are stored DECODED, fixed-size, uint8 HWC** — one
  `RecordLayout` field, so a whole ETRF chunk parses into an [n, S*S*C]
  array with a single numpy view (data/vectorized.py), no per-record
  Python and no JPEG decode in the training hot path.  Decode happens
  once at packing time (`write_image_etrf`); re-decoding JPEG per epoch
  costs ~10x the CPU of streaming raw and is the classic host-bound
  trap for TPU input pipelines.  Storage trades ~4x bytes for that CPU
  — the same trade TPU reference pipelines make with decoded caches.
- **Augmentation is uint8, host-side, vectorized**: random crop from
  the stored size (store slightly larger than the train size — the
  record-cache equivalent of ImageNet's crop jitter) plus horizontal
  flip.  Pure memory ops; no float math on the host.
- **Normalization happens ON DEVICE** (the model's first op — see
  model_zoo/resnet50 `normalize`): the host stages raw uint8, halving
  host->device bytes vs bf16 and quartering them vs f32, and the
  device's (x/255 - mean)/std fuses into the first conv's input cast.
"""

from __future__ import annotations

import numpy as np


def image_record_layout(size: int, channels: int = 3):
    """Fixed-width record: [size*size*channels] uint8 image + int32
    label.  Parses at buffer-view speed via RecordLayout."""
    from elasticdl_tpu.data.vectorized import RecordLayout

    return RecordLayout([
        ("image", np.uint8, size * size * channels),
        ("label", np.int32, 1),
    ])


def write_image_etrf(path: str, images: np.ndarray, labels: np.ndarray):
    """Pack [n, S, S, C] uint8 images + [n] labels into one ETRF file.
    Columnar-side assembly (one concatenate, rows split off views) —
    the writer-side mirror of the vectorized parse."""
    from elasticdl_tpu.data import recordfile

    images = np.ascontiguousarray(images, np.uint8)
    n = images.shape[0]
    flat = images.reshape((n, -1))
    lab = np.ascontiguousarray(labels, np.int32).reshape((n, 1))
    buf = np.concatenate([flat, lab.view(np.uint8)], axis=1)
    recordfile.write_records(path, (row.tobytes() for row in buf))


def random_crop_flip(
    images: np.ndarray,
    out_size: int,
    rng: np.random.Generator,
    flip: bool = True,
    order: np.ndarray = None,
) -> np.ndarray:
    """Train-time augmentation on uint8 [B, S, S, C]: per-sample random
    crop to out_size (requires S >= out_size; equality = no-op crop) and
    random horizontal flip.  `order` (a permutation of the batch) folds
    the training shuffle into the crop's gather, saving a separate
    full-array permutation pass — at image sizes that pass is hundreds
    of MB per task.

    Costs measured at 2048 x 256->224 on one core: per-sample slice
    copies run ~5.7 GB/s (numpy's 2D strided copy is memcpy-grade), and
    flipping IN the same per-sample copy (a reversed-stride slice) is
    2.3x cheaper than a separate `out[mask] = out[mask, :, ::-1]` pass
    — the boolean fancy-index pays a gather AND a scatter over half the
    batch."""
    b, s, c = images.shape[0], images.shape[1], images.shape[3]
    if s < out_size:
        raise ValueError(f"stored size {s} < crop size {out_size}")
    if order is None:
        order = np.arange(b)
    out = np.empty((b, out_size, out_size, c), np.uint8)
    span = s - out_size + 1
    dy = rng.integers(0, span, size=b)
    dx = rng.integers(0, span, size=b)
    do_flip = rng.random(b) < 0.5 if flip else np.zeros(b, bool)
    for i in range(b):
        src = images[
            order[i], dy[i]:dy[i] + out_size, dx[i]:dx[i] + out_size
        ]
        out[i] = src[:, ::-1] if do_flip[i] else src
    return out


def center_crop(images: np.ndarray, out_size: int) -> np.ndarray:
    """Eval-time deterministic crop ([B, S, S, C] uint8 -> out_size)."""
    s = images.shape[1]
    if s < out_size:
        raise ValueError(f"stored size {s} < crop size {out_size}")
    lo = (s - out_size) // 2
    return np.ascontiguousarray(
        images[:, lo:lo + out_size, lo:lo + out_size]
    )
