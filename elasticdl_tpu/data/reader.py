"""Shard-addressable data readers.

Parity: elasticdl/python/data/reader/ in the reference (RecordIODataReader,
ODPSDataReader, CSVDataReader + create_data_reader factory).  A reader
exposes `create_shards()` — the master uses it to build the task queue —
and `read_records(task)` — workers use it to stream a task's record range.

Readers here: NumpyDataReader (in-memory arrays, test/local harness),
CSVDataReader, TextLineDataReader, and RecordIODataReader backed by the
native C++ record file library (elasticdl_tpu/native) when built, with a
pure-Python fallback codec.
"""

from __future__ import annotations

import csv
import glob
import os
from abc import ABC, abstractmethod
from typing import Dict, Iterator

import numpy as np


class Metadata:
    """Feed metadata handed to the user's dataset_fn."""

    def __init__(self, column_names=None, column_dtypes=None):
        self.column_names = column_names or []
        self.column_dtypes = column_dtypes or {}


class AbstractDataReader(ABC):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    @abstractmethod
    def create_shards(self) -> Dict[str, object]:
        """shard_name -> record count (or (start, count))."""

    @abstractmethod
    def read_records(self, task) -> Iterator:
        """Yield raw records for task.shard_name[task.start:task.end]."""

    @property
    def metadata(self) -> Metadata:
        return Metadata()


class NumpyDataReader(AbstractDataReader):
    """In-memory (features, labels) arrays — the local/test harness reader.

    Records are (feature_row, label_row) tuples.
    """

    def __init__(self, features: np.ndarray, labels: np.ndarray, shard_name="memory", **kwargs):
        super().__init__(**kwargs)
        if len(features) != len(labels):
            raise ValueError("features and labels must have equal length")
        self._features = features
        self._labels = labels
        self._shard_name = shard_name

    def create_shards(self):
        return {self._shard_name: len(self._features)}

    def read_records(self, task):
        for i in range(task.start, min(task.end, len(self._features))):
            yield (self._features[i], self._labels[i])


class CSVDataReader(AbstractDataReader):
    """One shard per CSV file; a record is a list of string fields."""

    def __init__(self, data_dir: str = "", sep: str = ",", with_header: bool = True, **kwargs):
        super().__init__(**kwargs)
        self._data_dir = data_dir or kwargs.get("data_path", "")
        self._sep = sep
        self._with_header = with_header
        self._columns = None

    def _files(self):
        if os.path.isdir(self._data_dir):
            return sorted(glob.glob(os.path.join(self._data_dir, "*.csv")))
        return sorted(glob.glob(self._data_dir))

    def _count_records(self, path):
        # Count parsed rows (not raw lines): quoted fields may contain
        # newlines, and shard ranges must index the same record stream that
        # read_records yields.
        with open(path, newline="") as f:
            count = sum(1 for _ in csv.reader(f, delimiter=self._sep))
        return count - 1 if self._with_header else count

    def create_shards(self):
        shards = {}
        for path in self._files():
            shards[path] = self._count_records(path)
            if self._with_header and self._columns is None:
                with open(path, newline="") as f:
                    self._columns = next(csv.reader(f, delimiter=self._sep))
        return shards

    def read_records(self, task):
        with open(task.shard_name, newline="") as f:
            reader = csv.reader(f, delimiter=self._sep)
            if self._with_header:
                header = next(reader)
                if self._columns is None:
                    self._columns = header
            for index, row in enumerate(reader):
                if index < task.start:
                    continue
                if index >= task.end:
                    break
                yield row

    @property
    def metadata(self):
        if self._columns is None:
            self.create_shards()
        return Metadata(column_names=self._columns)


class TextLineDataReader(AbstractDataReader):
    """One shard per text file; a record is a line (str, no newline)."""

    def __init__(self, data_dir: str = "", **kwargs):
        super().__init__(**kwargs)
        self._data_dir = data_dir or kwargs.get("data_path", "")

    def _files(self):
        if os.path.isdir(self._data_dir):
            return sorted(
                path
                for name in os.listdir(self._data_dir)
                # Skip markers (_SUCCESS), hidden files, and subdirectories.
                if not name.startswith(("_", "."))
                and os.path.isfile(path := os.path.join(self._data_dir, name))
            )
        return sorted(p for p in glob.glob(self._data_dir) if os.path.isfile(p))

    def create_shards(self):
        shards = {}
        for path in self._files():
            with open(path) as f:
                shards[path] = sum(1 for _ in f)
        return shards

    def read_records(self, task):
        with open(task.shard_name) as f:
            for index, line in enumerate(f):
                if index < task.start:
                    continue
                if index >= task.end:
                    break
                yield line.rstrip("\n")


class RecordIODataReader(AbstractDataReader):
    """Shardable binary record files (the reference's RecordIO analogue).

    Uses the native C++ reader from elasticdl_tpu/native when built (fast
    path for high-throughput input pipelines), else the pure-Python codec in
    elasticdl_tpu.data.recordfile.
    """

    def __init__(self, data_dir: str = "", **kwargs):
        super().__init__(**kwargs)
        self._data_dir = data_dir or kwargs.get("data_path", "")

    def _files(self):
        if os.path.isdir(self._data_dir):
            return sorted(
                os.path.join(self._data_dir, name)
                for name in os.listdir(self._data_dir)
                if name.endswith((".rio", ".recordio"))
            )
        return sorted(p for p in glob.glob(self._data_dir) if os.path.isfile(p))

    def create_shards(self):
        from elasticdl_tpu.data import recordfile

        return {path: recordfile.count_records(path) for path in self._files()}

    def read_records(self, task):
        from elasticdl_tpu.data import recordfile

        yield from recordfile.read_range(task.shard_name, task.start, task.end)


_READERS = {
    "numpy": NumpyDataReader,
    "csv": CSVDataReader,
    "textline": TextLineDataReader,
    "recordio": RecordIODataReader,
}


def build_data_reader(args, model_spec, data_path: str):
    """Resolve the reader for a job: the model's custom_data_reader wins,
    else infer from the path.  Shared by master and worker entrypoints."""
    from elasticdl_tpu.common.args import parse_dict_params

    reader_params = parse_dict_params(args.data_reader_params)
    if model_spec.custom_data_reader is not None:
        reader = model_spec.custom_data_reader(data_path, **reader_params)
        if reader is not None:
            return reader
    return create_data_reader(data_path, **reader_params)


def create_data_reader(data_origin: str, records_per_task=None, **kwargs):
    """Factory. `data_origin` is 'reader_type:path' or a bare path.

    Bare paths infer the reader from the extension (.csv -> csv,
    .rio/.recordio -> recordio, else textline).
    """
    if ":" in data_origin and data_origin.split(":", 1)[0] in _READERS:
        reader_type, path = data_origin.split(":", 1)
    else:
        path = data_origin
        sample = path
        if os.path.isdir(path):
            entries = sorted(os.listdir(path))
            sample = entries[0] if entries else ""
        if sample.endswith(".csv"):
            reader_type = "csv"
        elif sample.endswith((".rio", ".recordio")):
            reader_type = "recordio"
        else:
            reader_type = "textline"
    cls = _READERS[reader_type]
    return cls(data_dir=path, **kwargs)
