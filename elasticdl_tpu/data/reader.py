"""Shard-addressable data readers.

Parity: elasticdl/python/data/reader/ in the reference (RecordIODataReader,
ODPSDataReader, CSVDataReader + create_data_reader factory).  A reader
exposes `create_shards()` — the master uses it to build the task queue —
and `read_records(task)` — workers use it to stream a task's record range.

Readers here: NumpyDataReader (in-memory arrays, test/local harness),
CSVDataReader, TextLineDataReader, and RecordIODataReader backed by the
native C++ record file library (elasticdl_tpu/native) when built, with a
pure-Python fallback codec.
"""

from __future__ import annotations

import csv
import glob
import os
from abc import ABC, abstractmethod
from typing import Dict, Iterator

import numpy as np


class Metadata:
    """Feed metadata handed to the user's dataset_fn."""

    def __init__(self, column_names=None, column_dtypes=None):
        self.column_names = column_names or []
        self.column_dtypes = column_dtypes or {}


class AbstractDataReader(ABC):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    @abstractmethod
    def create_shards(self) -> Dict[str, object]:
        """shard_name -> record count (or (start, count))."""

    @abstractmethod
    def read_records(self, task) -> Iterator:
        """Yield raw records for task.shard_name[task.start:task.end]."""

    def shard_names(self):
        """Deterministic shard-name listing WITHOUT counting records.
        Workers use this to index the task-broadcast encoding; only the
        master's task queue needs the counts (create_shards) — readers
        whose counting is expensive (ODPS table tunnel, big-file scans)
        override this to skip it."""
        return list(self.create_shards().keys())

    @property
    def metadata(self) -> Metadata:
        return Metadata()


class NumpyDataReader(AbstractDataReader):
    """In-memory (features, labels) arrays — the local/test harness reader.

    Records are (feature_row, label_row) tuples.
    """

    def __init__(self, features: np.ndarray, labels: np.ndarray, shard_name="memory", **kwargs):
        super().__init__(**kwargs)
        if len(features) != len(labels):
            raise ValueError("features and labels must have equal length")
        self._features = features
        self._labels = labels
        self._shard_name = shard_name

    def create_shards(self):
        return {self._shard_name: len(self._features)}

    def read_records(self, task):
        for i in range(task.start, min(task.end, len(self._features))):
            yield (self._features[i], self._labels[i])


class _ByteLines:
    """Line iterator over a binary file that tracks bytes consumed — the
    probe the offset index uses to learn where record N starts."""

    def __init__(self, f):
        self._f = f
        self.consumed = f.tell()

    def __iter__(self):
        return self

    def __next__(self):
        line = self._f.readline()
        if not line:
            raise StopIteration
        self.consumed += len(line)
        return line.decode("utf-8")


class _StridedOffsetIndex:
    """Byte offset of every STRIDE-th record per file, built during the
    counting pass `create_shards` already pays.  A task seek becomes
    O(STRIDE + records_per_task) instead of O(file) — the round-1 CSV/text
    readers re-scanned from byte 0 for every task, O(n^2) per epoch on one
    big file.  Entries invalidate on (mtime, size) change."""

    STRIDE = 64

    def __init__(self):
        self._entries: Dict[str, tuple] = {}

    @staticmethod
    def _stamp(path):
        stat = os.stat(path)
        return (stat.st_mtime_ns, stat.st_size)

    def put(self, path, count, offsets):
        self._entries[path] = (self._stamp(path), count, offsets)

    def get(self, path):
        entry = self._entries.get(path)
        if entry is None or entry[0] != self._stamp(path):
            return None
        return entry[1], entry[2]

    def position(self, path, start):
        """(byte_offset, records_to_skip) to reach record `start`, or
        None when the file isn't indexed (or changed since)."""
        entry = self.get(path)
        if entry is None or not entry[1]:
            return None
        _count, offsets = entry
        bucket = min(start // self.STRIDE, len(offsets) - 1)
        return offsets[bucket], start - bucket * self.STRIDE


def _resolve_position(index, scan, task):
    """Index lookup with self-healing: a miss (index never built — e.g. a
    Local-mode worker whose shard list came from the master — or
    invalidated by an mtime change) triggers ONE rebuilding scan when the
    task starts deep enough in the file that streaming from the top would
    cost more than the scan amortizes over subsequent tasks.  Shallow
    tasks just stream (no full-file pre-scan before row 0)."""
    position = index.position(task.shard_name, task.start)
    if position is None and task.start >= 4 * _StridedOffsetIndex.STRIDE:
        scan(task.shard_name)
        position = index.position(task.shard_name, task.start)
    return position


class CSVDataReader(AbstractDataReader):
    """One shard per CSV file; a record is a list of string fields.

    Record offsets index PARSED rows (quoted fields may contain newlines),
    probed through _ByteLines while csv.reader pulls lines — csv consumes
    lazily, so bytes-consumed after row i is exactly row i+1's offset.
    """

    def __init__(self, data_dir: str = "", sep: str = ",", with_header: bool = True, **kwargs):
        super().__init__(**kwargs)
        self._data_dir = data_dir or kwargs.get("data_path", "")
        self._sep = sep
        self._with_header = with_header
        self._columns = None
        self._index = _StridedOffsetIndex()

    def _files(self):
        if os.path.isdir(self._data_dir):
            return sorted(glob.glob(os.path.join(self._data_dir, "*.csv")))
        return sorted(glob.glob(self._data_dir))

    def shard_names(self):
        # Shard name == file path: workers list shards without the
        # counting scan create_shards pays (only the master needs counts).
        return self._files()

    def _scan(self, path):
        """One pass: record count + strided record offsets (+ header)."""
        with open(path, "rb") as f:
            lines = _ByteLines(f)
            reader = csv.reader(lines, delimiter=self._sep)
            if self._with_header:
                header = next(reader, None)
                if header is not None and self._columns is None:
                    self._columns = header
            count = 0
            offsets = []
            mark = lines.consumed
            for _row in reader:
                if count % _StridedOffsetIndex.STRIDE == 0:
                    offsets.append(mark)
                count += 1
                mark = lines.consumed
        self._index.put(path, count, offsets)
        return count

    def create_shards(self):
        return {path: self._scan(path) for path in self._files()}

    def read_records(self, task):
        position = self._resolve_position(task)
        with open(task.shard_name, "rb") as f:
            if position is not None:
                offset, skip = position
                f.seek(offset)
            else:
                # Unindexed near the top of the file: stream, bounded by
                # task.end — no full-file pre-scan before row 0.
                skip = task.start
            reader = csv.reader(_ByteLines(f), delimiter=self._sep)
            if position is None and self._with_header:
                next(reader, None)
            want = task.end - task.start
            for index, row in enumerate(reader):
                if index < skip:
                    continue
                if index - skip >= want:
                    break
                yield row

    def _resolve_position(self, task):
        return _resolve_position(self._index, self._scan, task)

    @property
    def metadata(self):
        if (
            self._columns is None
            and self._with_header
            and not getattr(self, "_header_scanned", False)
        ):
            # Header row from the first NON-EMPTY file — never the
            # counting scan create_shards pays (workers read metadata at
            # boot).  Scanned-flag caches the no-header outcome so empty
            # datasets don't re-open files on every access.
            self._header_scanned = True
            for path in self._files():
                with open(path, "rb") as f:
                    header = next(
                        csv.reader(_ByteLines(f), delimiter=self._sep), None
                    )
                if header:
                    self._columns = header
                    break
        return Metadata(column_names=self._columns)


class TextLineDataReader(AbstractDataReader):
    """One shard per text file; a record is a line (str, no newline).

    Strided line-offset index (built during the counting pass) gives
    O(STRIDE + range) task seeks, same as the CSV reader.
    """

    def __init__(self, data_dir: str = "", **kwargs):
        super().__init__(**kwargs)
        self._data_dir = data_dir or kwargs.get("data_path", "")
        self._index = _StridedOffsetIndex()

    def _files(self):
        if os.path.isdir(self._data_dir):
            return sorted(
                path
                for name in os.listdir(self._data_dir)
                # Skip markers (_SUCCESS), hidden files, and subdirectories.
                if not name.startswith(("_", "."))
                and os.path.isfile(path := os.path.join(self._data_dir, name))
            )
        return sorted(p for p in glob.glob(self._data_dir) if os.path.isfile(p))

    def shard_names(self):
        return self._files()

    def _scan(self, path):
        with open(path, "rb") as f:
            count = 0
            offsets = []
            mark = 0
            for line in f:
                if count % _StridedOffsetIndex.STRIDE == 0:
                    offsets.append(mark)
                count += 1
                mark += len(line)
        self._index.put(path, count, offsets)
        return count

    def create_shards(self):
        return {path: self._scan(path) for path in self._files()}

    def read_records(self, task):
        position = _resolve_position(self._index, self._scan, task)
        with open(task.shard_name, "rb") as f:
            if position is not None:
                offset, skip = position
                f.seek(offset)
            else:
                # Unindexed near the top: stream, bounded by task.end.
                skip = task.start
            want = task.end - task.start
            for index, line in enumerate(f):
                if index < skip:
                    continue
                if index - skip >= want:
                    break
                yield line.decode("utf-8").rstrip("\r\n")


class RecordIODataReader(AbstractDataReader):
    """Shardable binary record files (the reference's RecordIO analogue).

    Uses the native C++ reader from elasticdl_tpu/native when built (fast
    path for high-throughput input pipelines), else the pure-Python codec in
    elasticdl_tpu.data.recordfile.
    """

    def __init__(self, data_dir: str = "", **kwargs):
        super().__init__(**kwargs)
        self._data_dir = data_dir or kwargs.get("data_path", "")

    def _files(self):
        if os.path.isdir(self._data_dir):
            return sorted(
                os.path.join(self._data_dir, name)
                for name in os.listdir(self._data_dir)
                if name.endswith((".rio", ".recordio"))
            )
        return sorted(p for p in glob.glob(self._data_dir) if os.path.isfile(p))

    def shard_names(self):
        return self._files()

    def create_shards(self):
        from elasticdl_tpu.data import recordfile

        return {path: recordfile.count_records(path) for path in self._files()}

    def read_records(self, task):
        from elasticdl_tpu.data import recordfile

        yield from recordfile.read_range(task.shard_name, task.start, task.end)


def is_etrf_dir(path: str) -> bool:
    """True when `path` is a directory holding .etrf shard files (the
    reference's RecordIO-directory dataset layout)."""
    return os.path.isdir(path) and any(
        name.endswith(".etrf") for name in os.listdir(path)
    )


class FixedWidthEtrfReader(AbstractDataReader):
    """ETRF shards of fixed-width binary records with the vectorized
    columnar surface (data/vectorized.py + data/columnar.py).

    `path` is one .etrf file or a DIRECTORY of them — the reference's
    RecordIO-directory layout (†data/reader/recordio_reader.py): each
    file is one shard in the master's dynamic-sharding queue, tasks
    address [start, end) WITHIN their shard.  Subclasses supply the
    record layout and the per-row assembly for the per-record fallback
    path; the columnar fast path needs nothing else."""

    #: subclasses whose columnar consumers immediately gather into fresh
    #: arrays (the image crop) set False to skip the defensive copy.
    copy_columns = True
    #: per-chunk payload budget for the columnar path; 0 = the codec's
    #: default (128 MB).  Readers of large records raise it so a whole
    #: task arrives as ONE chunk — skipping the downstream concatenate
    #: and halving peak memory (data/recordfile.read_range_buffers).
    columnar_chunk_bytes = 0

    def __init__(self, path: str, **kwargs):
        super().__init__(**kwargs)
        self._path = path

    def _files(self):
        if os.path.isdir(self._path):
            files = sorted(
                os.path.join(self._path, name)
                for name in os.listdir(self._path)
                if name.endswith(".etrf")
            )
            if not files:
                raise ValueError(f"no .etrf shards under {self._path}")
            return files
        return [self._path]

    def shard_names(self):
        return self._files()

    def create_shards(self):
        from elasticdl_tpu.data import recordfile

        return {p: recordfile.count_records(p) for p in self._files()}

    def layout(self):
        """The RecordLayout shared by every shard."""
        raise NotImplementedError

    def _task_path(self, task) -> str:
        # Tasks carry their shard (file) name; harnesses that fake a
        # task over a SINGLE-file reader may omit it.  A directory
        # reader must never guess — serving shard 0 for every task
        # would be silently wrong data.
        path = getattr(task, "shard_name", None)
        if path:
            return path
        files = self._files()
        if len(files) > 1:
            raise ValueError(
                "task has no shard_name but this reader holds "
                f"{len(files)} shards under {self._path}"
            )
        return files[0]

    def record_count(self, task) -> int:
        """Record count of one task WITHOUT materializing anything: a
        task is a [start, end) range by contract, so the count is pure
        arithmetic.  The parse pool's bounded read-ahead (data/
        pipeline.py) sizes its lookahead from this instead of listing
        an epoch's records."""
        return max(0, int(task.end) - int(task.start))

    def read_columns(self, task, parse_pool=None):
        """Columnar chunks for one task.  With a `parse_pool`
        (data/pipeline.ParsePool), `parse_buffer` for chunk k+1..k+n
        runs on pool threads while the consumer transforms chunk k —
        numpy releases the GIL for the big view-copy, so the parse
        scales with host cores.  Ordering is deterministic either way
        (the pool reassembles by submission index)."""
        from elasticdl_tpu.data import recordfile

        layout = self.layout()
        buffers = recordfile.read_range_buffers(
            self._task_path(task), task.start, task.end,
            max_bytes=self.columnar_chunk_bytes,
        )
        if parse_pool is not None and getattr(parse_pool, "workers", 0):
            yield from parse_pool.imap(
                lambda chunk: layout.parse_buffer(
                    chunk[0], chunk[1], copy=self.copy_columns
                ),
                buffers,
            )
            return
        for buf, lengths in buffers:
            yield layout.parse_buffer(
                buf, lengths, copy=self.copy_columns
            )

    def _row(self, cols, i):
        """One record of a columnar chunk -> the per-record dataset
        item (the reference-parity fallback path)."""
        raise NotImplementedError

    def read_records(self, task):
        for cols in self.read_columns(task):
            n = len(next(iter(cols.values())))
            for i in range(n):
                yield self._row(cols, i)


def _odps_reader(**kwargs):
    from elasticdl_tpu.data.odps_reader import ODPSDataReader

    return ODPSDataReader(**kwargs)


_READERS = {
    "numpy": NumpyDataReader,
    "csv": CSVDataReader,
    "textline": TextLineDataReader,
    "recordio": RecordIODataReader,
    "odps": _odps_reader,
}


def build_data_reader(args, model_spec, data_path: str):
    """Resolve the reader for a job: the model's custom_data_reader wins,
    else infer from the path.  Shared by master and worker entrypoints."""
    from elasticdl_tpu.common.args import parse_dict_params

    reader_params = parse_dict_params(args.data_reader_params)
    if model_spec.custom_data_reader is not None:
        reader = model_spec.custom_data_reader(data_path, **reader_params)
        if reader is not None:
            return reader
    return create_data_reader(data_path, **reader_params)


def create_data_reader(data_origin: str, records_per_task=None, **kwargs):
    """Factory. `data_origin` is 'reader_type:path' or a bare path.

    Bare paths infer the reader from the extension (.csv -> csv,
    .rio/.recordio -> recordio, else textline).
    """
    if ":" in data_origin and data_origin.split(":", 1)[0] in _READERS:
        reader_type, path = data_origin.split(":", 1)
    else:
        path = data_origin
        sample = path
        if os.path.isdir(path):
            entries = sorted(os.listdir(path))
            sample = entries[0] if entries else ""
        if sample.endswith(".csv"):
            reader_type = "csv"
        elif sample.endswith((".rio", ".recordio")):
            reader_type = "recordio"
        else:
            reader_type = "textline"
    cls = _READERS[reader_type]
    return cls(data_dir=path, **kwargs)
