"""A minimal, TF-free host-side dataset pipeline.

The reference's model-zoo contract passes a `tf.data.Dataset` through the
user's `dataset_fn` (elasticdl/python/data/ in the reference).  The TPU
rebuild keeps the same call shape — `dataset_fn(dataset, mode, metadata)`
returning a transformed dataset — but the pipeline is a small numpy-based
iterator chain: records stream from the data reader on the host CPU, are
parsed/shuffled/batched here, and land on device as whole batches (one
host->HBM transfer per step, the TPU-friendly feed pattern).
"""

from __future__ import annotations

import collections
import random
from typing import Callable, Iterable, Iterator, Optional

import numpy as np


class Dataset:
    """Lazy record pipeline: from_generator -> map -> shuffle -> batch."""

    def __init__(self, source: Callable[[], Iterator]):
        # `source` is a zero-arg callable returning a fresh iterator so the
        # dataset can be re-iterated (e.g. retry of a failed task).
        self._source = source

    @staticmethod
    def from_generator(generator_fn: Callable[[], Iterator]) -> "Dataset":
        return Dataset(generator_fn)

    @staticmethod
    def from_iterable(iterable: Iterable) -> "Dataset":
        materialized = list(iterable) if not isinstance(iterable, (list, tuple)) else iterable
        return Dataset(lambda: iter(materialized))

    def map(self, fn: Callable) -> "Dataset":
        source = self._source

        def mapped():
            for record in source():
                yield fn(record)

        return Dataset(mapped)

    def filter(self, predicate: Callable) -> "Dataset":
        source = self._source

        def filtered():
            for record in source():
                if predicate(record):
                    yield record

        return Dataset(filtered)

    def shuffle(self, buffer_size: int, seed: Optional[int] = None) -> "Dataset":
        source = self._source

        def shuffled():
            rng = random.Random(seed)
            buffer = []
            for record in source():
                buffer.append(record)
                if len(buffer) >= buffer_size:
                    index = rng.randrange(len(buffer))
                    buffer[index], buffer[-1] = buffer[-1], buffer[index]
                    yield buffer.pop()
            rng.shuffle(buffer)
            yield from buffer

        return Dataset(shuffled)

    def batch(self, batch_size: int, drop_remainder: bool = False) -> "Dataset":
        source = self._source

        def batched():
            batch = []
            for record in source():
                batch.append(record)
                if len(batch) == batch_size:
                    yield _stack(batch)
                    batch = []
            if batch and not drop_remainder:
                yield _stack(batch)

        return Dataset(batched)

    def repeat(self, count: int) -> "Dataset":
        source = self._source

        def repeated():
            for _ in range(count):
                yield from source()

        return Dataset(repeated)

    def __iter__(self):
        return self._source()


def _stack(records):
    """Stack a list of examples into a batch, handling nested structures."""
    first = records[0]
    if isinstance(first, tuple):
        return tuple(_stack([r[i] for r in records]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _stack([r[k] for r in records]) for k in first}
    return np.stack([np.asarray(r) for r in records])


class SequentialRecords:
    """Bounded-memory sequential access to a dataset's records.

    The round-2 worker materialized each task with `list(dataset)` —
    O(task-records) of per-row Python objects on EVERY rank, an OOM
    shaped like a design choice at ImageNet/Criteo eval scale (VERDICT
    round-2 weak #5).  Batch ranges advance monotonically
    (parallel/elastic.iter_local_batch_ranges), so a one-pass cursor
    suffices: records stream from the iterator, only the requested slice
    is ever resident, and skipped ranges (other ranks' rows) are pulled
    and dropped.  `template()` peeks the first record without consuming
    it (ragged-tail batches need a shape exemplar)."""

    def __init__(self, dataset):
        self._it = iter(dataset)
        self._pending = None  # one-record lookahead (template peek)
        self._template = None  # first record ever seen (shape exemplar)
        self._pos = 0  # absolute index of the next un-consumed record

    def _next(self):
        if self._pending is not None:
            rec, self._pending = self._pending, None
        else:
            rec = next(self._it, None)
        if rec is not None and self._template is None:
            self._template = rec
        return rec

    def template(self):
        """The first record (cached; peeked without consuming if nothing
        has been pulled yet) — empty/ragged batches shape from it."""
        if self._template is None and self._pending is None:
            self._pending = next(self._it, None)
            self._template = self._pending
        if self._template is None:
            # Stacking a None "record" would produce an object-dtype batch
            # and an inscrutable downstream failure; the real problem is a
            # source that yielded nothing for a range its shard metadata
            # claims (short file, reader bug).
            raise ValueError(
                "dataset produced zero records — no batch-shape template "
                "exists (does the reader's shard metadata overstate the "
                "source's rows?)"
            )
        return self._template

    def slice(self, lo: int, hi: int) -> list:
        """Records [lo, hi); requires lo >= last consumed position."""
        if lo < self._pos:
            raise ValueError(
                f"SequentialRecords is one-pass: asked for [{lo},{hi}) "
                f"after position {self._pos}"
            )
        while self._pos < lo:
            if self._next() is None:
                return []
            self._pos += 1
        out = []
        while self._pos < hi:
            rec = self._next()
            if rec is None:
                break
            out.append(rec)
            self._pos += 1
        return out
