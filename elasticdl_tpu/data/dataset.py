"""A minimal, TF-free host-side dataset pipeline.

The reference's model-zoo contract passes a `tf.data.Dataset` through the
user's `dataset_fn` (elasticdl/python/data/ in the reference).  The TPU
rebuild keeps the same call shape — `dataset_fn(dataset, mode, metadata)`
returning a transformed dataset — but the pipeline is a small numpy-based
iterator chain: records stream from the data reader on the host CPU, are
parsed/shuffled/batched here, and land on device as whole batches (one
host->HBM transfer per step, the TPU-friendly feed pattern).
"""

from __future__ import annotations

import collections
import random
from typing import Callable, Iterable, Iterator, Optional

import numpy as np


class Dataset:
    """Lazy record pipeline: from_generator -> map -> shuffle -> batch."""

    def __init__(self, source: Callable[[], Iterator]):
        # `source` is a zero-arg callable returning a fresh iterator so the
        # dataset can be re-iterated (e.g. retry of a failed task).
        self._source = source

    @staticmethod
    def from_generator(generator_fn: Callable[[], Iterator]) -> "Dataset":
        return Dataset(generator_fn)

    @staticmethod
    def from_iterable(iterable: Iterable) -> "Dataset":
        materialized = list(iterable) if not isinstance(iterable, (list, tuple)) else iterable
        return Dataset(lambda: iter(materialized))

    def map(self, fn: Callable) -> "Dataset":
        source = self._source

        def mapped():
            for record in source():
                yield fn(record)

        return Dataset(mapped)

    def filter(self, predicate: Callable) -> "Dataset":
        source = self._source

        def filtered():
            for record in source():
                if predicate(record):
                    yield record

        return Dataset(filtered)

    def shuffle(self, buffer_size: int, seed: Optional[int] = None) -> "Dataset":
        source = self._source

        def shuffled():
            rng = random.Random(seed)
            buffer = []
            for record in source():
                buffer.append(record)
                if len(buffer) >= buffer_size:
                    index = rng.randrange(len(buffer))
                    buffer[index], buffer[-1] = buffer[-1], buffer[index]
                    yield buffer.pop()
            rng.shuffle(buffer)
            yield from buffer

        return Dataset(shuffled)

    def batch(self, batch_size: int, drop_remainder: bool = False) -> "Dataset":
        source = self._source

        def batched():
            batch = []
            for record in source():
                batch.append(record)
                if len(batch) == batch_size:
                    yield _stack(batch)
                    batch = []
            if batch and not drop_remainder:
                yield _stack(batch)

        return Dataset(batched)

    def repeat(self, count: int) -> "Dataset":
        source = self._source

        def repeated():
            for _ in range(count):
                yield from source()

        return Dataset(repeated)

    def __iter__(self):
        return self._source()


def _stack(records):
    """Stack a list of examples into a batch, handling nested structures."""
    first = records[0]
    if isinstance(first, tuple):
        return tuple(_stack([r[i] for r in records]) for i in range(len(first)))
    if isinstance(first, dict):
        return {k: _stack([r[k] for r in records]) for k in first}
    return np.stack([np.asarray(r) for r in records])
