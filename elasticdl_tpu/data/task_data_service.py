"""Build the per-task dataset a worker trains on.

Parity: elasticdl/python/data/task_data_service.py in the reference — turns
the current task's record range into the user-visible dataset by streaming
reader records through the user's dataset_fn.
"""

from __future__ import annotations

from elasticdl_tpu.data.dataset import Dataset


class TaskDataService:
    def __init__(self, data_reader, dataset_fn, metadata=None):
        self._reader = data_reader
        self._dataset_fn = dataset_fn
        self._metadata = metadata if metadata is not None else data_reader.metadata

    def get_dataset(self, task, mode: str) -> Dataset:
        reader = self._reader

        def records():
            return reader.read_records(task)

        dataset = Dataset.from_generator(records)
        return self._dataset_fn(dataset, mode, self._metadata)
