"""Build the per-task dataset a worker trains on.

Parity: elasticdl/python/data/task_data_service.py in the reference — turns
the current task's record range into the user-visible dataset by streaming
reader records through the user's dataset_fn.
"""

from __future__ import annotations

from elasticdl_tpu.data.dataset import Dataset


class TaskDataService:
    def __init__(self, data_reader, dataset_fn, metadata=None):
        self._reader = data_reader
        self._dataset_fn = dataset_fn
        self._metadata = metadata if metadata is not None else data_reader.metadata

    def record_count(self, task) -> int:
        """How many records the task holds — WITHOUT materializing the
        epoch.  A task is a [start, end) range by contract, so readers
        that don't override `record_count` get pure arithmetic; the
        async pipeline's bounded read-ahead (data/pipeline.Prefetcher)
        sizes itself from this, never from a listed epoch."""
        counter = getattr(self._reader, "record_count", None)
        if counter is not None:
            return int(counter(task))
        return max(0, int(task.end) - int(task.start))

    def get_dataset(self, task, mode: str) -> Dataset:
        reader = self._reader

        def records():
            return reader.read_records(task)

        dataset = Dataset.from_generator(records)
        return self._dataset_fn(dataset, mode, self._metadata)

    def get_batches(self, task, mode: str, batch_size: int, lookahead: int = 0):
        """The task's minibatch iterator, optionally with BOUNDED
        background read-ahead: `lookahead > 0` wraps the iterator in a
        data/pipeline.Prefetcher whose queue holds at most `lookahead`
        batches — a slow consumer (device) stalls the producer instead
        of growing an unbounded buffer.  The caller owns the returned
        Prefetcher's `close()` (task/rendezvous boundaries drain it)."""
        batches = iter(self.get_dataset(task, mode).batch(batch_size))
        if lookahead <= 0:
            return batches
        from elasticdl_tpu.data.pipeline import Prefetcher

        return Prefetcher(batches, max_inflight=lookahead)
