"""ODPS (MaxCompute) table reader.

Parity: elasticdl/python/data/reader/odps_reader.py + odps_io.py in the
reference — shard an ODPS table by row ranges (`create_shards` names the
table, `read_records` pulls a range through a tunnel reader), so cloud
tables plug into the same dynamic-sharding task queue as files.

The `odps` SDK is cloud-specific and not in this image, so the transport
is injectable: `ODPSDataReader(client=...)` takes any object with the
small `TableClient` surface below (row_count / open_reader), and the
default client is built lazily from the `odps` package + env/kwargs
credentials — constructing the reader without either fails with a clear
message, never at import time.  The fake-client tests
(tests/test_odps_reader.py) pin the sharding/range semantics the real SDK
path rides on.

Credentials resolve reference-style from kwargs or env:
ODPS_ACCESS_ID / ODPS_ACCESS_KEY / ODPS_PROJECT_NAME / ODPS_ENDPOINT.
"""

from __future__ import annotations

import os
from typing import Iterator, List, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.reader import AbstractDataReader, Metadata

logger = get_logger("data.odps_reader")


class TableClient:
    """The transport surface ODPSDataReader needs (duck-typed).

    - row_count(table, partition) -> int
    - read_rows(table, partition, start, count, columns) -> iterator of
      row tuples/lists
    - column_names(table) -> list[str]
    """

    def row_count(self, table: str, partition: Optional[str]) -> int:
        raise NotImplementedError

    def read_rows(self, table, partition, start, count, columns):
        raise NotImplementedError

    def column_names(self, table: str) -> List[str]:
        raise NotImplementedError


class _OdpsSdkClient(TableClient):
    """Real transport over the `odps` package (pyodps)."""

    def __init__(self, access_id, access_key, project, endpoint):
        try:
            from odps import ODPS  # cloud SDK; not baked into this image
        except ImportError as e:
            raise RuntimeError(
                "ODPSDataReader needs the `odps` package (pyodps) or an "
                "injected client=; neither is available"
            ) from e
        self._odps = ODPS(access_id, access_key, project, endpoint=endpoint)

    def _table(self, table):
        return self._odps.get_table(table)

    def row_count(self, table, partition):
        t = self._table(table)
        if partition:
            return t.get_partition(partition).record_num
        with t.open_reader() as reader:
            return reader.count

    def read_rows(self, table, partition, start, count, columns):
        with self._table(table).open_reader(partition=partition) as reader:
            for record in reader.read(start=start, count=count,
                                      columns=columns or None):
                yield [record[i] for i in range(len(record))]

    def column_names(self, table):
        return [c.name for c in self._table(table).table_schema.columns]


class ODPSDataReader(AbstractDataReader):
    """Shard-addressable reader over one ODPS table.

    kwargs (reference flag names, via --data_reader_params):
    table=, partition=, columns= ('a;b;c'), plus credentials
    (access_id/access_key/project/endpoint) falling back to ODPS_* env.
    """

    def __init__(
        self,
        data_dir: str = "",
        table: str = "",
        partition: str = "",
        columns: str = "",
        client: Optional[TableClient] = None,
        **kwargs,
    ):
        super().__init__(**kwargs)
        # `odps://table_name` / bare table name via the data path, or
        # table= via reader params.
        path = data_dir or kwargs.get("data_path", "")
        if path.startswith("odps://"):
            path = path[len("odps://"):]
        # The factory splits 'odps://table' at the first ':', handing this
        # reader '//table'.
        self._table = table or path.lstrip("/")
        if not self._table:
            raise ValueError("ODPSDataReader needs a table name")
        self._partition = partition or None
        self._columns = (
            [c for c in columns.split(";") if c] if columns else []
        )
        self._client = client or self._default_client(kwargs)
        self._count: Optional[int] = None

    @staticmethod
    def _default_client(kwargs) -> TableClient:
        def cred(name, env):
            return kwargs.get(name, "") or os.environ.get(env, "")

        access_id = cred("access_id", "ODPS_ACCESS_ID")
        access_key = cred("access_key", "ODPS_ACCESS_KEY")
        project = cred("project", "ODPS_PROJECT_NAME")
        endpoint = cred("endpoint", "ODPS_ENDPOINT")
        if not (access_id and access_key and project):
            raise ValueError(
                "ODPS credentials missing: pass access_id/access_key/"
                "project via --data_reader_params or the ODPS_ACCESS_ID/"
                "ODPS_ACCESS_KEY/ODPS_PROJECT_NAME env vars"
            )
        return _OdpsSdkClient(access_id, access_key, project, endpoint)

    # -- AbstractDataReader ----------------------------------------------

    def _shard_name(self) -> str:
        return (
            f"{self._table}/{self._partition}"
            if self._partition
            else self._table
        )

    def shard_names(self):
        """Config-derived: no table-count RPC — N workers calling this at
        boot must not fan N redundant tunnel-reader opens at the cloud."""
        return [self._shard_name()]

    def create_shards(self):
        if self._count is None:
            self._count = int(
                self._client.row_count(self._table, self._partition)
            )
        return {self._shard_name(): self._count}

    def read_records(self, task) -> Iterator:
        start = max(0, task.start)
        count = task.end - start
        if count <= 0:
            return
        yield from self._client.read_rows(
            self._table, self._partition, start, count, self._columns
        )

    @property
    def metadata(self) -> Metadata:
        names = self._columns or self._client.column_names(self._table)
        return Metadata(column_names=list(names))
