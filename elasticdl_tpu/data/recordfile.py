"""Shardable binary record file format ("ETRF") — pure-Python codec.

Parity: the reference depends on RecordIO (external C++/Go, pyrecordio) as
its shard-addressable record format.  ETRF is this framework's equivalent:

    header:  magic b"ETRF" + u32 version (little-endian)
    record:  u32 payload_length + u32 crc32(payload) + payload bytes
    footer:  u64 record_count + u64 index_offset + magic b"FTRE"
             where index (at index_offset) is record_count u64 file offsets

The index footer makes `count_records` and `read_range` O(1) seeks instead
of scans — that is what makes dynamic sharding cheap for the master.  The
native C++ implementation (elasticdl_tpu/native/recordfile.cc) reads and
writes the same format and is preferred automatically when the toolchain
built it (`read_range`/`count_records` dispatch below); this module is the
always-available fallback and the reference implementation for parity
tests (tests/test_native_recordfile.py).  Set ELASTICDL_DISABLE_NATIVE=1
to force the Python codec.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Iterator, List


def _native():
    if os.environ.get("ELASTICDL_DISABLE_NATIVE"):
        return None
    from elasticdl_tpu import native as native_mod

    return native_mod.record_file()

MAGIC = b"ETRF"
FOOTER_MAGIC = b"FTRE"
VERSION = 1

_HEADER = struct.Struct("<4sI")       # magic, version
_RECORD_HEAD = struct.Struct("<II")   # length, crc32
_FOOTER = struct.Struct("<QQ4s")      # record_count, index_offset, magic


class RecordFileError(IOError):
    pass


class Writer:
    def __init__(self, path: str):
        self._file = open(path, "wb")
        self._file.write(_HEADER.pack(MAGIC, VERSION))
        self._offsets: List[int] = []

    def write(self, payload: bytes):
        if not isinstance(payload, (bytes, bytearray, memoryview)):
            raise TypeError("record payload must be bytes")
        payload = bytes(payload)
        self._offsets.append(self._file.tell())
        self._file.write(_RECORD_HEAD.pack(len(payload), zlib.crc32(payload)))
        self._file.write(payload)

    def close(self):
        index_offset = self._file.tell()
        for offset in self._offsets:
            self._file.write(struct.pack("<Q", offset))
        self._file.write(_FOOTER.pack(len(self._offsets), index_offset, FOOTER_MAGIC))
        self._file.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_records(path: str, records) -> int:
    with Writer(path) as writer:
        count = 0
        for record in records:
            writer.write(record)
            count += 1
    return count


def _read_footer(f) -> tuple:
    f.seek(0, os.SEEK_END)
    size = f.tell()
    if size < _HEADER.size + _FOOTER.size:
        raise RecordFileError("File too small to be an ETRF record file")
    f.seek(size - _FOOTER.size)
    count, index_offset, magic = _FOOTER.unpack(f.read(_FOOTER.size))
    if magic != FOOTER_MAGIC:
        raise RecordFileError("Bad footer magic (truncated or not an ETRF file)")
    return count, index_offset


def count_records(path: str) -> int:
    native = _native()
    if native is not None:
        try:
            return native.count_records(path)
        except RecordFileError:
            raise
        except OSError as e:
            raise RecordFileError(str(e)) from e
    return _count_records_py(path)


def _count_records_py(path: str) -> int:
    with open(path, "rb") as f:
        header = f.read(_HEADER.size)
        magic, _version = _HEADER.unpack(header)
        if magic != MAGIC:
            raise RecordFileError(f"Bad magic in {path}")
        count, _ = _read_footer(f)
        return count


def read_range(path: str, start: int, end: int) -> Iterator[bytes]:
    """Yield records [start, end) using the index footer to seek directly.
    Dispatches to the native C++ codec when built (one C call per range)."""
    native = _native()
    if native is not None:
        try:
            yield from native.read_range(path, start, end)
        except RecordFileError:
            raise
        except OSError as e:
            raise RecordFileError(str(e)) from e
        return
    yield from _read_range_py(path, start, end)


def _read_range_py(path: str, start: int, end: int) -> Iterator[bytes]:
    with open(path, "rb") as f:
        magic, _version = _HEADER.unpack(f.read(_HEADER.size))
        if magic != MAGIC:
            raise RecordFileError(f"Bad magic in {path}")
        count, index_offset = _read_footer(f)
        start = max(0, start)
        end = min(end, count)
        if start >= end:
            return
        f.seek(index_offset + 8 * start)
        first_offset = struct.unpack("<Q", f.read(8))[0]
        f.seek(first_offset)
        for _ in range(end - start):
            length, crc = _RECORD_HEAD.unpack(f.read(_RECORD_HEAD.size))
            payload = f.read(length)
            if len(payload) != length:
                raise RecordFileError("Truncated record")
            if zlib.crc32(payload) != crc:
                raise RecordFileError("CRC mismatch (corrupt record)")
            yield payload


def read_all(path: str) -> Iterator[bytes]:
    yield from read_range(path, 0, count_records(path))


def read_range_buffers(path: str, start: int, end: int,
                       max_bytes: int = 0):
    """Yield (payload_buffer np.uint8, lengths np.uint32) chunks of
    records [start, end) — the vectorized data-plane path: payloads ride
    one contiguous buffer per chunk with NO per-record Python objects,
    feeding data/vectorized.py's RecordLayout.parse_buffer directly.
    Native codec when built; Python fallback assembles equivalent
    chunks.

    `max_bytes` overrides the default per-chunk payload bound.
    Consumers that concatenate the chunks anyway (the columnar task
    path) pass their whole-task budget: one chunk instead of N both
    skips the concatenate pass and HALVES peak memory (no chunks+copy
    coexistence) — at image record sizes that pass was ~20% of the
    host pipeline."""
    import numpy as np

    native = _native()
    if native is not None:
        try:
            yield from native.read_range_buffers(
                path, start, end, max_bytes=max_bytes
            )
        except RecordFileError:
            raise
        except OSError as e:
            raise RecordFileError(str(e)) from e
        return
    # Same chunk bounds as the native codec (one source of truth).
    from elasticdl_tpu.native import NativeRecordFile

    # The fallback IGNORES a larger max_bytes: it accumulates per-record
    # bytes objects before the join, so honoring a 1 GiB budget would
    # hold the object list AND the joined copy simultaneously (~2x task
    # bytes + object overhead) — the opposite of the memory win the
    # budget buys on the native path.  Downstream columnar consumers
    # already handle multi-chunk results (they concatenate), so a
    # smaller-than-requested chunking is always correct.
    max_records = NativeRecordFile.CHUNK_RECORDS
    max_bytes = min(max_bytes or NativeRecordFile.CHUNK_BYTES,
                    NativeRecordFile.CHUNK_BYTES)

    def emit(records):
        buf = np.frombuffer(b"".join(records), np.uint8)
        return buf, np.asarray([len(r) for r in records], np.uint32)

    chunk_records: list = []
    chunk_bytes = 0
    for payload in _read_range_py(path, start, end):
        chunk_records.append(payload)
        chunk_bytes += len(payload)
        if len(chunk_records) >= max_records or chunk_bytes >= max_bytes:
            yield emit(chunk_records)
            chunk_records, chunk_bytes = [], 0
    if chunk_records:
        yield emit(chunk_records)
