from elasticdl_tpu.proto import elasticdl_pb2
from elasticdl_tpu.proto.service import (
    MasterServicer,
    MasterStub,
    add_MasterServicer_to_server,
)

__all__ = [
    "elasticdl_pb2",
    "MasterServicer",
    "MasterStub",
    "add_MasterServicer_to_server",
]
