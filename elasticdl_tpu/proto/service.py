"""Hand-written gRPC wiring for the `Master` service.

The environment ships `protoc` (message codegen) but not the gRPC protoc
plugin, so the stub/servicer glue that `elasticdl_pb2_grpc.py` would contain
in the reference (generated from elasticdl/proto/elasticdl.proto) is written
by hand here.  It is equivalent in shape: a `MasterServicer` base class, a
`MasterStub` client, and `add_MasterServicer_to_server`.
"""

from __future__ import annotations

import grpc

from elasticdl_tpu.proto import elasticdl_pb2 as pb

_SERVICE_NAME = "elasticdl_tpu.Master"

# method name -> (request class, response class)
_METHODS = {
    "get_task": (pb.GetTaskRequest, pb.GetTaskResponse),
    "report_task_result": (pb.ReportTaskResultRequest, pb.ReportTaskResultResponse),
    "report_evaluation_metrics": (
        pb.ReportEvaluationMetricsRequest,
        pb.ReportEvaluationMetricsResponse,
    ),
    "report_version": (pb.ReportVersionRequest, pb.ReportVersionResponse),
    "get_comm_rank": (pb.GetCommRankRequest, pb.GetCommRankResponse),
    "report_worker_liveness": (
        pb.ReportWorkerLivenessRequest,
        pb.ReportWorkerLivenessResponse,
    ),
    "get_shard_checkpoint": (pb.ShardCheckpointRequest, pb.ShardCheckpointResponse),
}


class MasterServicer:
    """Base class; override each method. Unimplemented methods return UNIMPLEMENTED."""

    def _unimplemented(self, request, context):
        context.set_code(grpc.StatusCode.UNIMPLEMENTED)
        context.set_details("Method not implemented")
        raise NotImplementedError("Method not implemented")


for _name in _METHODS:
    setattr(MasterServicer, _name, MasterServicer._unimplemented)


def add_MasterServicer_to_server(servicer, server):
    handlers = {}
    for name, (req_cls, resp_cls) in _METHODS.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE_NAME, handlers),)
    )


class MasterStub:
    """Client stub for the Master service."""

    def __init__(self, channel: grpc.Channel):
        for name, (req_cls, resp_cls) in _METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{_SERVICE_NAME}/{name}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString,
                ),
            )
