from elasticdl_tpu.preprocessing.layers import (  # noqa: F401
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    Normalizer,
    RoundIdentity,
    to_padded_ids,
)
from elasticdl_tpu.preprocessing.feature_column import (  # noqa: F401
    FeatureLayer,
    bucketized_column,
    categorical_column_with_hash_bucket,
    categorical_column_with_identity,
    categorical_column_with_vocabulary_list,
    crossed_column,
    embedding_column,
    numeric_column,
    shared_embedding_columns,
)
