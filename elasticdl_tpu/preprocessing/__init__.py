from elasticdl_tpu.preprocessing.layers import (  # noqa: F401
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    Normalizer,
    RoundIdentity,
    to_padded_ids,
)
