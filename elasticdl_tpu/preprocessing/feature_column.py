"""Feature-column glue: declarative feature specs over the transforms.

Parity: elasticdl_preprocessing/feature_column/ in the reference (~400 LoC
of TF feature-column compatible glue — numeric_column, bucketized_column,
categorical_column_with_*, crossed_column, embedding_column — that lets a
model declare its input schema once and get both the input pipeline and
the embedding-table wiring from it).

TPU-first shape: a `FeatureLayer` compiles the declared columns into ONE
host transform `raw batch dict -> {"dense": [B, D] f32, "cat": [B, K] i32}`
— fixed shapes, strings resolved on host, every categorical family offset
into a disjoint range of a single shared id space (the packed-table-
friendly layout the CTR models already use; see ConcatenateWithOffset).
The model side needs exactly one `layers.Embedding(layer.total_id_space,
dim)` per embedding group instead of per-feature tables, which is the
lookup-batching trick the reference's shared embedding columns exist for.

Same-object train==serve consistency holds by construction: the
FeatureLayer instance used by dataset_fn is the one serving callers use.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu.preprocessing.layers import (
    ConcatenateWithOffset,
    Discretization,
    Hashing,
    IndexLookup,
    Normalizer,
    RoundIdentity,
)


class FeatureColumn:
    """Base: every column names the raw feature(s) it consumes."""

    key: str


@dataclass
class NumericColumn(FeatureColumn):
    key: str
    normalizer: Optional[Normalizer] = None

    def values(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        x = np.asarray(batch[self.key], np.float32)
        if self.normalizer is not None:
            x = self.normalizer(x)
        return x.reshape(len(x), -1)


class CategoricalColumn(FeatureColumn):
    """Base for id-producing columns: `num_ids` sizes the id space,
    `ids(batch)` yields [B] (or [B, W] multi-hot) int32 in [0, num_ids)
    with negative = padding."""

    @property
    def num_ids(self) -> int:
        raise NotImplementedError

    def ids(self, batch: Dict[str, np.ndarray]) -> np.ndarray:
        raise NotImplementedError


@dataclass
class HashedCategoricalColumn(CategoricalColumn):
    key: str
    hashing: Hashing

    @property
    def num_ids(self) -> int:
        return self.hashing.num_bins

    def ids(self, batch):
        return np.asarray(self.hashing(np.asarray(batch[self.key])), np.int32)


@dataclass
class VocabCategoricalColumn(CategoricalColumn):
    key: str
    lookup: IndexLookup

    @property
    def num_ids(self) -> int:
        return self.lookup.vocab_size

    def ids(self, batch):
        return self.lookup(np.asarray(batch[self.key]))


@dataclass
class IdentityCategoricalColumn(CategoricalColumn):
    key: str
    round_identity: RoundIdentity

    @property
    def num_ids(self) -> int:
        return self.round_identity.max_value

    def ids(self, batch):
        return np.asarray(
            self.round_identity(np.asarray(batch[self.key])), np.int32
        )


@dataclass
class BucketizedColumn(CategoricalColumn):
    source: NumericColumn
    discretization: Discretization

    @property
    def key(self) -> str:  # type: ignore[override]
        return self.source.key

    @property
    def num_ids(self) -> int:
        return self.discretization.num_bins

    def ids(self, batch):
        # Bucketize the RAW value (reference semantics: bucketized_column
        # wraps the source column pre-normalization).
        raw = np.asarray(batch[self.source.key], np.float32)
        return np.asarray(self.discretization(raw), np.int32)


@dataclass
class CrossedColumn(CategoricalColumn):
    keys: Tuple[str, ...]
    hashing: Hashing

    @property
    def key(self) -> str:  # type: ignore[override]
        return "_x_".join(self.keys)

    @property
    def num_ids(self) -> int:
        return self.hashing.num_bins

    def ids(self, batch):
        # Vectorized cross: str-cast each column once and join with
        # np.char.add (a per-row Python str() loop here reintroduced the
        # per-record interpreter cost the vectorized data plane removed —
        # O(B) string ops on the dataset_fn hot path).
        cols = [
            np.char.mod("%s", np.asarray(batch[k]).ravel()) for k in self.keys
        ]
        joined = cols[0]
        for col in cols[1:]:
            joined = np.char.add(np.char.add(joined, "\x01"), col)
        return np.asarray(self.hashing(joined), np.int32)


@dataclass
class EmbeddingColumn(FeatureColumn):
    """Marks a categorical column for dense-embedding treatment, with the
    table width the model should use.  `shared_embedding_columns` is just
    several of these with the same `group`."""

    categorical: CategoricalColumn
    dimension: int
    group: str = "default"

    @property
    def key(self) -> str:  # type: ignore[override]
        return self.categorical.key


# -- constructors mirroring the reference's public names ----------------


def numeric_column(key: str, normalizer: Optional[Normalizer] = None):
    return NumericColumn(key, normalizer)


def bucketized_column(source: NumericColumn, boundaries: Sequence[float]):
    return BucketizedColumn(source, Discretization(boundaries))


def categorical_column_with_hash_bucket(key: str, hash_bucket_size: int):
    return HashedCategoricalColumn(key, Hashing(hash_bucket_size))


def categorical_column_with_vocabulary_list(
    key: str, vocabulary: Sequence[str], num_oov_indices: int = 1
):
    return VocabCategoricalColumn(
        key, IndexLookup(vocabulary, num_oov_indices)
    )


def categorical_column_with_identity(key: str, num_buckets: int):
    return IdentityCategoricalColumn(key, RoundIdentity(num_buckets))


def crossed_column(keys: Sequence[str], hash_bucket_size: int):
    return CrossedColumn(tuple(keys), Hashing(hash_bucket_size, salt=2))


def embedding_column(
    categorical: CategoricalColumn, dimension: int, group: str = "default"
):
    return EmbeddingColumn(categorical, dimension, group)


def shared_embedding_columns(
    categoricals: Sequence[CategoricalColumn],
    dimension: int,
    group: str = "shared",
):
    return [EmbeddingColumn(c, dimension, group) for c in categoricals]


# -- the layer ----------------------------------------------------------


@dataclass
class _Group:
    columns: List[CategoricalColumn] = field(default_factory=list)
    dimension: int = 0


class FeatureLayer:
    """Compile declared columns into one batch transform.

    `__call__(raw)` takes a dict of same-length raw feature arrays and
    returns the model inputs:

    - `"dense"`: [B, D] float32 — numeric columns, concatenated in
      declaration order (empty key omitted when there are none);
    - `"cat"` (per embedding group, named `"cat"` for the default group,
      `"cat_<group>"` otherwise): [B, K] int32 ids offset into the
      group's shared id space.

    `embedding_specs()` -> {group: (total_id_space, dimension)} sizes the
    model's Embedding tables.  Bare CategoricalColumns (declared without
    embedding_column) join the default group with dimension 0 — callers
    that one-hot or wide-weight them read the id space from
    `embedding_specs` all the same.
    """

    def __init__(self, columns: Sequence[FeatureColumn]):
        self._numeric: List[NumericColumn] = []
        self._groups: Dict[str, _Group] = {}
        for col in columns:
            if isinstance(col, NumericColumn):
                self._numeric.append(col)
            elif isinstance(col, EmbeddingColumn):
                group = self._groups.setdefault(col.group, _Group())
                group.columns.append(col.categorical)
                if group.dimension and group.dimension != col.dimension:
                    raise ValueError(
                        f"Embedding group {col.group!r} mixes dimensions "
                        f"{group.dimension} and {col.dimension}"
                    )
                group.dimension = col.dimension
            elif isinstance(col, CategoricalColumn):
                self._groups.setdefault("default", _Group()).columns.append(
                    col
                )
            else:
                raise TypeError(f"Not a feature column: {col!r}")
        self._offsets = {
            name: ConcatenateWithOffset(
                [c.num_ids for c in group.columns]
            )
            for name, group in self._groups.items()
        }

    def _cat_key(self, group: str) -> str:
        return "cat" if group == "default" else f"cat_{group}"

    def embedding_specs(self) -> Dict[str, Tuple[int, int]]:
        return {
            name: (self._offsets[name].total_id_space, group.dimension)
            for name, group in self._groups.items()
        }

    def total_id_space(self, group: str = "default") -> int:
        return self._offsets[group].total_id_space

    def __call__(self, raw: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        if self._numeric:
            out["dense"] = np.concatenate(
                [c.values(raw) for c in self._numeric], axis=-1
            ).astype(np.float32)
        for name, group in self._groups.items():
            id_cols = [c.ids(raw) for c in group.columns]
            out[self._cat_key(name)] = np.asarray(
                self._offsets[name](id_cols), np.int32
            )
        return out
