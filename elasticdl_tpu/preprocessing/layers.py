"""Feature-preprocessing layers.

Parity: elasticdl_preprocessing/layers in the reference (~1500 LoC of
Keras layers: Hashing, IndexLookup, Discretization, Normalizer,
ConcatenateWithOffset, RoundIdentity, ToSparse) — the transforms CTR
models need to consume raw strings/floats instead of pre-encoded ids.

TPU-first split: a TPU program cannot hold strings, so each transform
declares where it runs —

- HOST transforms (Hashing over strings, IndexLookup, to_padded_ids) run
  in the data pipeline (dataset_fn / reader) on numpy, producing the
  fixed-shape integer/float tensors the compiled model consumes.
- DEVICE transforms (Discretization, Normalizer, RoundIdentity,
  ConcatenateWithOffset, Hashing over ints) are pure jnp functions that
  trace cleanly under jit inside the model.

Every transform is ONE callable usable with both numpy and jax.numpy
inputs with identical semantics, so the exact object used in training's
dataset_fn is reusable at serving time (train==serve consistency, the
property the reference's Keras-layer design exists for — asserted
leaf-by-leaf in tests/test_preprocessing.py).

The reference's ToSparse (dense -> SparseTensor for variable-length
categorical features) has no TPU analogue — XLA wants static shapes — so
its job is done by `to_padded_ids`: ragged id lists become a fixed-width
dense block padded with -1, which `layers.Embedding` already treats as
"no row" (negative-id masking).
"""

from __future__ import annotations

import hashlib
from typing import Dict, Iterable, List, Optional, Sequence, Union

import numpy as np

ArrayLike = Union[np.ndarray, "jax.Array"]  # noqa: F821


def _np_like(x):
    """jnp for traced/device values, np otherwise — keeps one code path
    valid in both the host pipeline and a jitted model."""
    import jax.numpy as jnp

    return jnp if type(x).__module__.startswith("jax") else np


def _mix32(h):
    """Murmur3 fmix32 finalizer — identical bit-for-bit in numpy and jnp
    uint32 arithmetic (no uint64, which jax disables without x64)."""
    xp = _np_like(h)
    h = xp.asarray(h).astype(xp.uint32)
    h = (h ^ (h >> 16)) * xp.uint32(0x85EBCA6B)
    h = (h ^ (h >> 13)) * xp.uint32(0xC2B2AE35)
    return h ^ (h >> 16)


class Hashing:
    """Deterministic hash-bucketing: x -> [0, num_bins).

    Parity: elasticdl_preprocessing Hashing (reference hashes with
    FarmHash64 via tf.strings.to_hash_bucket_fast).  Strings hash on HOST
    (md5-based, stable across processes and restarts — Python's builtin
    hash() is salted and must never be used here); integers hash with a
    murmur-finalizer that runs identically on host numpy and inside jit.
    """

    def __init__(self, num_bins: int, salt: int = 0):
        if num_bins <= 0:
            raise ValueError("num_bins must be positive")
        self.num_bins = num_bins
        self.salt = salt

    def _hash_str(self, s: str) -> int:
        digest = hashlib.md5(
            (f"{self.salt}\x00" + s).encode("utf-8")
        ).digest()
        return int.from_bytes(digest[:8], "little") % self.num_bins

    def __call__(self, x: ArrayLike) -> ArrayLike:
        arr = x if hasattr(x, "dtype") else np.asarray(x)
        if hasattr(arr, "dtype") and arr.dtype.kind in ("U", "S", "O"):
            flat = np.asarray(arr).ravel()
            out = np.fromiter(
                (self._hash_str(str(s)) for s in flat),
                count=flat.size,
                dtype=np.int32,
            )
            return out.reshape(np.shape(arr))
        xp = _np_like(arr)
        h = _mix32(
            xp.asarray(arr).astype(xp.uint32) ^ xp.uint32(self.salt)
        )
        return (h % xp.uint32(self.num_bins)).astype(xp.int32)


class IndexLookup:
    """Vocabulary lookup: token -> index; unknown tokens map to OOV ids.

    Parity: elasticdl_preprocessing IndexLookup.  Layout matches the
    reference: indices [0, num_oov_indices) are OOV buckets (hashed when
    more than one), vocabulary tokens follow.  HOST transform (strings).
    """

    def __init__(
        self,
        vocabulary: Sequence[str],
        num_oov_indices: int = 1,
    ):
        if num_oov_indices < 0:
            raise ValueError("num_oov_indices must be >= 0")
        self.vocabulary: List[str] = list(vocabulary)
        self.num_oov_indices = num_oov_indices
        self._table: Dict[str, int] = {
            token: i + num_oov_indices
            for i, token in enumerate(self.vocabulary)
        }
        self._oov_hash = Hashing(max(1, num_oov_indices), salt=1)

    @property
    def vocab_size(self) -> int:
        """Total id space including OOV buckets (embedding input_dim)."""
        return len(self.vocabulary) + self.num_oov_indices

    def _lookup_one(self, token: str) -> int:
        idx = self._table.get(token)
        if idx is not None:
            return idx
        if self.num_oov_indices == 0:
            raise KeyError(f"Token {token!r} not in vocabulary (no OOV)")
        if self.num_oov_indices == 1:
            return 0
        return int(self._oov_hash(np.asarray([token], object))[0])

    def __call__(self, x: ArrayLike) -> np.ndarray:
        arr = np.asarray(x)
        flat = arr.ravel()
        out = np.fromiter(
            (self._lookup_one(str(s)) for s in flat),
            count=flat.size,
            dtype=np.int32,
        )
        return out.reshape(arr.shape)


class Discretization:
    """Bucketize by boundaries: value -> bin index in [0, len(bins)].

    Parity: elasticdl_preprocessing Discretization.  DEVICE transform
    (searchsorted lowers to XLA); same call works on host numpy.
    """

    def __init__(self, bin_boundaries: Sequence[float]):
        self.bin_boundaries = [float(b) for b in bin_boundaries]
        if sorted(self.bin_boundaries) != self.bin_boundaries:
            raise ValueError("bin_boundaries must be ascending")

    @property
    def num_bins(self) -> int:
        return len(self.bin_boundaries) + 1

    def __call__(self, x: ArrayLike) -> ArrayLike:
        xp = _np_like(x)
        bounds = xp.asarray(self.bin_boundaries, xp.float32)
        return xp.searchsorted(
            bounds, xp.asarray(x, xp.float32), side="right"
        ).astype(xp.int32)


class Normalizer:
    """(x - subtract) / divide, elementwise.

    Parity: elasticdl_preprocessing Normalizer (the standardize/min-max
    scaling layer).  DEVICE transform; fuses into adjacent XLA ops.
    """

    def __init__(self, subtract: float = 0.0, divide: float = 1.0):
        if divide == 0.0:
            raise ValueError("divide must be nonzero")
        self.subtract = float(subtract)
        self.divide = float(divide)

    @classmethod
    def from_stats(cls, mean: float, std: float) -> "Normalizer":
        return cls(subtract=mean, divide=std if std else 1.0)

    def __call__(self, x: ArrayLike) -> ArrayLike:
        xp = _np_like(x)
        x = xp.asarray(x, xp.float32)
        return (x - xp.float32(self.subtract)) / xp.float32(self.divide)


class RoundIdentity:
    """Round a numeric feature into an integer id in [0, max_value).

    Parity: elasticdl_preprocessing RoundIdentity (numeric -> embedding id
    without binning).  DEVICE transform.
    """

    def __init__(self, max_value: int):
        if max_value <= 0:
            raise ValueError("max_value must be positive")
        self.max_value = int(max_value)

    def __call__(self, x: ArrayLike) -> ArrayLike:
        xp = _np_like(x)
        ids = xp.round(xp.asarray(x, xp.float32))
        return xp.clip(ids, 0, self.max_value - 1).astype(xp.int32)


class ConcatenateWithOffset:
    """Concatenate id columns, offsetting each into a disjoint id range —
    the shared-embedding-table trick (one [sum(sizes), dim] table serves
    every categorical feature with a single lookup).

    Parity: elasticdl_preprocessing ConcatenateWithOffset.  DEVICE
    transform.  Negative ids (padding, see to_padded_ids) stay negative:
    offsetting a pad row would turn "no row" into a real row.
    """

    def __init__(self, id_space_sizes: Sequence[int]):
        self.id_space_sizes = [int(s) for s in id_space_sizes]
        offsets = np.concatenate(
            [[0], np.cumsum(self.id_space_sizes[:-1])]
        ).astype(np.int32)
        self.offsets = offsets

    @property
    def total_id_space(self) -> int:
        return int(sum(self.id_space_sizes))

    def __call__(self, columns: Iterable[ArrayLike]) -> ArrayLike:
        columns = list(columns)
        if len(columns) != len(self.id_space_sizes):
            raise ValueError(
                f"Expected {len(self.id_space_sizes)} columns, "
                f"got {len(columns)}"
            )
        xp = _np_like(columns[0])
        shifted = []
        for column, offset in zip(columns, self.offsets):
            ids = xp.asarray(column, xp.int32)
            if ids.ndim == 1:
                ids = ids[:, None]
            shifted.append(xp.where(ids >= 0, ids + xp.int32(offset), ids))
        return xp.concatenate(shifted, axis=-1)


def to_padded_ids(
    rows: Sequence[Sequence[int]],
    max_len: int,
    pad_id: int = -1,
    dtype=np.int32,
) -> np.ndarray:
    """Ragged id lists -> fixed [len(rows), max_len] dense block padded
    with `pad_id` (the reference ToSparse's job, reshaped for XLA's
    static-shape world; layers.Embedding masks ids < 0).  Overlong rows
    truncate — deterministically, keeping the first max_len ids."""
    out = np.full((len(rows), max_len), pad_id, dtype=dtype)
    for i, row in enumerate(rows):
        take = min(len(row), max_len)
        if take:
            out[i, :take] = np.asarray(row[:take], dtype=dtype)
    return out
