"""Native (C++) host kernel library: build + ctypes bindings.

Parity: the reference's native layer (elasticdl/pkg/kernel — cgo bindings
over Eigen C++ kernels).  The build is a single translation unit compiled
to a shared library; bindings are ctypes (the environment ships no
pybind11), with numpy arrays passed as raw pointers.

`load()` returns the bound library, building it on first use when a C++
toolchain is present; callers treat None as "native unavailable" and fall
back to the pure-Python/JAX paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("native")

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO_PATH = os.path.join(_DIR, "libedl_kernels.so")
_SOURCES = [
    os.path.join(_DIR, "kernel_api.cc"),
    os.path.join(_DIR, "recordfile.cc"),
]
_lib = None
_load_failed = False


def build_native(force: bool = False) -> Optional[str]:
    """Compile the native sources -> libedl_kernels.so. Returns the path,
    or None when no toolchain / compile failure."""
    if os.path.exists(_SO_PATH):
        if not force and os.path.getmtime(_SO_PATH) >= max(
            os.path.getmtime(src) for src in _SOURCES
        ):
            return _SO_PATH
        # Unlink before relinking: if the stale .so is already dlopen'd,
        # a fresh inode is the only way a retry CDLL sees the new build
        # (dlopen caches by pathname/inode), and overwriting a mapped
        # file risks SIGBUS in the running process.
        try:
            os.unlink(_SO_PATH)
        except OSError:
            pass
    # Prefer linking zlib for its optimized CRC-32 (measured 2.1x the
    # in-file slicing-by-8 — recordfile.cc); fall back to the
    # self-contained build where zlib headers aren't installed.
    variants = (
        ["-DEDL_USE_ZLIB"], [],
    )
    for compiler in ("g++", "c++", "clang++"):
        zlib_failed = False
        for extra in variants:
            try:
                subprocess.run(
                    [compiler, "-O3", "-shared", "-fPIC", "-std=c++17",
                     *extra, *_SOURCES, "-o", _SO_PATH,
                     *(["-lz"] if extra else [])],
                    check=True, capture_output=True, timeout=120,
                )
                if zlib_failed:
                    # Succeeded only WITHOUT zlib: say so — the silent
                    # symptom is large-record CRC at ~1.8 GB/s instead
                    # of ~4 (missing zlib.h, usually).
                    logger.warning(
                        "zlib-CRC native build failed (no zlib dev "
                        "headers?); built the slower self-contained "
                        "CRC variant"
                    )
                logger.info(
                    "Built native library with %s%s -> %s", compiler,
                    " (+zlib crc)" if extra else "", _SO_PATH,
                )
                return _SO_PATH
            except FileNotFoundError:
                break  # compiler missing; try the next compiler
            except subprocess.CalledProcessError as exc:
                if extra:
                    zlib_failed = True
                    continue  # zlib variant failed; retry without
                # The plain variant failing is a genuine source/compile
                # error — fail fast, don't re-run it per compiler.
                logger.error(
                    "Native build failed (%s): %s",
                    compiler, exc.stderr.decode()[:2000],
                )
                return None
    logger.warning("No C++ compiler found; native library unavailable")
    return None


# Must match edl_abi_version() in recordfile.cc; bump both on any C-ABI
# change so a stale .so can never be called with shifted arguments.
_ABI_VERSION = 2


def _bind(lib):
    # ABI gate FIRST: a pre-versioning .so lacks the symbol entirely
    # (AttributeError), an outdated one returns the wrong number — both
    # route to the rebuild path in load().
    lib.edl_abi_version.restype = ctypes.c_longlong
    found = int(lib.edl_abi_version())
    if found != _ABI_VERSION:
        raise AttributeError(
            f"native ABI {found} != expected {_ABI_VERSION} (stale .so)"
        )
    f32p = ctypes.POINTER(ctypes.c_float)
    i64p = ctypes.POINTER(ctypes.c_int64)
    i64 = ctypes.c_int64
    f32 = ctypes.c_float
    i32 = ctypes.c_int
    lib.edl_sgd_dense.argtypes = [f32p, f32p, f32, i64]
    lib.edl_momentum_dense.argtypes = [f32p, f32p, f32p, f32, f32, i32, i64]
    lib.edl_adagrad_dense.argtypes = [f32p, f32p, f32p, f32, f32, i64]
    lib.edl_adam_dense.argtypes = [f32p, f32p, f32p, f32p, f32, f32, f32, f32,
                                   i64, i64]
    lib.edl_sgd_sparse.argtypes = [f32p, i64, i64p, f32p, i64, f32]
    lib.edl_momentum_sparse.argtypes = [f32p, f32p, i64, i64p, f32p, i64, f32,
                                        f32, i32]
    lib.edl_adagrad_sparse.argtypes = [f32p, f32p, i64, i64p, f32p, i64, f32,
                                       f32]
    lib.edl_adam_sparse.argtypes = [f32p, f32p, f32p, i64p, i64, i64p, f32p,
                                    i64, f32, f32, f32, f32]
    # Record file (ETRF) codec.
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    ll = ctypes.c_longlong
    voidp = ctypes.c_void_p
    lib.edl_rf_last_error.restype = ctypes.c_char_p
    lib.edl_rf_open.argtypes = [ctypes.c_char_p]
    lib.edl_rf_open.restype = voidp
    lib.edl_rf_count.argtypes = [voidp]
    lib.edl_rf_count.restype = ll
    lib.edl_rf_range_size.argtypes = [voidp, ll, ll]
    lib.edl_rf_range_size.restype = ll
    lib.edl_rf_read_range.argtypes = [voidp, ll, ll, u8p, ll, u32p]
    lib.edl_rf_read_range.restype = ll
    lib.edl_rf_close.argtypes = [voidp]
    lib.edl_rf_writer_open.argtypes = [ctypes.c_char_p]
    lib.edl_rf_writer_open.restype = voidp
    lib.edl_rf_writer_write.argtypes = [voidp, u8p, ctypes.c_uint32]
    lib.edl_rf_writer_write.restype = i32
    lib.edl_rf_writer_close.argtypes = [voidp]
    lib.edl_rf_writer_close.restype = i32
    return lib


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    path = build_native()
    if path is None:
        _load_failed = True
        return None
    try:
        _lib = _bind(ctypes.CDLL(path))
    except (OSError, AttributeError):
        # Corrupt/arch-mismatched .so, or a stale one predating newer
        # symbols but with a fresher mtime (tar/rsync preserve source
        # timestamps): rebuild once from source before giving up.
        logger.warning("Native library at %s unusable; rebuilding", path)
        path = build_native(force=True)
        if path is None:
            _load_failed = True
            return None
        try:
            _lib = _bind(ctypes.CDLL(path))
        except Exception:
            logger.exception("Rebuilt native library still unusable")
            _load_failed = True
            return None
    return _lib


def _fp(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _ip(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _check(a, dtype):
    a = np.ascontiguousarray(a, dtype)
    return a


class NativeKernels:
    """Numpy-facing wrapper over the C bindings (in-place updates)."""

    def __init__(self):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native kernels unavailable (no C++ toolchain)")

    # Dense -------------------------------------------------------------

    def sgd(self, param, grad, lr):
        self._lib.edl_sgd_dense(_fp(param), _fp(grad), lr, param.size)

    def momentum(self, param, velocity, grad, lr, mu, nesterov=False):
        self._lib.edl_momentum_dense(
            _fp(param), _fp(velocity), _fp(grad), lr, mu, int(nesterov),
            param.size,
        )

    def adagrad(self, param, accum, grad, lr, eps=1e-7):
        self._lib.edl_adagrad_dense(
            _fp(param), _fp(accum), _fp(grad), lr, eps, param.size
        )

    def adam(self, param, m, v, grad, lr, beta1, beta2, eps, step):
        self._lib.edl_adam_dense(
            _fp(param), _fp(m), _fp(v), _fp(grad), lr, beta1, beta2, eps,
            step, param.size,
        )

    # Sparse ------------------------------------------------------------

    def sgd_sparse(self, table, ids, grads, lr):
        ids = _check(ids, np.int64)
        self._lib.edl_sgd_sparse(
            _fp(table), table.shape[1], _ip(ids), _fp(grads), len(ids), lr
        )

    def momentum_sparse(self, table, velocity, ids, grads, lr, mu,
                        nesterov=False):
        ids = _check(ids, np.int64)
        self._lib.edl_momentum_sparse(
            _fp(table), _fp(velocity), table.shape[1], _ip(ids), _fp(grads),
            len(ids), lr, mu, int(nesterov),
        )

    def adagrad_sparse(self, table, accum, ids, grads, lr, eps=1e-7):
        ids = _check(ids, np.int64)
        self._lib.edl_adagrad_sparse(
            _fp(table), _fp(accum), table.shape[1], _ip(ids), _fp(grads),
            len(ids), lr, eps,
        )

    def adam_sparse(self, table, m, v, t_rows, ids, grads, lr,
                    beta1=0.9, beta2=0.999, eps=1e-8):
        ids = _check(ids, np.int64)
        self._lib.edl_adam_sparse(
            _fp(table), _fp(m), _fp(v), _ip(t_rows), table.shape[1],
            _ip(ids), _fp(grads), len(ids), lr, beta1, beta2, eps,
        )


class NativeRecordFile:
    """Native ETRF codec bindings (data/recordfile.py format).

    Batch read: one C call per [start, end) range returns concatenated
    payloads + lengths — a single Python<->C crossing per task instead of
    per record (parity: the reference's pyrecordio over C++ recordio)."""

    def __init__(self):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError(
                "native record file unavailable (no C++ toolchain)"
            )

    def _error(self) -> str:
        return self._lib.edl_rf_last_error().decode(errors="replace")

    def count_records(self, path: str) -> int:
        handle = self._lib.edl_rf_open(path.encode())
        if not handle:
            raise IOError(self._error())
        try:
            return int(self._lib.edl_rf_count(handle))
        finally:
            self._lib.edl_rf_close(handle)

    # Chunk bounds: one C crossing per CHUNK_RECORDS records, split further
    # if a chunk's payload exceeds CHUNK_BYTES — memory stays bounded like
    # the streaming Python codec, unlike a single whole-range buffer which
    # would OOM on a big task (records_per_task * record size).
    CHUNK_RECORDS = 4096
    CHUNK_BYTES = 128 * 1024 * 1024

    def read_range(self, path: str, start: int, end: int):
        """Yield payload bytes of records [start, end) (CRC-checked) —
        a per-record splitter over read_range_buffers."""
        for buf, lengths in self.read_range_buffers(path, start, end):
            view = memoryview(buf)
            offset = 0
            for length in lengths:
                yield bytes(view[offset : offset + int(length)])
                offset += int(length)

    def read_range_buffers(self, path: str, start: int, end: int,
                           max_bytes: int = 0):
        """Yield (payloads np.uint8 buffer, lengths np.uint32) CHUNKS of
        records [start, end) — payloads back-to-back, no per-record
        Python objects (the vectorized data-plane path; see
        data/vectorized.py).  `max_bytes` overrides the default chunk
        byte bound (and lifts the record cap — the caller's byte budget
        is the bound; see data/recordfile.read_range_buffers)."""
        bytes_cap = max_bytes or self.CHUNK_BYTES
        handle = self._lib.edl_rf_open(path.encode())
        if not handle:
            raise IOError(self._error())
        try:
            count = int(self._lib.edl_rf_count(handle))
            start = max(0, start)
            end = min(end, count)
            pos = start
            while pos < end:
                n = (
                    end - pos if max_bytes
                    else min(self.CHUNK_RECORDS, end - pos)
                )
                total = int(self._lib.edl_rf_range_size(handle, pos, pos + n))
                if total < 0:
                    raise IOError(self._error())
                while n > 1 and total > bytes_cap:
                    n //= 2  # range_size is O(1) over the index
                    total = int(
                        self._lib.edl_rf_range_size(handle, pos, pos + n)
                    )
                    if total < 0:
                        raise IOError(self._error())
                buf = np.empty(total, np.uint8)
                lengths = np.empty(n, np.uint32)
                read = self._lib.edl_rf_read_range(
                    handle,
                    pos,
                    pos + n,
                    buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    total,
                    lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
                )
                if read < 0:
                    raise IOError(self._error())
                used = int(lengths[:read].sum())
                yield buf[:used], lengths[:read]
                pos += read
        finally:
            self._lib.edl_rf_close(handle)

    def write_records(self, path: str, records) -> int:
        handle = self._lib.edl_rf_writer_open(path.encode())
        if not handle:
            raise IOError(self._error())
        count = 0
        try:
            for payload in records:
                payload = bytes(payload)
                arr = np.frombuffer(payload, np.uint8)
                status = self._lib.edl_rf_writer_write(
                    handle,
                    arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    len(payload),
                )
                if status != 0:
                    raise IOError(self._error())
                count += 1
        finally:
            if self._lib.edl_rf_writer_close(handle) != 0:
                raise IOError(self._error())
        return count


_record_file: Optional[NativeRecordFile] = None
_record_file_failed = False


def record_file() -> Optional[NativeRecordFile]:
    """Singleton NativeRecordFile, or None when native is unavailable.
    Catches EVERYTHING construction can throw (no toolchain, corrupt or
    arch-mismatched .so from CDLL, stale .so missing the edl_rf_* symbols
    in _bind) — the Python codec is the always-available fallback and a
    broken native build must never take the data plane down."""
    global _record_file, _record_file_failed
    if _record_file is None and not _record_file_failed:
        try:
            _record_file = NativeRecordFile()
        except Exception:
            logger.exception(
                "Native record file unavailable; using the Python codec"
            )
            _record_file_failed = True
    return _record_file
