// Host-side optimizer kernels: dense and sparse (indexed-rows) apply.
//
// Parity: the reference's cgo/C++ kernels
// (elasticdl/pkg/kernel/capi/kernel_api.cc — Eigen-backed
// SGD/Adam/Momentum/AdaGrad plus their *SparseApply variants used by the
// Go parameter server on pushed IndexedSlices).  On TPU the production
// update path is XLA-compiled (parallel/sparse_optim.py); this library is
// the native mirror of that math for host-side application (CPU-resident
// tables, feature pipelines) and for cross-implementation parity tests —
// both suites check against the same golden values.
//
// Sparse semantics match sparse_optim.py exactly: duplicate ids within one
// apply are segment-summed first, then each unique row is updated once.
// Zero-gradient rows (padding) are skipped entirely.
//
// Build: g++ -O3 -shared -fPIC kernel_api.cc -o libedl_kernels.so
// (see elasticdl_tpu/native/__init__.py::build_native).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Dense kernels.
// ---------------------------------------------------------------------------

void edl_sgd_dense(float* param, const float* grad, float lr, int64_t n) {
  for (int64_t i = 0; i < n; ++i) param[i] -= lr * grad[i];
}

void edl_momentum_dense(float* param, float* velocity, const float* grad,
                        float lr, float mu, int nesterov, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    velocity[i] = mu * velocity[i] + grad[i];
    const float step = nesterov ? mu * velocity[i] + grad[i] : velocity[i];
    param[i] -= lr * step;
  }
}

void edl_adagrad_dense(float* param, float* accum, const float* grad,
                       float lr, float eps, int64_t n) {
  for (int64_t i = 0; i < n; ++i) {
    accum[i] += grad[i] * grad[i];
    param[i] -= lr * grad[i] / (std::sqrt(accum[i]) + eps);
  }
}

void edl_adam_dense(float* param, float* m, float* v, const float* grad,
                    float lr, float beta1, float beta2, float eps,
                    int64_t step, int64_t n) {
  const float bc1 = 1.0f - std::pow(beta1, static_cast<float>(step));
  const float bc2 = 1.0f - std::pow(beta2, static_cast<float>(step));
  for (int64_t i = 0; i < n; ++i) {
    m[i] = beta1 * m[i] + (1.0f - beta1) * grad[i];
    v[i] = beta2 * v[i] + (1.0f - beta2) * grad[i] * grad[i];
    param[i] -= lr * (m[i] / bc1) / (std::sqrt(v[i] / bc2) + eps);
  }
}

// ---------------------------------------------------------------------------
// Sparse (indexed-rows) kernels.  grads is [n_ids, dim] row-major.
// ---------------------------------------------------------------------------

namespace {

// Segment-sum duplicate ids; returns unique ids (first-seen order) and the
// summed gradient rows.  Rows whose summed gradient is entirely zero are
// dropped (padding must not touch slots).
void dedup(const int64_t* ids, const float* grads, int64_t n_ids,
           int64_t dim, std::vector<int64_t>* out_ids,
           std::vector<float>* out_grads) {
  std::unordered_map<int64_t, int64_t> slot;  // id -> index in out
  slot.reserve(static_cast<size_t>(n_ids) * 2);
  for (int64_t i = 0; i < n_ids; ++i) {
    auto it = slot.find(ids[i]);
    int64_t row;
    if (it == slot.end()) {
      row = static_cast<int64_t>(out_ids->size());
      slot.emplace(ids[i], row);
      out_ids->push_back(ids[i]);
      out_grads->insert(out_grads->end(), dim, 0.0f);
    } else {
      row = it->second;
    }
    float* acc = out_grads->data() + row * dim;
    const float* g = grads + i * dim;
    for (int64_t d = 0; d < dim; ++d) acc[d] += g[d];
  }
}

bool all_zero(const float* g, int64_t dim) {
  for (int64_t d = 0; d < dim; ++d)
    if (g[d] != 0.0f) return false;
  return true;
}

}  // namespace

void edl_sgd_sparse(float* table, int64_t dim, const int64_t* ids,
                    const float* grads, int64_t n_ids, float lr) {
  std::vector<int64_t> uids;
  std::vector<float> ugrads;
  dedup(ids, grads, n_ids, dim, &uids, &ugrads);
  for (size_t r = 0; r < uids.size(); ++r) {
    float* row = table + uids[r] * dim;
    const float* g = ugrads.data() + r * dim;
    for (int64_t d = 0; d < dim; ++d) row[d] -= lr * g[d];
  }
}

void edl_momentum_sparse(float* table, float* velocity, int64_t dim,
                         const int64_t* ids, const float* grads,
                         int64_t n_ids, float lr, float mu, int nesterov) {
  std::vector<int64_t> uids;
  std::vector<float> ugrads;
  dedup(ids, grads, n_ids, dim, &uids, &ugrads);
  for (size_t r = 0; r < uids.size(); ++r) {
    const float* g = ugrads.data() + r * dim;
    if (all_zero(g, dim)) continue;
    float* row = table + uids[r] * dim;
    float* vel = velocity + uids[r] * dim;
    for (int64_t d = 0; d < dim; ++d) {
      vel[d] = mu * vel[d] + g[d];
      const float step = nesterov ? mu * vel[d] + g[d] : vel[d];
      row[d] -= lr * step;
    }
  }
}

void edl_adagrad_sparse(float* table, float* accum, int64_t dim,
                        const int64_t* ids, const float* grads,
                        int64_t n_ids, float lr, float eps) {
  std::vector<int64_t> uids;
  std::vector<float> ugrads;
  dedup(ids, grads, n_ids, dim, &uids, &ugrads);
  for (size_t r = 0; r < uids.size(); ++r) {
    const float* g = ugrads.data() + r * dim;
    float* row = table + uids[r] * dim;
    float* acc = accum + uids[r] * dim;
    for (int64_t d = 0; d < dim; ++d) {
      acc[d] += g[d] * g[d];
      row[d] -= lr * g[d] / (std::sqrt(acc[d]) + eps);
    }
  }
}

void edl_adam_sparse(float* table, float* m, float* v, int64_t* t_rows,
                     int64_t dim, const int64_t* ids, const float* grads,
                     int64_t n_ids, float lr, float beta1, float beta2,
                     float eps) {
  std::vector<int64_t> uids;
  std::vector<float> ugrads;
  dedup(ids, grads, n_ids, dim, &uids, &ugrads);
  for (size_t r = 0; r < uids.size(); ++r) {
    const float* g = ugrads.data() + r * dim;
    if (all_zero(g, dim)) continue;
    const int64_t id = uids[r];
    t_rows[id] += 1;
    const float t = static_cast<float>(t_rows[id]);
    const float bc1 = 1.0f - std::pow(beta1, t);
    const float bc2 = 1.0f - std::pow(beta2, t);
    float* row = table + id * dim;
    float* mr = m + id * dim;
    float* vr = v + id * dim;
    for (int64_t d = 0; d < dim; ++d) {
      mr[d] = beta1 * mr[d] + (1.0f - beta1) * g[d];
      vr[d] = beta2 * vr[d] + (1.0f - beta2) * g[d] * g[d];
      row[d] -= lr * (mr[d] / bc1) / (std::sqrt(vr[d] / bc2) + eps);
    }
  }
}

}  // extern "C"
