// Native ETRF record-file codec.
//
// Parity: the reference's RecordIO dependency is a C++ library with
// language bindings (pyrecordio); this is the equivalent native fast path
// for this framework's ETRF format, byte-identical with the pure-Python
// codec in elasticdl_tpu/data/recordfile.py:
//
//   header:  magic "ETRF" + u32 version (little-endian)
//   record:  u32 payload_length + u32 crc32(payload) + payload
//   footer:  u64 record_count + u64 index_offset + magic "FTRE"
//            index (at index_offset) = record_count u64 file offsets
//
// The C API is batch-oriented: one call reads a whole [start, end) range
// (CRC-checked) into a caller buffer with per-record lengths — a single
// Python<->C crossing per task instead of per record, which is where the
// native reader earns its keep on the data plane.  Thread-safety: one
// reader/writer handle per thread; error text is thread-local.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#ifdef EDL_USE_ZLIB
#include <zlib.h>
#endif

namespace {

constexpr char kMagic[4] = {'E', 'T', 'R', 'F'};
constexpr char kFooterMagic[4] = {'F', 'T', 'R', 'E'};
constexpr uint32_t kVersion = 1;
constexpr size_t kHeaderSize = 8;    // magic + u32 version
constexpr size_t kFooterSize = 20;   // u64 count + u64 index_offset + magic
constexpr size_t kRecordHead = 8;    // u32 len + u32 crc

thread_local std::string g_last_error;

void set_error(const std::string& message) { g_last_error = message; }

// zlib-compatible CRC-32 (polynomial 0xEDB88320).  The byte-at-a-time
// table walk capped the record read path at ~300 MB/s, which for 150 KB
// image records (round-5 image data plane) made CRC the whole
// data-plane bottleneck.  Two implementations, dispatched by payload
// size (all numbers measured on the CI host, /tmp scratch bench):
//
//   - slicing-by-8 (below): ~2-3 GB/s on SMALL payloads — wins under
//     ~512 B because it has no per-call setup;
//   - zlib's crc32 (when built with -DEDL_USE_ZLIB -lz): ~4 GB/s on
//     large payloads, but only ~0.7 GB/s at Criteo's 109 B records —
//     its braided hot loop needs length to amortize.
//
// Crossover measured at ~512-1024 B; dispatch at 512.  Without zlib
// headers the build falls back to slicing-by-8 everywhere.
const uint32_t (*crc_tables())[256] {
  static uint32_t tables[8][256];
  static bool initialized = false;
  if (!initialized) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) {
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      }
      tables[0][i] = c;
    }
    for (int t = 1; t < 8; ++t) {
      for (uint32_t i = 0; i < 256; ++i) {
        uint32_t c = tables[t - 1][i];
        tables[t][i] = tables[0][c & 0xFF] ^ (c >> 8);
      }
    }
    initialized = true;
  }
  return tables;
}

uint32_t crc32_slice8(const uint8_t* data, size_t len) {
  const uint32_t (*t)[256] = crc_tables();
  uint32_t c = 0xFFFFFFFFu;
  while (len >= 8) {
    uint32_t lo;
    uint32_t hi;
    std::memcpy(&lo, data, 4);      // little-endian loads (x86/arm LE)
    std::memcpy(&hi, data + 4, 4);
    c ^= lo;
    c = t[7][c & 0xFF] ^ t[6][(c >> 8) & 0xFF] ^ t[5][(c >> 16) & 0xFF] ^
        t[4][c >> 24] ^ t[3][hi & 0xFF] ^ t[2][(hi >> 8) & 0xFF] ^
        t[1][(hi >> 16) & 0xFF] ^ t[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  for (size_t i = 0; i < len; ++i) {
    c = t[0][(c ^ data[i]) & 0xFF] ^ (c >> 8);
  }
  return c ^ 0xFFFFFFFFu;
}

#ifdef EDL_USE_ZLIB
uint32_t crc32_impl(const uint8_t* data, size_t len) {
  if (len < 512) return crc32_slice8(data, len);
  return static_cast<uint32_t>(::crc32(0L, data, len));
}
#else
uint32_t crc32_impl(const uint8_t* data, size_t len) {
  return crc32_slice8(data, len);
}
#endif  // EDL_USE_ZLIB

uint32_t read_u32(const uint8_t* p) {
  return static_cast<uint32_t>(p[0]) | (static_cast<uint32_t>(p[1]) << 8) |
         (static_cast<uint32_t>(p[2]) << 16) |
         (static_cast<uint32_t>(p[3]) << 24);
}

uint64_t read_u64(const uint8_t* p) {
  return static_cast<uint64_t>(read_u32(p)) |
         (static_cast<uint64_t>(read_u32(p + 4)) << 32);
}

void write_u32(uint8_t* p, uint32_t v) {
  p[0] = v & 0xFF;
  p[1] = (v >> 8) & 0xFF;
  p[2] = (v >> 16) & 0xFF;
  p[3] = (v >> 24) & 0xFF;
}

void write_u64(uint8_t* p, uint64_t v) {
  write_u32(p, static_cast<uint32_t>(v));
  write_u32(p + 4, static_cast<uint32_t>(v >> 32));
}

struct Reader {
  FILE* file = nullptr;
  uint64_t count = 0;
  uint64_t index_offset = 0;
  std::vector<uint64_t> index;  // loaded lazily on first range read
};

struct Writer {
  FILE* file = nullptr;
  std::vector<uint64_t> offsets;
};

bool load_index(Reader* r) {
  if (!r->index.empty() || r->count == 0) return true;
  if (fseek(r->file, static_cast<long>(r->index_offset), SEEK_SET) != 0) {
    set_error("seek to index failed");
    return false;
  }
  std::vector<uint8_t> raw(r->count * 8);
  if (fread(raw.data(), 1, raw.size(), r->file) != raw.size()) {
    set_error("truncated index");
    return false;
  }
  r->index.resize(r->count);
  for (uint64_t i = 0; i < r->count; ++i) {
    r->index[i] = read_u64(raw.data() + i * 8);
  }
  return true;
}

}  // namespace

extern "C" {

// Bumped on ANY C-ABI change (argument lists included): the Python side
// refuses to bind a library whose version doesn't match, which converts
// "stale .so with a fresher mtime called with shifted arguments" from
// heap corruption into a clean rebuild.
long long edl_abi_version() { return 2; }

const char* edl_rf_last_error() { return g_last_error.c_str(); }

// ---------------------------------------------------------------------
// Reader
// ---------------------------------------------------------------------

void* edl_rf_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) {
    set_error(std::string("cannot open ") + path);
    return nullptr;
  }
  uint8_t header[kHeaderSize];
  if (fread(header, 1, kHeaderSize, f) != kHeaderSize ||
      memcmp(header, kMagic, 4) != 0) {
    set_error("bad magic (not an ETRF file)");
    fclose(f);
    return nullptr;
  }
  if (fseek(f, 0, SEEK_END) != 0) {
    set_error("seek failed");
    fclose(f);
    return nullptr;
  }
  long size = ftell(f);
  if (size < static_cast<long>(kHeaderSize + kFooterSize)) {
    set_error("file too small to be an ETRF record file");
    fclose(f);
    return nullptr;
  }
  uint8_t footer[kFooterSize];
  fseek(f, size - static_cast<long>(kFooterSize), SEEK_SET);
  if (fread(footer, 1, kFooterSize, f) != kFooterSize ||
      memcmp(footer + 16, kFooterMagic, 4) != 0) {
    set_error("bad footer magic (truncated or not an ETRF file)");
    fclose(f);
    return nullptr;
  }
  Reader* r = new Reader();
  r->file = f;
  r->count = read_u64(footer);
  r->index_offset = read_u64(footer + 8);
  return r;
}

long long edl_rf_count(void* handle) {
  return static_cast<long long>(static_cast<Reader*>(handle)->count);
}

// Total payload bytes of records [start, end) (clamped); -1 on error.
// O(1): records are contiguous, so the byte span between the start
// record's offset and the end boundary (next record's offset, or the
// index itself for the last record) minus the fixed per-record heads IS
// the payload total — no I/O beyond the already-loaded index.
long long edl_rf_range_size(void* handle, long long start, long long end) {
  Reader* r = static_cast<Reader*>(handle);
  if (start < 0) start = 0;
  if (end > static_cast<long long>(r->count)) end = r->count;
  if (start >= end) return 0;
  if (!load_index(r)) return -1;
  uint64_t boundary = (end < static_cast<long long>(r->count))
                          ? r->index[end]
                          : r->index_offset;
  long long total = static_cast<long long>(boundary - r->index[start]) -
                    static_cast<long long>(kRecordHead) * (end - start);
  if (boundary < r->index[start] || total < 0) {
    set_error("corrupt index (non-monotonic offsets)");
    return -1;
  }
  return total;
}

// Read records [start, end) into buf (payloads back-to-back, at most
// buf_size bytes), lengths[i] = payload length of record start+i.
// CRC-checked; a record whose length field would overrun the caller's
// buffer (corrupt length byte) errors out instead of writing past it.
// Returns records read, or -1 on error.
long long edl_rf_read_range(void* handle, long long start, long long end,
                            uint8_t* buf, long long buf_size,
                            uint32_t* lengths) {
  Reader* r = static_cast<Reader*>(handle);
  if (start < 0) start = 0;
  if (end > static_cast<long long>(r->count)) end = r->count;
  if (start >= end) return 0;
  if (!load_index(r)) return -1;
  if (fseek(r->file, static_cast<long>(r->index[start]), SEEK_SET) != 0) {
    set_error("seek failed");
    return -1;
  }
  uint8_t* out = buf;
  long long remaining = buf_size;
  for (long long i = start; i < end; ++i) {
    uint8_t head[kRecordHead];
    if (fread(head, 1, kRecordHead, r->file) != kRecordHead) {
      set_error("truncated record head");
      return -1;
    }
    uint32_t length = read_u32(head);
    uint32_t crc = read_u32(head + 4);
    if (static_cast<long long>(length) > remaining) {
      set_error("record length exceeds buffer (corrupt length field)");
      return -1;
    }
    if (fread(out, 1, length, r->file) != length) {
      set_error("truncated record");
      return -1;
    }
    if (crc32_impl(out, length) != crc) {
      set_error("CRC mismatch (corrupt record)");
      return -1;
    }
    lengths[i - start] = length;
    out += length;
    remaining -= length;
  }
  return end - start;
}

void edl_rf_close(void* handle) {
  Reader* r = static_cast<Reader*>(handle);
  if (r->file) fclose(r->file);
  delete r;
}

// ---------------------------------------------------------------------
// Writer
// ---------------------------------------------------------------------

void* edl_rf_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) {
    set_error(std::string("cannot create ") + path);
    return nullptr;
  }
  uint8_t header[kHeaderSize];
  memcpy(header, kMagic, 4);
  write_u32(header + 4, kVersion);
  if (fwrite(header, 1, kHeaderSize, f) != kHeaderSize) {
    set_error("header write failed");
    fclose(f);
    return nullptr;
  }
  Writer* w = new Writer();
  w->file = f;
  return w;
}

int edl_rf_writer_write(void* handle, const uint8_t* data, uint32_t length) {
  Writer* w = static_cast<Writer*>(handle);
  long pos = ftell(w->file);
  if (pos < 0) {
    set_error("tell failed");
    return -1;
  }
  uint8_t head[kRecordHead];
  write_u32(head, length);
  write_u32(head + 4, crc32_impl(data, length));
  if (fwrite(head, 1, kRecordHead, w->file) != kRecordHead ||
      fwrite(data, 1, length, w->file) != length) {
    set_error("record write failed");
    return -1;
  }
  w->offsets.push_back(static_cast<uint64_t>(pos));
  return 0;
}

int edl_rf_writer_close(void* handle) {
  Writer* w = static_cast<Writer*>(handle);
  int status = 0;
  long index_offset = ftell(w->file);
  if (index_offset < 0) {
    set_error("tell failed");
    status = -1;
  } else {
    std::vector<uint8_t> raw(w->offsets.size() * 8 + kFooterSize);
    for (size_t i = 0; i < w->offsets.size(); ++i) {
      write_u64(raw.data() + i * 8, w->offsets[i]);
    }
    uint8_t* footer = raw.data() + w->offsets.size() * 8;
    write_u64(footer, w->offsets.size());
    write_u64(footer + 8, static_cast<uint64_t>(index_offset));
    memcpy(footer + 16, kFooterMagic, 4);
    if (fwrite(raw.data(), 1, raw.size(), w->file) != raw.size()) {
      set_error("footer write failed");
      status = -1;
    }
  }
  if (fclose(w->file) != 0) {
    set_error("close failed");
    status = -1;
  }
  delete w;
  return status;
}

}  // extern "C"
