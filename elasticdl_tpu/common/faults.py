"""Deterministic fault injection for resilience testing.

The elasticity claims of this framework (workers ride through a master
restart; restores never load a torn checkpoint) are only claims until a
test can *make* the fault happen on demand.  Chip-side chaos testing is
unreliable (VERDICT.md records multi-round TPU-tunnel outages), so the
injection points here are designed to prove the recovery paths on CPU,
deterministically:

- **call-count triggered** — a fault fires on the Nth..(N+count-1)th call
  of its site, never on wall clock and never on randomness, so a failing
  chaos run replays exactly;
- **off by default and zero-cost when disabled** — `fire()` is a single
  module-attribute `None` check until `install()`/`ELASTICDL_FAULTS`
  arms the registry, so production hot paths pay nothing.

Injection sites wired into the framework:

    rpc.<method>   every RPC attempt in grpc_utils.call_with_retry
                   (kinds: error[=STATUS_CODE], latency[=seconds])
    ckpt.write     every CheckpointSaver state-file write
                   (kind: truncate[=keep_bytes] — a torn write)
    worker.task    every task a worker starts processing
    worker.step    every train batch in the simple worker
                   (kind: crash[=exit_code] — SIGKILL-equivalent)
    stream.source  every SyntheticClickStream.advance (kind:
                   latency[=seconds] — a wedged upstream pipe stalls
                   production for that much VIRTUAL time; @t specs are
                   applied by the driver via due() + stream.stall())
    ckpt.delta     every delta-checkpoint publish (kind:
                   truncate[=keep_bytes] — tears the largest delta file
                   after its checksum is manifested)
    serving.delta_apply
                   every serving-side delta apply (kind: error[=msg] —
                   the apply fails and rolls back to the previous
                   generation)
    stream.labels  every delayed-label range fetch
                   (data/stream.feedback_labels; kinds:
                   truncate — label-feed outage, the range returns no
                   labels; error — poisoned feed, every label flipped:
                   the canary-gate chaos scenario)
    quality.label_join
                   every label delivery into the quality ledger
                   (obs/quality.py; kinds: error — the label is
                   dropped; truncate — delivered twice, the
                   at-least-once-feed duplicate)
    quality.shadow_eval
                   every canary-gate shadow evaluation (kind:
                   error[=msg] — the evaluation blows up; the gate
                   degrades to quality-unknown instead of crashing
                   the delta watcher)

Spec grammar (comma/semicolon separated, via `ELASTICDL_FAULTS` or
`install()`):

    site:kind[=arg][@after|@tSECONDS][xcount]

    rpc.get_task:error=UNAVAILABLE@1x3   calls 1-3 raise UNAVAILABLE
    rpc.get_task:latency=0.25@2          2nd call delayed 0.25 s
    ckpt.write:truncate@2                2nd checkpoint write torn
    worker.task:crash@3                  process exits on 3rd task
    storm.preempt:crash@t2.5             due once 2.5 s into a schedule

`after` is 1-based (default 1); `count` is how many consecutive calls
trigger (default 1, `x*` = every call from `after` on).

**Schedule-based triggers** (`@t<seconds>`): the spec fires once, at a
RELATIVE time on a timeline the *caller* owns — this module never reads
a clock (determinism).  A driver (e.g. the preemption-storm chaos
harness) polls `due(site, elapsed_s)` with its own elapsed seconds and
applies every newly-due spec; `remaining_due(site)` says when the
schedule is exhausted.  Time specs never trigger through `fire()` and
never combine with `xcount` (one spec per scheduled firing keeps replay
exact).
"""

from __future__ import annotations

# deterministic-replay-path — the invariant analyzer bans wall-clock and
# unseeded-RNG reads in this module (docs/invariants.md, rule `determinism`).

import os
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

ENV_VAR = "ELASTICDL_FAULTS"

KINDS = ("error", "latency", "truncate", "crash")


@dataclass
class FaultSpec:
    site: str
    kind: str
    arg: str = ""
    after: int = 1  # first triggering call, 1-based
    count: int = 1  # number of consecutive triggering calls; -1 = forever
    at_s: Optional[float] = None  # schedule trigger: relative seconds

    def triggers_at(self, call_number: int) -> bool:
        if self.at_s is not None:
            return False  # schedule specs fire through due(), not fire()
        if call_number < self.after:
            return False
        return self.count < 0 or call_number < self.after + self.count


@dataclass
class _Registry:
    specs: List[FaultSpec] = field(default_factory=list)
    counters: Dict[str, int] = field(default_factory=dict)
    fired_schedule: set = field(default_factory=set)  # spec indices
    lock: threading.Lock = field(default_factory=threading.Lock)


# None = disabled; fire() bails on one attribute load, so armed-off cost
# is zero on hot paths (per-RPC-attempt, per-train-batch).
_registry: Optional[_Registry] = None


def parse_specs(text: str) -> List[FaultSpec]:
    specs = []
    for token in text.replace(";", ",").split(","):
        token = token.strip()
        if not token:
            continue
        try:
            site, rest = token.split(":", 1)
            count = 1
            explicit_count = False
            if "x" in rest.rsplit("@", 1)[-1]:
                rest, count_text = rest.rsplit("x", 1)
                count = -1 if count_text == "*" else int(count_text)
                explicit_count = True
            after = 1
            at_s = None
            if "@" in rest:
                rest, after_text = rest.rsplit("@", 1)
                if after_text.startswith("t"):
                    at_s = float(after_text[1:])
                else:
                    after = int(after_text)
            kind, _, arg = rest.partition("=")
        except ValueError as exc:
            raise ValueError(f"Unparseable fault spec {token!r}") from exc
        if kind not in KINDS:
            raise ValueError(
                f"Unknown fault kind {kind!r} in {token!r} (know {KINDS})"
            )
        if after < 1 or (count < 1 and count != -1):
            raise ValueError(f"Bad @after/xcount in fault spec {token!r}")
        if at_s is not None and (at_s < 0 or explicit_count):
            raise ValueError(
                f"Bad schedule trigger in fault spec {token!r}: @t needs "
                "seconds >= 0 and fires exactly once (no xcount — list "
                "one spec per firing)"
            )
        specs.append(
            FaultSpec(
                site=site, kind=kind, arg=arg, after=after, count=count,
                at_s=at_s,
            )
        )
    return specs


def install(specs) -> None:
    """Arm the registry with FaultSpecs (or a spec string)."""
    global _registry
    if isinstance(specs, str):
        specs = parse_specs(specs)
    _registry = _Registry(specs=list(specs))


def install_from_env(environ=os.environ) -> bool:
    """Arm from ELASTICDL_FAULTS if set; True when faults were armed.
    Called at worker/master process start so subprocess chaos tests can
    inject through the environment."""
    text = environ.get(ENV_VAR, "")
    if not text:
        return False
    install(text)
    return bool(_registry.specs)


def clear() -> None:
    global _registry
    _registry = None


def enabled() -> bool:
    return _registry is not None


def call_count(site: str) -> int:
    if _registry is None:
        return 0
    with _registry.lock:
        return _registry.counters.get(site, 0)


def fire(site: str) -> Optional[FaultSpec]:
    """Count one call of `site`; return the FaultSpec to apply, if any.

    The caller applies the fault (raise / sleep / truncate / exit) — this
    module never touches the network or filesystem itself, so sites stay
    import-light and the mapping fault->behavior lives next to the code
    it perturbs.
    """
    registry = _registry
    if registry is None:
        return None
    with registry.lock:
        registry.counters[site] = n = registry.counters.get(site, 0) + 1
        for spec in registry.specs:
            if spec.site == site and spec.triggers_at(n):
                return spec
    return None


def due(site: str, elapsed_s: float) -> List[FaultSpec]:
    """Schedule-based triggers: the `@t<seconds>` specs of `site` whose
    time has come at `elapsed_s` — seconds on the CALLER's timeline
    (this module never reads a clock; the driver owns schedule start).
    Each spec is returned exactly once, so a polling driver applies
    every firing exactly once however often it polls."""
    registry = _registry
    if registry is None:
        return []
    hits: List[FaultSpec] = []
    with registry.lock:
        for index, spec in enumerate(registry.specs):
            if spec.site != site or spec.at_s is None:
                continue
            if spec.at_s <= elapsed_s and index not in registry.fired_schedule:
                registry.fired_schedule.add(index)
                hits.append(spec)
    hits.sort(key=lambda spec: spec.at_s)
    return hits


def remaining_due(site: str) -> int:
    """How many of `site`'s schedule-based specs have not fired yet —
    a storm driver's loop-exit condition."""
    registry = _registry
    if registry is None:
        return 0
    with registry.lock:
        return sum(
            1
            for index, spec in enumerate(registry.specs)
            if spec.site == site
            and spec.at_s is not None
            and index not in registry.fired_schedule
        )


def crash_now(spec: FaultSpec) -> None:
    """Apply a `crash` fault: immediate process death (no atexit, no
    flush) — indistinguishable from SIGKILL to the supervisor."""
    os._exit(int(spec.arg or 13))
