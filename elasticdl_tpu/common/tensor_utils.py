"""numpy/JAX array <-> proto Tensor conversion.

Parity: the reference's tensor plumbing lives in
elasticdl/python/common/tensor_utils.py (Python side) and
elasticdl/pkg/common/tensor.go (Go side).  Here a single numpy-based codec
serves both directions; JAX arrays convert via numpy (device_get).
"""

from __future__ import annotations

import numpy as np

from elasticdl_tpu.proto import elasticdl_pb2 as pb

# ml_dtypes ships with jax and provides the bfloat16 numpy scalar type.
import ml_dtypes

_NP_TO_PB = {
    np.dtype(np.float32): pb.DT_FLOAT32,
    np.dtype(np.float64): pb.DT_FLOAT64,
    np.dtype(np.int32): pb.DT_INT32,
    np.dtype(np.int64): pb.DT_INT64,
    np.dtype(np.bool_): pb.DT_BOOL,
    np.dtype(ml_dtypes.bfloat16): pb.DT_BFLOAT16,
    np.dtype(np.uint8): pb.DT_UINT8,
    np.dtype(np.int8): pb.DT_INT8,
    np.dtype(np.float16): pb.DT_FLOAT16,
}

_PB_TO_NP = {v: k for k, v in _NP_TO_PB.items()}


def np_dtype_to_pb(dtype) -> int:
    dtype = np.dtype(dtype)
    if dtype not in _NP_TO_PB:
        raise ValueError(f"Unsupported dtype for wire transfer: {dtype}")
    return _NP_TO_PB[dtype]


def pb_dtype_to_np(pb_dtype: int):
    if pb_dtype not in _PB_TO_NP:
        raise ValueError(f"Unsupported proto dtype: {pb_dtype}")
    return _PB_TO_NP[pb_dtype]


def ndarray_to_pb(array, name: str = "", indices=None) -> pb.Tensor:
    """Serialize an array (numpy or JAX) into a proto Tensor.

    `indices` non-None marks a sparse row-slice gradient (the reference's
    IndexedSlices): `array` holds the rows, `indices` the row ids.
    """
    array = np.ascontiguousarray(np.asarray(array))
    tensor = pb.Tensor(
        name=name,
        dims=list(array.shape),
        content=array.tobytes(),
        dtype=np_dtype_to_pb(array.dtype),
    )
    if indices is not None:
        tensor.indices.extend(int(i) for i in np.asarray(indices).ravel())
    return tensor


def pb_to_ndarray(tensor: pb.Tensor) -> np.ndarray:
    dtype = pb_dtype_to_np(tensor.dtype)
    # Copy: frombuffer over proto bytes is read-only, and consumers apply
    # in-place updates (e.g. optimizer apply on a restored parameter).
    array = np.frombuffer(tensor.content, dtype=dtype).copy()
    return array.reshape(tuple(tensor.dims))


def pb_to_indexed_slices(tensor: pb.Tensor):
    """Returns (values, indices) for a sparse row-slice tensor."""
    values = pb_to_ndarray(tensor)
    indices = np.asarray(tensor.indices, dtype=np.int64)
    return values, indices
