"""Framework-wide constants.

Parity: elasticdl/python/common/constants.py in the reference.
"""


class DistributionStrategy:
    LOCAL = "Local"
    PARAMETER_SERVER = "ParameterServerStrategy"  # TPU: sharded-embedding data plane
    ALLREDUCE = "AllreduceStrategy"  # TPU: psum over ICI


class JobType:
    TRAINING_ONLY = "training_only"
    EVALUATION_ONLY = "evaluation_only"
    PREDICTION_ONLY = "prediction_only"
    TRAINING_WITH_EVALUATION = "training_with_evaluation"


class TaskExecCounterKey:
    BATCH_COUNT = "batch_count"
    RECORD_COUNT = "record_count"
    # Out-of-vocabulary LOOKUPS seen by the task's train steps (PS mode;
    # counted device-side per window, see layers.embedding
    # OOV_COLLECTION).  A lookup is one (id, table) pair: a model that
    # routes the same ids through two tables (e.g. DeepFM's split
    # layout) counts each OOV id once per table — the count is an alarm
    # signal whose zero/nonzero contract is layout-independent, but its
    # magnitude follows the model's lookup structure.
    OOV_LOOKUP_COUNT = "oov_lookup_count"


class GRPC:
    # The reference raises gRPC limits because its PS data plane rides
    # protobuf; we keep generous limits for checkpoint/eval tensors.
    MAX_SEND_MESSAGE_LENGTH = 256 * 1024 * 1024
    MAX_RECEIVE_MESSAGE_LENGTH = 256 * 1024 * 1024
    KEEPALIVE_TIME_MS = 30000
    KEEPALIVE_TIMEOUT_MS = 10000
    # Cap the channel's TCP reconnect backoff: gRPC's default grows the
    # gap between connection attempts toward 120 s, so a worker whose
    # channel went TRANSIENT_FAILURE during a brief master restart could
    # fail RPCs for minutes after the master is back (the retry plane
    # retries fast, but no attempt can succeed until the channel
    # reconnects).  2 s bounds outage detection; gRPC's built-in jitter
    # decorrelates the fleet's reconnect storm.
    INITIAL_RECONNECT_BACKOFF_MS = 200
    MIN_RECONNECT_BACKOFF_MS = 200
    MAX_RECONNECT_BACKOFF_MS = 2000


class RPC:
    # Transient-failure plane (common/grpc_utils.py): every client RPC
    # carries an explicit deadline; idempotent RPCs retry
    # UNAVAILABLE/DEADLINE_EXCEEDED with capped exponential backoff.  The
    # budget is sized to ride through a full master restart (process
    # spawn + imports + progress-snapshot resume, seconds to ~a minute)
    # without approaching the pod manager's restart-the-world escalation.
    DEADLINE_S = 30.0
    EVAL_REPORT_DEADLINE_S = 120.0  # chunked eval tensors can be large
    MAX_ATTEMPTS = 24
    BASE_BACKOFF_S = 0.1
    MAX_BACKOFF_S = 2.0
    JITTER = 0.25
    TOTAL_BUDGET_S = 120.0


class WorkerEnv:
    MASTER_ADDR = "ELASTICDL_MASTER_ADDR"
    WORKER_ID = "ELASTICDL_WORKER_ID"
    WORKER_NUM = "ELASTICDL_WORKER_NUM"


class DefaultTimeouts:
    # Seconds a task may sit in `doing` before the master declares the
    # worker slow/dead and recovers the task (0 disables).
    TASK_TIMEOUT = 0
    WORKER_HEARTBEAT_INTERVAL = 5
    WORKER_LIVENESS_TIMEOUT = 30


class Mode:
    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
