"""gRPC channel/server helpers with framework-wide options.

Parity: elasticdl/python/common/grpc_utils.py in the reference (message size
limits + keepalive so large checkpoint/eval tensors fit).
"""

from concurrent import futures

import grpc

from elasticdl_tpu.common.constants import GRPC

_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
    ("grpc.keepalive_time_ms", GRPC.KEEPALIVE_TIME_MS),
    ("grpc.keepalive_timeout_ms", GRPC.KEEPALIVE_TIMEOUT_MS),
]


def build_channel(addr: str) -> grpc.Channel:
    return grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)


def build_server(max_workers: int = 64) -> grpc.Server:
    return grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
    )
