"""gRPC channel/server helpers with framework-wide options.

Parity: elasticdl/python/common/grpc_utils.py in the reference (message size
limits + keepalive so large checkpoint/eval tensors fit), extended with the
transient-failure plane: every RPC carries an explicit deadline, and
idempotent RPCs retry UNAVAILABLE/DEADLINE_EXCEEDED with exponential
backoff so a brief master restart or network blip is absorbed at the RPC
layer instead of crashing the worker and escalating into a (much more
expensive) restart-the-world re-formation.
"""

from __future__ import annotations

import random
import threading
import time
from concurrent import futures
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple

import grpc

from elasticdl_tpu import obs
from elasticdl_tpu.common import faults
from elasticdl_tpu.common.constants import GRPC, RPC
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.grpc_utils")

#: Process-wide RPC retry-plane counters: every RetryStats instance also
#: feeds these, so retries/give-ups appear on the obs exporter's /metrics
#: alongside the rest of the control plane (RetryStats keeps the
#: per-client view the chaos tests assert on).
_RPC_CALLS = obs.counter(
    "elasticdl_rpc_calls_total", "Client RPC calls entered"
)
_RPC_ATTEMPTS = obs.counter(
    "elasticdl_rpc_attempts_total", "Client RPC wire attempts"
)
_RPC_RETRIES = obs.counter(
    "elasticdl_rpc_retries_total",
    "Transient-failure retries, by RPC method",
    labelnames=("method",),
)
_RPC_GIVE_UPS = obs.counter(
    "elasticdl_rpc_give_ups_total",
    "Retry budgets exhausted, by RPC method",
    labelnames=("method",),
)

# ---------------------------------------------------------------------------
# Cross-process trace correlation
# ---------------------------------------------------------------------------

#: gRPC metadata key carrying a per-task trace id across the
#: master/worker process boundary.  The master's TaskManager mints the id
#: at dispatch (it rides GetTaskResponse.task.trace_id); the worker sends
#: it BACK as call metadata on report_task_result, and both ends stamp it
#: on their journal/span records — so `get_task -> train ->
#: report_task_result -> requeue/complete` reconstructs as one causal
#: chain (docs/observability.md).  Lowercase per the gRPC metadata spec.
TRACE_METADATA_KEY = "elasticdl-trace-id"

#: Companion metadata key carrying the CALLER's open span id, so the
#: receiving servicer's RPC-handler span nests under the client span in
#: the assembled trace (obs/tracing.py; docs/observability.md
#: "Distributed tracing").  Optional and independent of the trace id —
#: old peers that only speak TRACE_METADATA_KEY remain wire-compatible.
SPAN_METADATA_KEY = "elasticdl-span-id"


def trace_metadata(
    trace_id: str, span_id: str = ""
) -> Optional[Tuple[Tuple[str, str], ...]]:
    """Call-metadata tuple carrying `trace_id` (and, when given, the
    caller's `span_id` for cross-process span parenting).  None when
    both are empty, so callers can pass the result straight to
    `call_with_retry`."""
    pairs = []
    if trace_id:
        pairs.append((TRACE_METADATA_KEY, str(trace_id)))
    if span_id:
        pairs.append((SPAN_METADATA_KEY, str(span_id)))
    return tuple(pairs) or None


def _metadata_value(context, wanted_key: str) -> str:
    try:
        metadata = context.invocation_metadata()
    except Exception:
        return ""
    for key, value in metadata or ():
        if key == wanted_key:
            return value
    return ""


def trace_id_from_context(context) -> str:
    """Extract the trace id from a servicer context's invocation
    metadata ('' when absent — old workers, non-task RPCs)."""
    return _metadata_value(context, TRACE_METADATA_KEY)


def span_id_from_context(context) -> str:
    """The caller's span id ('' when absent) — the parent for this
    handler's RPC span."""
    return _metadata_value(context, SPAN_METADATA_KEY)


_CHANNEL_OPTIONS = [
    ("grpc.max_send_message_length", GRPC.MAX_SEND_MESSAGE_LENGTH),
    ("grpc.max_receive_message_length", GRPC.MAX_RECEIVE_MESSAGE_LENGTH),
    ("grpc.keepalive_time_ms", GRPC.KEEPALIVE_TIME_MS),
    ("grpc.keepalive_timeout_ms", GRPC.KEEPALIVE_TIMEOUT_MS),
    ("grpc.initial_reconnect_backoff_ms", GRPC.INITIAL_RECONNECT_BACKOFF_MS),
    ("grpc.min_reconnect_backoff_ms", GRPC.MIN_RECONNECT_BACKOFF_MS),
    ("grpc.max_reconnect_backoff_ms", GRPC.MAX_RECONNECT_BACKOFF_MS),
]


def build_channel(addr: str) -> grpc.Channel:
    return grpc.insecure_channel(addr, options=_CHANNEL_OPTIONS)


def build_server(max_workers: int = 64) -> grpc.Server:
    return grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=_CHANNEL_OPTIONS,
    )


# ---------------------------------------------------------------------------
# Retrying call plane
# ---------------------------------------------------------------------------

#: Status codes that signal a transient condition worth retrying: the
#: server was unreachable/restarting (UNAVAILABLE) or the deadline lapsed
#: (DEADLINE_EXCEEDED).  Anything else (INVALID_ARGUMENT, INTERNAL, ...)
#: is a real error and propagates immediately.
TRANSIENT_CODES = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
)


@dataclass(frozen=True)
class RetryPolicy:
    """Per-RPC deadline + bounded exponential backoff.

    `max_attempts=1` means deadline-only (the non-idempotent policy).
    Backoff for attempt k (1-based) is
    ``min(max_backoff_s, base_backoff_s * 2**(k-1))`` scaled by a
    DETERMINISTIC jitter in [1, 1+jitter] seeded from (method, k) — no
    wall-clock randomness, so a chaos run's schedule replays exactly.
    `total_budget_s` bounds the whole call including backoff sleeps: a
    retry that could not complete within the remaining budget is not
    attempted (fail fast rather than overshoot).

    `wait_for_ready` queues the RPC while the channel is disconnected
    (up to the deadline) instead of failing instantly.  This matters for
    outage ride-through beyond politeness: a pending RPC is what drives
    gRPC to keep attempting the TCP reconnect — a tight fail-fast retry
    loop over a TRANSIENT_FAILURE channel can spin for minutes after the
    server is back without ever kicking a connection attempt (observed
    with grpc 1.68; the chaos e2e would hang exactly that way).
    """

    timeout_s: float = RPC.DEADLINE_S
    max_attempts: int = 1
    base_backoff_s: float = RPC.BASE_BACKOFF_S
    max_backoff_s: float = RPC.MAX_BACKOFF_S
    jitter: float = RPC.JITTER
    total_budget_s: float = RPC.TOTAL_BUDGET_S
    wait_for_ready: bool = False

    def backoff_s(self, method: str, attempt: int, salt: str = "") -> float:
        base = min(
            self.max_backoff_s, self.base_backoff_s * (2 ** (attempt - 1))
        )
        if not self.jitter:
            return base
        # Seeded by (salt, method, attempt) — deterministic across runs,
        # and with a per-worker salt (worker id) the fleet's retry storm
        # after a master restart is decorrelated instead of N workers
        # hammering the recovering master in lockstep.
        u = random.Random(f"{salt}:{method}:{attempt}").random()
        return base * (1.0 + self.jitter * u)


#: The two client-side policies.  Idempotency is a per-RPC property the
#: caller declares (see worker/master_client.py); the wrapper never
#: guesses.
IDEMPOTENT_POLICY = RetryPolicy(
    max_attempts=RPC.MAX_ATTEMPTS, wait_for_ready=True
)
NON_IDEMPOTENT_POLICY = RetryPolicy(max_attempts=1)


@dataclass
class RetryStats:
    """Mutable per-client counters (observability + chaos-test asserts).
    Lock-guarded: one MasterClient is shared by the task loop and the
    heartbeat thread, and unsynchronized `+=` would drop counts.

    Every record also feeds the process-wide obs registry counters, and
    retry traffic folds into a RATE-LIMITED periodic summary: one INFO
    line per `SUMMARY_INTERVAL_S` with the retries/give-ups since the
    last line, instead of per-event warnings (the first-retry outage
    announcement and give-up close-out in `call_with_retry` remain — they
    bracket an outage; this line quantifies the steady drizzle between).
    """

    #: Seconds between retry-summary INFO lines (5 minutes).
    SUMMARY_INTERVAL_S = 300.0

    calls: int = 0  # guarded-by: _lock
    attempts: int = 0  # guarded-by: _lock
    retries: int = 0  # guarded-by: _lock
    give_ups: int = 0  # guarded-by: _lock
    last_error: str = ""  # guarded-by: _lock
    per_method_retries: dict = field(default_factory=dict)  # guarded-by: _lock
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )
    # Summary-window baselines (deltas since the last summary line).
    _summary_started: Optional[float] = field(
        default=None, repr=False, compare=False
    )  # guarded-by: _lock
    _summary_retries: int = field(default=0, repr=False, compare=False)  # guarded-by: _lock
    _summary_give_ups: int = field(default=0, repr=False, compare=False)  # guarded-by: _lock
    _summary_per_method: dict = field(
        default_factory=dict, repr=False, compare=False
    )  # guarded-by: _lock

    def record_call(self):
        with self._lock:
            self.calls += 1
        _RPC_CALLS.inc()

    def record_attempt(self):
        with self._lock:
            self.attempts += 1
        _RPC_ATTEMPTS.inc()

    def record_retry(self, method: str):
        with self._lock:
            self.retries += 1
            self.per_method_retries[method] = (
                self.per_method_retries.get(method, 0) + 1
            )
        _RPC_RETRIES.inc(method=method)

    def record_give_up(self, method: str, code_name: str):
        with self._lock:
            self.give_ups += 1
            self.last_error = f"{method}: {code_name}"
        _RPC_GIVE_UPS.inc(method=method)

    def maybe_log_summary(
        self, now: Optional[float] = None, interval_s: Optional[float] = None
    ):
        """Emit at most one INFO summary line per interval covering the
        retry/give-up traffic since the previous line.  `now` is a
        monotonic-clock reading (injectable for tests; never wall clock —
        this module is on the deterministic-replay path)."""
        now = time.monotonic() if now is None else now
        interval = self.SUMMARY_INTERVAL_S if interval_s is None else interval_s
        line = None
        with self._lock:
            if self._summary_started is None:
                # First retry-plane event opens the window; no line yet.
                self._summary_started = now
                self._summary_retries = self.retries
                self._summary_give_ups = self.give_ups
                self._summary_per_method = dict(self.per_method_retries)
                return
            if now - self._summary_started < interval:
                return
            retries_delta = self.retries - self._summary_retries
            give_ups_delta = self.give_ups - self._summary_give_ups
            per_method = {
                method: count - self._summary_per_method.get(method, 0)
                for method, count in self.per_method_retries.items()
                if count - self._summary_per_method.get(method, 0) > 0
            }
            elapsed = now - self._summary_started
            self._summary_started = now
            self._summary_retries = self.retries
            self._summary_give_ups = self.give_ups
            self._summary_per_method = dict(self.per_method_retries)
            if retries_delta or give_ups_delta:
                top = ", ".join(
                    f"{method}={count}"
                    for method, count in sorted(
                        per_method.items(), key=lambda kv: -kv[1]
                    )[:5]
                )
                line = (
                    f"RPC retry summary: {retries_delta} retries, "
                    f"{give_ups_delta} give-ups in the last "
                    f"{elapsed / 60:.1f} min"
                    + (f" (by method: {top})" if top else "")
                )
        if line:
            logger.info(line)


class InjectedRpcError(grpc.RpcError):
    """A fault-injected RPC failure (faults.py `rpc.*:error`).  Mimics the
    subset of grpc.Call the retry wrapper and callers inspect."""

    def __init__(self, code: grpc.StatusCode):
        super().__init__(f"injected {code.name}")
        self._code = code

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return "injected fault (elasticdl_tpu.common.faults)"


def _apply_rpc_fault(spec: faults.FaultSpec, sleep: Callable[[float], None]):
    if spec.kind == "error":
        raise InjectedRpcError(
            getattr(grpc.StatusCode, spec.arg or "UNAVAILABLE")
        )
    if spec.kind == "latency":
        sleep(float(spec.arg or 0.1))


def call_with_retry(
    rpc_callable: Callable,
    request,
    method: str,
    policy: RetryPolicy,
    stats: Optional[RetryStats] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    seed: str = "",
    metadata: Optional[Tuple[Tuple[str, str], ...]] = None,
):
    """Invoke `rpc_callable(request, timeout=...)` under `policy`.

    Every attempt carries the policy's explicit deadline; transient
    failures (TRANSIENT_CODES) back off and retry while attempts and the
    total budget last.  The `rpc.<method>` fault-injection site fires
    once per ATTEMPT (so `error=...@1x3` means "first three attempts
    fail"), before the wire call, and is a no-op when faults are
    disarmed.  `metadata` (e.g. `trace_metadata(...)`) is forwarded to
    every attempt; None sends none — keeping the common path compatible
    with test doubles that only accept (request, timeout, wait_for_ready).
    """
    if stats is not None:
        stats.record_call()
    deadline = clock() + policy.total_budget_s
    attempt = 0
    while True:
        attempt += 1
        if stats is not None:
            stats.record_attempt()
        try:
            spec = faults.fire(f"rpc.{method}")
            if spec is not None:
                _apply_rpc_fault(spec, sleep)
            kwargs = {} if metadata is None else {"metadata": metadata}
            return rpc_callable(
                request,
                timeout=policy.timeout_s,
                wait_for_ready=policy.wait_for_ready,
                **kwargs,
            )
        except grpc.RpcError as exc:
            code = exc.code() if callable(getattr(exc, "code", None)) else None
            transient = code in TRANSIENT_CODES
            backoff = policy.backoff_s(method, attempt, salt=seed)
            # Reserve backoff AND the next attempt's full deadline: the
            # budget is a hard bound on the whole call, so an attempt
            # that could still be in flight past it is not started.
            out_of_budget = clock() + backoff + policy.timeout_s > deadline
            if (
                not transient
                or attempt >= policy.max_attempts
                or out_of_budget
            ):
                if stats is not None and transient:
                    stats.record_give_up(method, code and code.name)
                    stats.maybe_log_summary(now=clock())
                if transient and policy.max_attempts > 1:
                    logger.warning(
                        "RPC %s failed with %s after %d attempt(s)%s",
                        method,
                        code and code.name,
                        attempt,
                        " (retry budget exhausted)" if out_of_budget else "",
                    )
                raise
            if stats is not None:
                stats.record_retry(method)
                stats.maybe_log_summary(now=clock())
            if attempt == 1:
                # One line per outage, not per retry: the first retry
                # announces the condition, the give-up (above) closes it.
                logger.warning(
                    "RPC %s hit %s; retrying with backoff (deadline %.0fs, "
                    "budget %.0fs)",
                    method,
                    code and code.name,
                    policy.timeout_s,
                    policy.total_budget_s,
                )
            sleep(backoff)


def expected_backoff_schedule(
    method: str, policy: RetryPolicy, retries: int, seed: str = ""
) -> Tuple[float, ...]:
    """The exact backoff sequence `call_with_retry` will sleep for
    `retries` consecutive transient failures of `method` under `seed`
    (the caller's jitter salt, e.g. the worker id) — exported so tests
    assert the schedule instead of re-deriving the jitter."""
    return tuple(
        policy.backoff_s(method, attempt, salt=seed)
        for attempt in range(1, retries + 1)
    )
