"""Centralized flag system.

Parity: elasticdl/python/common/args.py in the reference — flat argparse with
distinct parser assemblies per role (master / worker / CLI) sharing flag
groups; unknown flags round-trip client -> master -> worker.
"""

from __future__ import annotations

import argparse


def pos_int(value):
    ivalue = int(value)
    if ivalue <= 0:
        raise argparse.ArgumentTypeError(f"{value} must be a positive integer")
    return ivalue


def non_neg_int(value):
    ivalue = int(value)
    if ivalue < 0:
        raise argparse.ArgumentTypeError(f"{value} must be >= 0")
    return ivalue


def pos_int_or_auto(value):
    if value == "auto":
        return value
    return pos_int(value)


def _profile_steps_spec(value):
    """Validate --profile_steps AT PARSE TIME (master-side): a malformed
    spec must fail the submission, not crash-loop every worker pod until
    the restart budget dies."""
    if value:
        from elasticdl_tpu.common.profiler import parse_profile_steps

        try:
            parse_profile_steps(value)
        except ValueError as e:
            raise argparse.ArgumentTypeError(str(e))
    return value


def str2bool(value):
    if isinstance(value, bool):
        return value
    if value.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if value.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError(f"Cannot parse bool from {value!r}")


def add_common_arguments(parser: argparse.ArgumentParser):
    parser.add_argument("--job_name", default="elasticdl-job", help="Job name")
    parser.add_argument(
        "--distribution_strategy",
        default="Local",
        choices=["Local", "ParameterServerStrategy", "AllreduceStrategy"],
        help="Local, ParameterServerStrategy (sharded-embedding data plane) "
        "or AllreduceStrategy (psum over ICI)",
    )
    parser.add_argument("--log_level", default="INFO")


def add_model_zoo_arguments(parser: argparse.ArgumentParser):
    parser.add_argument(
        "--model_zoo", required=True, help="Directory or module path of the model zoo"
    )
    parser.add_argument(
        "--model_def",
        required=True,
        help="Model module within the zoo, e.g. mnist.mnist_functional_api",
    )
    parser.add_argument(
        "--model_params",
        default="",
        help="Comma-separated key=value pairs passed to custom_model()",
    )
    parser.add_argument("--dataset_fn", default="dataset_fn")
    parser.add_argument("--loss", default="loss")
    parser.add_argument("--optimizer", default="optimizer")
    parser.add_argument("--eval_metrics_fn", default="eval_metrics_fn")
    parser.add_argument("--custom_data_reader", default="custom_data_reader")
    parser.add_argument("--callbacks", default="callbacks")


def add_data_arguments(parser: argparse.ArgumentParser):
    parser.add_argument("--training_data", default="", help="Training data path/pattern")
    parser.add_argument("--validation_data", default="", help="Validation data path")
    parser.add_argument("--prediction_data", default="", help="Prediction data path")
    parser.add_argument("--records_per_task", type=pos_int, default=4096)
    parser.add_argument("--minibatch_size", type=pos_int, default=64)
    parser.add_argument("--num_epochs", type=pos_int, default=1)
    parser.add_argument(
        "--data_reader_params",
        default="",
        help="Comma-separated key=value pairs passed to the data reader",
    )


def add_train_arguments(parser: argparse.ArgumentParser):
    parser.add_argument("--evaluation_steps", type=non_neg_int, default=0,
                        help="Evaluate every N steps (0: per epoch)")
    parser.add_argument("--checkpoint_steps", type=non_neg_int, default=0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--keep_checkpoint_max", type=non_neg_int, default=3)
    parser.add_argument("--output", default="", help="Trained model output path")
    parser.add_argument("--tensorboard_log_dir", default="")
    parser.add_argument(
        "--dense_sharding", default="replicated",
        choices=["replicated", "fsdp"],
        help="Dense param/optimizer placement in AllReduce mode: "
        "'replicated' (psum gradients) or 'fsdp' (state sharded over the "
        "data axis — each chip holds 1/N of model+optimizer memory; XLA "
        "inserts the weight all-gathers / gradient reduce-scatters)",
    )
    parser.add_argument(
        "--train_window_steps", type=non_neg_int, default=0,
        help="Training batches fused per device dispatch in cluster "
        "strategies. 0 = AUTO: up to 400 steps (the measured optimum, "
        "BASELINE.md dispatch-window scaling), bounded by the task's "
        "batch count and a 1 GiB staged-bytes cap. Explicit values "
        "override the auto sizing entirely.",
    )
    parser.add_argument(
        "--sparse_apply_every", type=pos_int_or_auto, default="auto",
        help="ParameterServerStrategy only: apply the sparse embedding "
        "optimizer once per N train steps from the accumulated gradients "
        "(N=1 is strict per-step semantics). N>1 trades bounded "
        "staleness — forwards within a chunk read chunk-start tables, "
        "the async-PS behaviour of upstream ElasticDL — for amortizing "
        "the table-sized moment update, the dominant step cost once the "
        "per-chip table exceeds ~10M rows (BASELINE.md table-scale "
        "probe). The default 'auto' resolves from the model's resident "
        "table rows at init: strict (1) up to 10M rows, 32 above — the "
        "convergence-validated large-table config (BASELINE.md "
        "'Windowed-apply convergence'; upstream ElasticDL's async PS was "
        "likewise its default mode). Pass 1 to force strict semantics at "
        "any scale. Chunks never span device dispatches: the worker "
        "grows --train_window_steps to a multiple of N, and task-tail "
        "batches outside a full window apply per-step.",
    )
    parser.add_argument(
        "--sparse_kernel", default="auto", choices=["xla", "fused", "auto"],
        help="ParameterServerStrategy sparse-path engine: 'xla' (packed "
        "gather + one-hot select lookups, stream/scatter optimizer "
        "apply) or 'fused' (the Pallas kernels in ops/sparse_embedding "
        "— lookup, dedup+apply, and the DeepFM FM interaction keep "
        "touched rows in VMEM instead of round-tripping [n, 128] HBM "
        "intermediates; single-device tables only in v1, bit-exactness "
        "contract in docs/design.md). 'auto' currently resolves to xla "
        "— the fused kernels' chip numbers are queued driver work "
        "(BASELINE.md) and auto never moves the headline onto "
        "unmeasured code.",
    )
    parser.add_argument(
        "--pipeline", default="sync", choices=["sync", "async"],
        help="Step-execution pipeline (data/pipeline.py). 'sync' is the "
        "classic serial loop (parse -> stage -> dispatch, reference "
        "parity). 'async' overlaps the host with the device: bounded "
        "background prefetch runs parse/batching off the step loop's "
        "critical path, staging for window N+1 issues while window N "
        "executes (booked as overlap_s in step anatomy, not data_wait/"
        "stage), and a parse pool (--parse_pool_workers) fans chunk "
        "parsing across host cores. Training results are bit-identical "
        "to sync (tests/test_pipeline.py proves it); pipelines drain "
        "at every task/rendezvous boundary so elastic events never see "
        "a stale in-flight batch.",
    )
    parser.add_argument(
        "--parse_pool_workers", type=non_neg_int, default=0,
        help="Host parse-pool threads for --pipeline async (0 = parse "
        "on the prefetch thread). numpy releases the GIL for the "
        "columnar parse, so threads scale with physical cores; size to "
        "~cores-2, leaving the step loop and heartbeat their own.",
    )
    parser.add_argument(
        "--pipeline_inflight", type=pos_int, default=2,
        help="--pipeline async read-ahead bound: max batches buffered "
        "between the prefetch producer and the step loop. The "
        "backpressure contract — a slow device stalls the producer at "
        "this bound instead of growing host memory.",
    )
    parser.add_argument(
        "--dispatch_depth", type=pos_int, default=2,
        help="--pipeline async: how many dispatched windows are assumed "
        "in flight on the device queue for overlap accounting (staging "
        "issued with a dispatch outstanding books as overlap_s).",
    )
    parser.add_argument(
        "--oov_diagnostics", type=str2bool, nargs="?", const=True,
        default=False,
        help="Report per-step counts of embedding ids >= vocab_size in "
        "worker logs instead of dropping them silently. The fixed-vocab "
        "contract (docs/design.md): out-of-range ids read zeros and "
        "receive no update — upstream ElasticDL's PS lazily grew such "
        "rows; port open-vocabulary models by hashing ids into fixed "
        "bins (preprocessing.Hashing).",
    )
    parser.add_argument(
        "--profile_steps", default="", type=_profile_steps_spec,
        help="'START,END': each worker captures a jax.profiler trace of "
        "its training steps in [START, END) under "
        "<tensorboard_log_dir>/profile (TensorBoard Profile plugin)",
    )
    parser.add_argument(
        "--mesh_model_axis", type=pos_int, default=1,
        help="Size of the mesh's `model` axis in cluster strategies "
        "(total devices = data x model). >1 shards embedding tables over "
        "it (PS mode) and gives mesh-aware zoo models (custom_model() "
        "accepting `mesh`, e.g. transformer.transformer_lm) a parallel "
        "axis: ring-attention context parallelism by default, or "
        "Megatron-style tensor parallelism with "
        "--model_params model_axis_mode=tp",
    )
    parser.add_argument(
        "--task_timeout_s", type=non_neg_int, default=900,
        help="Requeue a dispatched task not reported done within this "
        "many seconds (0 disables). Nonzero by default as the liveness "
        "backstop for a LOST dispatch: get_task retries on "
        "DEADLINE_EXCEEDED, so a reply that died on the wire leaves the "
        "popped task in `doing` with no worker-crash to recover it — "
        "without a timeout the job would hang at job-end waiting on it "
        "forever. At-least-once semantics make a spurious requeue of a "
        "genuinely-slow task safe (it just re-runs).",
    )
    parser.add_argument(
        "--jax_compilation_cache_dir", default="",
        help="Persistent XLA compilation cache directory (shared across "
        "worker restarts). Elastic recovery restarts the world with fresh "
        "processes; with the cache, the re-formed world's compiles are "
        "disk hits instead of recompiles — the dominant recovery cost "
        "after process start (BASELINE.md elasticity numbers).",
    )
    parser.add_argument(
        "--use_bf16", type=str2bool, nargs="?", const=True, default=True,
        help="Compute in bfloat16 on the MXU: forwarded to zoo models "
        "whose custom_model() accepts a use_bf16 parameter (explicit "
        "--model_params use_bf16=... wins)",
    )


def add_cluster_arguments(parser: argparse.ArgumentParser):
    parser.add_argument("--num_workers", type=pos_int, default=1)
    parser.add_argument("--master_addr", default="", help="host:port of the master")
    parser.add_argument("--master_port", type=non_neg_int, default=0,
                        help="0 picks a free port")
    parser.add_argument("--worker_pod_priority", default="")
    parser.add_argument(
        "--metrics_port", type=non_neg_int, default=None,
        help="Embed the observability exporter in the master on this "
        "port, serving /metrics (Prometheus text exposition), /healthz, "
        "and /debug/vars (JSON metric dump + event-journal tail). "
        "0 picks a free port (logged); omit to disable.",
    )
    parser.add_argument("--max_worker_restarts", type=non_neg_int, default=3)
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--image_name", default="")
    parser.add_argument(
        "--need_elasticity", type=str2bool, nargs="?", const=True, default=True
    )
    parser.add_argument(
        "--policy_enabled", type=str2bool, nargs="?", const=True,
        default=True,
        help="Run the goodput-driven elastic policy engine "
        "(master/policy.py): scale-up gated on amortizing the measured "
        "rescale cost, scale-down/hold under rescale thrash, and "
        "budgeted straggler eviction. False = observe-only (PR-4/5 "
        "advisory behavior).",
    )
    parser.add_argument(
        "--policy_amortize_horizon_s", type=float, default=600.0,
        help="Scale-up is approved only when the marginal-throughput "
        "gain of the granted workers repays the goodput ledger's "
        "measured per-rescale cost within this many seconds (see "
        "docs/failure_model.md 'Policy enforcement' for tuning).",
    )
    parser.add_argument(
        "--policy_tick_interval_s", type=float, default=2.0,
        help="Seconds between policy-engine evaluation ticks.",
    )
    parser.add_argument(
        "--policy_min_workers", type=pos_int, default=1,
        help="Enforcement floor: no policy decision (eviction or "
        "scale-down) may shrink the fleet below this.",
    )
    parser.add_argument(
        "--policy_evict_after", type=pos_int, default=3,
        help="A straggler must stay flagged for this many CONSECUTIVE "
        "policy ticks before eviction (on top of the detector's own "
        "hysteresis — one noisy snapshot can never kill a worker).",
    )
    parser.add_argument(
        "--policy_kill_budget", type=non_neg_int, default=1,
        help="Straggler evictions allowed per budget window; 0 keeps "
        "the straggler path advisory-only.",
    )
    parser.add_argument(
        "--policy_kill_budget_window_s", type=float, default=600.0,
        help="Length of the straggler kill-budget window; the budget "
        "refills when a window elapses.",
    )
    parser.add_argument(
        "--slo_enabled", type=str2bool, nargs="?", const=True, default=True,
        help="Run the master's SLO plane (obs/slo.py): a metrics-history "
        "sampler + burn-rate evaluator feeding /slo and the policy "
        "engine's advisory input.",
    )
    parser.add_argument(
        "--slo_goodput_target", type=float, default=0.0,
        help="Goodput-ratio floor for the master goodput SLO; 0 "
        "registers no goodput SLO (the history sampler still runs for "
        "/slo sparklines).",
    )
    parser.add_argument(
        "--slo_compliance_window_s", type=float, default=3600.0,
        help="Rolling error-budget compliance window; the burn-rate "
        "alert windows are the canonical 30-day fractions of this "
        "(docs/observability.md 'SLO plane').",
    )
    parser.add_argument(
        "--slo_tick_interval_s", type=float, default=2.0,
        help="Seconds between SLO-plane sample+evaluate ticks.",
    )
    parser.add_argument(
        "--quality_drift_bins", type=non_neg_int, default=0,
        help="Hash buckets of the train-side feature-id sketch "
        "(obs/quality.py): each worker sketches every train batch into "
        "a process-local DriftMonitor for train-serve skew comparison; "
        "0 disables the hook (the default — no per-step cost).",
    )
    parser.add_argument(
        "--quality_drift_threshold", type=float, default=0.25,
        help="Train-serve sketch divergence (total variation) that "
        "journals a quality_drift breach edge.",
    )
    parser.add_argument(
        "--worker_liveness_timeout_s", type=non_neg_int, default=60,
        help="Kill+relaunch a worker whose heartbeat is silent this long "
        "(0 disables hung-worker detection)",
    )
    parser.add_argument(
        "--devices_per_worker", type=pos_int, default=1,
        help="TPU chips visible to each worker host (mesh = workers x devices)",
    )
    parser.add_argument(
        "--master_resource_request", default="",
        help='k8s resources for the master pod, e.g. "cpu=1,memory=2Gi"',
    )
    parser.add_argument(
        "--worker_resource_request", default="",
        help='k8s resources per worker pod, e.g. "cpu=4,memory=8Gi,google.com/tpu=1"',
    )
    parser.add_argument(
        "--tpu_slice", default="",
        help="Schedule workers onto a named TPU pod slice (e.g. "
        "'v5e-16'): each worker pod is one TPU VM host — it requests "
        "the host's chips (google.com/tpu) and pins to nodes of the "
        "slice's accelerator/topology labels; --num_workers must equal "
        "the slice's host count (v5e-16 = 4 hosts). See "
        "master/tpu_slice.py for known shapes.",
    )
    parser.add_argument(
        "--volume", default="",
        help="k8s volumes mounted into every job pod, e.g. "
        '"claim_name=ckpt-pvc,mount_path=/ckpt" or '
        '"host_path=/mnt/nfs,mount_path=/data"; separate multiple with ";". '
        "Elastic training needs --checkpoint_dir on such a shared mount.",
    )


def build_master_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="elasticdl_tpu master", allow_abbrev=False)
    add_common_arguments(parser)
    add_model_zoo_arguments(parser)
    add_data_arguments(parser)
    add_train_arguments(parser)
    add_cluster_arguments(parser)
    parser.add_argument("--job_type", default="training_with_evaluation")
    return parser


def build_worker_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="elasticdl_tpu worker", allow_abbrev=False)
    add_common_arguments(parser)
    add_model_zoo_arguments(parser)
    add_data_arguments(parser)
    add_train_arguments(parser)
    parser.add_argument("--worker_id", type=non_neg_int, required=True)
    parser.add_argument("--master_addr", required=True)
    parser.add_argument("--job_type", default="training_with_evaluation")
    return parser


def _validate_cross_flags(args):
    if getattr(args, "profile_steps", "") and not getattr(
        args, "tensorboard_log_dir", ""
    ):
        raise ValueError(
            "--profile_steps requires --tensorboard_log_dir (traces are "
            "written under it for the TensorBoard Profile plugin)"
        )


def parse_master_args(argv=None):
    args, unknown = build_master_parser().parse_known_args(argv)
    _apply_log_level(args)
    _validate_cross_flags(args)
    return args


def parse_worker_args(argv=None):
    args, unknown = build_worker_parser().parse_known_args(argv)
    _apply_log_level(args)
    _validate_cross_flags(args)
    return args


def _apply_log_level(args):
    from elasticdl_tpu.common.log_utils import set_default_level

    set_default_level(args.log_level)


def parse_dict_params(params: str) -> dict:
    """Parse 'a=1,b=hello,c=0.5' into {'a': 1, 'b': 'hello', 'c': 0.5}."""
    result = {}
    if not params:
        return result
    for item in params.split(","):
        item = item.strip()
        if not item:
            continue
        if "=" not in item:
            raise ValueError(f"Malformed key=value pair: {item!r}")
        key, value = item.split("=", 1)
        for cast in (int, float):
            try:
                value = cast(value)
                break
            except ValueError:
                continue
        else:
            if isinstance(value, str):
                low = value.lower()
                if low in ("true", "false"):
                    value = low == "true"
        result[key.strip()] = value
    return result


def format_dict_params(params: dict) -> str:
    """Inverse of parse_dict_params: {'a': 1, 'b': True} -> 'a=1,b=true'.
    Used to record the RESOLVED model params (job flags injected by
    model_utils._forward_flag included) into serving artifacts, so a
    reload rebuilds the exact trained model — e.g. DeepFM's table layout
    follows sparse_apply_every, and an artifact recording only the raw
    --model_params string would rebuild the wrong structure."""
    def fmt(value):
        if isinstance(value, bool):
            return "true" if value else "false"
        return str(value)

    for key, value in params.items():
        # ',' is the only non-round-trippable character: parse splits
        # items on ',' before the first '=', so '=' inside a value (a
        # URL, a nested spec) survives the round trip intact.
        if isinstance(value, str) and "," in value:
            raise ValueError(
                f"model param {key}={value!r} cannot round-trip "
                "through the k=v,k=v format"
            )
    return ",".join(f"{k}={fmt(v)}" for k, v in sorted(params.items()))


def args_to_argv(args: argparse.Namespace, keys=None) -> list:
    """Round-trip a namespace back into --flag value argv (client -> pods)."""
    argv = []
    for key, value in sorted(vars(args).items()):
        if keys is not None and key not in keys:
            continue
        if value is None or value == "":
            continue
        argv.extend([f"--{key}", str(value)])
    return argv
