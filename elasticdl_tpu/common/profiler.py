"""Step-windowed jax.profiler tracing.

SURVEY.md §5 names `jax.profiler` the cheap observability win: a trace of
N real training steps captures XLA op timings, HBM transfers, and (on
real hardware) TPU utilization, viewable in TensorBoard's Profile plugin
from the same --tensorboard_log_dir the master's scalar service writes.

Usage: `--profile_steps=START,END` on the job; each worker traces its
own training steps with index in [START, END) (1-based, the value of
`trainer.step` after the step runs) into <log_dir>/profile/worker_<id>.
The training loop brackets its work with `before_steps(current, n)` /
`after_steps(current)`, so tracing starts BEFORE the first in-window
step executes (its XLA compile is captured) and stops right after the
last.  Windowed trainers that run K steps per device call (PS/AllReduce
`train_window`) trace the superset of whole windows overlapping the
range — boundaries round outward to window edges, never silently skip.
A window the loop has already passed logs a loud warning instead of
silently capturing nothing.
"""

from __future__ import annotations

import atexit
import os
from typing import Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.profiler")


def parse_profile_steps(spec: str):
    """'100,120' -> (100, 120); '' -> None."""
    if not spec:
        return None
    try:
        start, end = (int(s) for s in spec.split(","))
    except ValueError as e:
        raise ValueError(
            f"--profile_steps must be 'start,end', got {spec!r}"
        ) from e
    if not (0 <= start < end):
        raise ValueError(f"--profile_steps needs 0 <= start < end: {spec!r}")
    return start, end


class StepProfiler:
    """Starts/stops one jax.profiler trace as the step counter crosses
    the configured window.  Inactive (all no-ops) when unconfigured;
    --profile_steps without a log dir is rejected loudly (a silently
    dangling flag is the round-1 failure mode this replaces)."""

    def __init__(self, log_dir: str, profile_steps: str, worker_id: int = 0):
        if profile_steps and not log_dir:
            raise ValueError(
                "--profile_steps requires --tensorboard_log_dir (traces "
                "are written under it for the TensorBoard Profile plugin)"
            )
        window = parse_profile_steps(profile_steps)
        self._window = window
        self._worker_id = int(worker_id)
        self._dir = (
            os.path.join(log_dir, "profile", f"worker_{worker_id}")
            if window
            else ""
        )
        self._tracing = False
        self._done = False
        if window:
            # Shutdown-path flush: a worker that exits (or is preempted)
            # mid-window would otherwise never reach the task loop's
            # stop() and lose the whole trace.  atexit + the worker
            # main's SIGTERM->SystemExit conversion flush a PARTIAL trace
            # instead; stop() is idempotent, so the normal path is
            # unaffected.
            atexit.register(self.stop)

    def before_steps(self, current_step: int, n: int = 1):
        """About to run steps current_step+1 .. current_step+n: start the
        trace if any of them fall in the window (called BEFORE the device
        dispatch so the first in-window step — and its compile — is
        captured even when n steps run as one fused window)."""
        if self._window is None or self._done or self._tracing:
            return
        start, end = self._window
        first, last = current_step + 1, current_step + n
        if first >= end:
            logger.warning(
                "Profile window [%d, %d) already passed at step %d — "
                "no trace captured (window smaller than the training "
                "loop's step granularity?)",
                start,
                end,
                current_step,
            )
            self._done = True
            return
        if last >= start:
            try:
                import jax

                # Inside the guard: an unwritable/unmounted trace dir must
                # disable profiling, never crash training.
                os.makedirs(self._dir, exist_ok=True)
                jax.profiler.start_trace(self._dir)
                self._tracing = True
                logger.info(
                    "Profiling steps [%d, %d) -> %s", start, end, self._dir
                )
                self._journal_window("open", at_step=current_step)
            except Exception:
                logger.exception("start_trace failed; profiling disabled")
                self._done = True

    def after_steps(self, current_step: int):
        """Steps up to current_step have run: stop once the last
        in-window step (end - 1) is done."""
        if self._tracing and current_step >= self._window[1] - 1:
            self.stop()

    def stop(self):
        # Drop the shutdown hook first (bound-method equality): repeated
        # in-process construction (tests, e2e harnesses) must not pin
        # every historical profiler until interpreter exit.
        atexit.unregister(self.stop)
        if not self._tracing:
            return
        import jax

        try:
            jax.profiler.stop_trace()
            logger.info("Profile trace written to %s", self._dir)
        except Exception:
            logger.exception("stop_trace failed")
        self._tracing = False
        self._done = True
        self._journal_window("close")

    def _journal_window(self, action: str, at_step=None):
        """Journal a ``profile_window`` event so postmortem timelines
        (obs.report) can point at the TensorBoard trace that covers an
        anomalous window.  Best-effort: journaling failure must never
        break tracing (this also runs on the atexit shutdown path)."""
        try:
            from elasticdl_tpu import obs

            fields = dict(
                worker_id=self._worker_id,
                action=action,
                step_start=self._window[0],
                step_end=self._window[1],
                trace_dir=self._dir,
            )
            if at_step is not None:
                fields["at_step"] = int(at_step)
            obs.journal().record("profile_window", **fields)
        except Exception:
            logger.exception("profile_window journal record failed")
