"""Model-zoo module loading.

Parity: elasticdl/python/common/model_utils.py in the reference — dynamic
import of the user's model module by zoo path + dotted module name, and
resolution of the contract functions (custom_model / loss / optimizer /
dataset_fn / eval_metrics_fn / callbacks / custom_data_reader).
"""

from __future__ import annotations

import importlib
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from elasticdl_tpu.common.args import parse_dict_params
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("common.model_utils")


@dataclass
class ModelSpec:
    module: Any
    custom_model: Callable
    loss: Callable
    optimizer: Callable
    dataset_fn: Callable
    # Optional vectorized twin of dataset_fn: (columns dict, mode,
    # metadata) -> (features tree, labels) operating on whole column
    # arrays.  With a reader exposing read_columns(task), the worker's
    # task pipeline then never touches individual records
    # (data/columnar.py — the 1-core-host data plane).
    columnar_dataset_fn: Optional[Callable] = None
    eval_metrics_fn: Optional[Callable] = None
    callbacks: Optional[Callable] = None
    custom_data_reader: Optional[Callable] = None
    # Optional: returns a parallel.sparse_optim.SparseOptimizer for the
    # model's sharded embedding tables (PS mode; reference: the Go PS ran
    # one optimizer for dense+sparse, here the sparse path is explicit).
    embedding_optimizer: Optional[Callable] = None
    model_params: dict = field(default_factory=dict)

    def build_model(self, mesh=None):
        """`mesh` is forwarded only to mesh-aware models (custom_model
        declaring a `mesh` parameter — e.g. the transformer's ring
        attention needs the mesh for its context axis)."""
        import inspect

        params = dict(self.model_params)
        if mesh is not None and "mesh" not in params:
            try:
                accepts_mesh = (
                    "mesh" in inspect.signature(self.custom_model).parameters
                )
            except (TypeError, ValueError):
                accepts_mesh = False
            if accepts_mesh:
                from elasticdl_tpu.common.log_utils import get_logger

                params["mesh"] = mesh
                # e2e tests grep this line to prove the mesh actually
                # reached the model (TP/CP silently degrade to
                # single-device layouts without it).
                get_logger("common.model_utils").info(
                    "Mesh-aware model: forwarding mesh %s",
                    dict(mesh.shape),
                )
        return self.custom_model(**params)


def load_module(model_zoo: str, model_def: str):
    """Import `model_def` (dotted module path) from the `model_zoo` directory.

    `model_zoo` may be a directory (added to sys.path, reference behavior)
    or an importable package name.
    """
    if os.path.isdir(model_zoo):
        parent = os.path.abspath(os.path.join(model_zoo, os.pardir))
        if parent not in sys.path:
            sys.path.insert(0, parent)
        zoo_package = os.path.basename(os.path.normpath(model_zoo))
        module_name = f"{zoo_package}.{model_def}"
    else:
        module_name = f"{model_zoo}.{model_def}" if model_zoo else model_def
    return importlib.import_module(module_name)


def _forward_flag(custom_model, model_params: dict, name, value) -> None:
    """Inject a job-flag value into model_params when custom_model
    declares the parameter and --model_params didn't set it explicitly."""
    import inspect

    try:
        accepts = name in inspect.signature(custom_model).parameters
    except (TypeError, ValueError):
        accepts = False
    if accepts and name not in model_params:
        model_params[name] = value


def load_model_spec(args) -> ModelSpec:
    """Resolve the model-zoo contract from parsed args."""
    module = load_module(args.model_zoo, args.model_def)

    def require(name):
        fn = getattr(module, name, None)
        if fn is None:
            raise ValueError(
                f"Model module {args.model_def!r} must define {name}()"
            )
        return fn

    def optional(name):
        return getattr(module, name, None) if name else None

    custom_model = require("custom_model")
    model_params = parse_dict_params(args.model_params)
    # Job flags reach opted-in models here: a zoo model declares the
    # parameter on custom_model() and the flag value flows into
    # model_params.  Explicit --model_params wins; models without the
    # parameter are untouched.
    # - use_bf16: mixed precision (e.g. cifar10's conv/activation dtype).
    # - sparse_apply_every: per-mode table layout (deepfm splits its
    #   merged table under strict apply at large scale — BASELINE.md
    #   table-scale probe).
    _forward_flag(
        custom_model, model_params, "use_bf16",
        bool(getattr(args, "use_bf16", True)),
    )
    job_w = getattr(args, "sparse_apply_every", 1) or 1
    if job_w != "auto":
        job_w = int(job_w)
    explicit_w = model_params.get("sparse_apply_every")
    if explicit_w is not None and explicit_w != job_w and job_w != "auto":
        # job_w == "auto" resolves only at trainer init, so no static
        # comparison is possible here — and an explicit numeric layout
        # pin under the auto default is the documented escape hatch, not
        # an inconsistency; warning on every such job would be noise.
        # An explicit --model_params sparse_apply_every wins over the job
        # flag here (layout override is a supported escape hatch), but
        # the trainer still APPLIES with the job flag's W — the model
        # would run a layout the strict/windowed cost analysis picked for
        # a different mode.  Numerically valid, so warn rather than fail.
        logger.warning(
            "model_params sparse_apply_every=%s overrides the job flag "
            "--sparse_apply_every=%s for the TABLE LAYOUT only; the "
            "trainer still applies with the job flag's interval. Drop "
            "the model param unless you are deliberately pinning a "
            "layout.",
            explicit_w, job_w,
        )
    _forward_flag(
        custom_model, model_params, "sparse_apply_every", job_w,
    )
    # - sparse_kernel: lookup/FM engine selection for models that thread
    #   it into their Embedding layers (deepfm); worker main also sets
    #   the process default, so this forward only matters for the
    #   layout-aware auto rules (deepfm merges its table under fused).
    _forward_flag(
        custom_model, model_params, "sparse_kernel",
        getattr(args, "sparse_kernel", "auto") or "auto",
    )

    return ModelSpec(
        module=module,
        custom_model=custom_model,
        loss=require(args.loss),
        optimizer=require(args.optimizer),
        dataset_fn=require(args.dataset_fn),
        columnar_dataset_fn=optional("columnar_dataset_fn"),
        eval_metrics_fn=optional(args.eval_metrics_fn),
        callbacks=optional(args.callbacks),
        custom_data_reader=optional(args.custom_data_reader),
        embedding_optimizer=optional("embedding_optimizer"),
        model_params=model_params,
    )
