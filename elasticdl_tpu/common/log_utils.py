"""Structured logging helpers.

Parity: elasticdl/python/common/log_utils.py in the reference.
"""

import logging
import sys

_LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"

_initialized = False


def _init_root():
    global _initialized
    if _initialized:
        return
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(_LOG_FORMAT))
    root = logging.getLogger("elasticdl_tpu")
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    root.propagate = False
    _initialized = True


def get_logger(name: str, level=None) -> logging.Logger:
    _init_root()
    logger = logging.getLogger(f"elasticdl_tpu.{name}")
    if level is not None:
        logger.setLevel(level)
    return logger


def set_default_level(level):
    """Apply --log_level to the whole framework (root elasticdl_tpu logger)."""
    _init_root()
    if isinstance(level, str):
        level = level.upper()
    logging.getLogger("elasticdl_tpu").setLevel(level)


default_logger = get_logger("default")
