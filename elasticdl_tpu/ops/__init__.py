from elasticdl_tpu.ops.flash_attention import flash_attention  # noqa: F401
from elasticdl_tpu.ops import sparse_embedding  # noqa: F401
