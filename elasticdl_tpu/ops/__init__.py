from elasticdl_tpu.ops.flash_attention import flash_attention  # noqa: F401
