"""Fused Pallas TPU kernels for the sparse embedding hot path.

BENCH_r04 named the perf ceiling: DeepFM trains at 972.9k samples/s/chip
with ``bound: sparse-row-count`` (ns_per_row 39.5, floor_frac 0.632) —
the lookup gather + one-hot select and the dedup+scatter optimizer
update, not matmul, are the wall.  The XLA formulation of that path
(parallel/packed.py + parallel/sparse_optim.py) round-trips several
``[n, block_width]`` (512 B/row) intermediates through HBM per step:

- ``pk.lookup``: gather full storage rows to an HBM ``[n, 128]`` buffer,
  re-read it for the one-hot slot-select einsum, write ``[n, dim_pad]``;
- ``scatter_apply`` (per optimizer): 2-4 such lookups for the slot rows
  PLUS 3-4 ``expand_updates`` scatters, each materializing a tiled+
  masked ``[n, 128]`` update operand before the full-row scatter-add.

The kernels here keep the touched rows in VMEM between those steps
instead (the same treatment ``ops/flash_attention.py`` gave the dense
side — 2.4x on the transformer):

``fused_lookup``       gather-and-lane-select in one kernel: each
                       storage row is DMA'd HBM->VMEM once, the packed
                       slot's lanes are selected with an EXACT f32
                       dynamic slice (no MXU contraction, so no
                       precision= escape hatch needed), and only the
                       compact ``[n, dim_pad]`` result is written back.
``fused_dedup_apply``  the optimizer update in one pass: the sort-free
                       segment-combine (scatter-max representatives —
                       the same mechanism as
                       ``packed.dedup_representatives``, pinned
                       bit-exact by tests) runs as a cheap O(n)
                       prologue, then ONE kernel walks the touched
                       representatives, DMAs table+slot rows into VMEM,
                       applies sgd/momentum/adagrad/adam slot math in
                       delta form (the scatter path's read-modify-write
                       adds, <= 1 ulp — see its docstring), and DMAs
                       the rows back — zero ``[n, 128]`` HBM
                       intermediates.
``fused_lookup_fm``    the DeepFM combined ``1+dim`` lookup feeding the
                       FM second-order term: one pass emits the field
                       activations (the deep tower needs them) AND the
                       first-order sum + FM partial sums, so the FM
                       term never re-reads the ``[batch, fields, dim]``
                       tensor from HBM.  Differentiable via custom_vjp
                       (the perturbation-capture input ``bet`` carries
                       the sparse gradient, exactly like the unfused
                       Embedding layer's capture point).

Mode selection: the kernels are wired as a third ``fused`` mode behind
``sparse_optim``'s stream/scatter switch and the ``--sparse_kernel
{xla,fused,auto}`` job flag (threaded through ps_trainer, the Embedding
layer, and the DeepFM zoo model).  ``auto`` currently resolves to
``xla``: the fused path's chip numbers are queued driver work
(BASELINE.md "queued chip work") and auto must not move the headline on
unmeasured code — flip AUTO_FUSED_READY once the evidence lands.

Every kernel runs in Pallas interpret mode off-TPU (same
``_use_interpret()`` pattern as flash_attention), so tier-1 CPU tests
exercise the real kernel bodies, and ``scripts/convergence_ab.py
--sparse-kernel fused`` gates end-to-end training quality.

Sharded dispatch (round 7): ``pl.pallas_call`` is not
SPMD-partitionable the way the XLA gather/scatter ops are, so on a
multi-device mesh every fused kernel routes through ``shard_map``
(built via the parallel/compile.py shim) instead of the SPMD
partitioner: embedding tables shard their storage blocks over the
mesh's ``model`` axis (``table_partition_axis``), each shard runs the
SAME kernel body over its resident blocks with ids routed to their
owning shard (out-of-shard ids contribute exact zeros / are dropped by
the dedup prologue), and the cross-shard combine is a ``psum`` for
lookups and nothing at all for the apply (each shard owns its rows'
writes; the batch gradient all-gathers over ``data`` first so every
replica applies the identical update).  ``dispatch_route(mesh)``
selects ``single_device`` (plain pallas_call) vs ``shard_map``;
trainers journal the decision in ``sparse_kernel_selected``.  Tables
whose blocks don't divide the model axis replicate (each shard then
runs the full-table body — still inside shard_map, because manual
sharding is what makes a pallas body legal on a multi-device mesh).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from elasticdl_tpu.parallel import packed as pk
from elasticdl_tpu.parallel.packed import PackedSpec

#: Ids processed per grid step.  VMEM cost per step is bounded by
#: TILE x dim_padded f32 (the gsum / output tiles) + a double-buffered
#: pair of 512 B row scratches — ~130 KB at the default, far under the
#: ~16 MB scoped-VMEM budget (see docs/design.md "VMEM budget math").
DEFAULT_IDS_PER_TILE = 128
#: Batch rows per grid step of the FM kernel (x fields x (1+dim) f32 for
#: the bet/acts tiles — 8 x 26 x 16 x 4 B = 13 KB at DeepFM shapes).
DEFAULT_FM_BATCH_TILE = 8

KERNELS = ("xla", "fused", "auto")

#: Gate for auto mode: the fused kernels' chip numbers are queued driver
#: work (BASELINE.md).  Until a driver bench verifies them, `auto`
#: resolves to the measured xla path so the headline never silently
#: moves onto unmeasured code.  Flip to True WITH the chip evidence.
AUTO_FUSED_READY = False

_DEFAULT_KERNEL = "xla"


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def set_default_kernel(kernel: str) -> None:
    """Process-wide default consulted by Embedding layers whose model
    did not thread ``sparse_kernel`` explicitly (worker main sets this
    from ``--sparse_kernel`` before the model is built)."""
    global _DEFAULT_KERNEL
    if kernel not in KERNELS:
        raise ValueError(f"sparse_kernel must be one of {KERNELS}, got {kernel!r}")
    _DEFAULT_KERNEL = kernel


def default_kernel() -> str:
    return _DEFAULT_KERNEL


def resolve_kernel(requested: Optional[str] = None) -> str:
    """'xla' or 'fused' from a requested mode (None = process default).

    ``auto`` prefers the fused kernels only once AUTO_FUSED_READY is
    flipped by chip evidence (see module docstring); until then it IS
    the xla path, logged once by ps_trainer at init.
    """
    kernel = requested or _DEFAULT_KERNEL
    if kernel not in KERNELS:
        raise ValueError(f"sparse_kernel must be one of {KERNELS}, got {kernel!r}")
    if kernel == "auto":
        return "fused" if AUTO_FUSED_READY else "xla"
    return kernel


# ----------------------------------------------------------------------
# sharded dispatch (multi-device meshes; see the module docstring)
# ----------------------------------------------------------------------

#: Process-default dispatch mesh: worker/main registers the job's mesh
#: so Embedding layers that did not thread `mesh` explicitly still take
#: the shard_map route on multi-device worlds (an unpartitionable
#: pallas_call traced into an SPMD program is the failure mode this
#: replaces).  Ops-level functions consult ONLY their explicit `mesh`
#: argument; the layer resolves None against this default.
_DISPATCH_MESH = None


def set_dispatch_mesh(mesh) -> None:
    global _DISPATCH_MESH
    _DISPATCH_MESH = mesh


def dispatch_mesh():
    return _DISPATCH_MESH


def dispatch_route(mesh=None) -> str:
    """'single_device' (plain pallas_call) or 'shard_map' (per-shard
    kernel bodies inside shard_map) for a given mesh."""
    if mesh is not None and int(mesh.devices.size) > 1:
        return "shard_map"
    return "single_device"


def table_partition_axis(num_blocks: int, mesh) -> Optional[str]:
    """Mesh axis the fused engine shards a table's storage blocks over:
    the `model` axis when it divides them (the one table-placement
    decision — ps_trainer's rule table and the shard_map in_specs here
    both read it), else None (replicate — the table is tiny)."""
    from elasticdl_tpu.parallel.mesh import MODEL_AXIS

    if mesh is None:
        return None
    # Host ints throughout (mesh shape and PackedSpec fields are static
    # Python values — no tracer ever reaches this decision).
    msize = mesh.shape.get(MODEL_AXIS, 1)
    if msize > 1 and num_blocks % msize == 0:
        return MODEL_AXIS
    return None


def _shard_local_spec(spec: PackedSpec, mesh) -> PackedSpec:
    """The per-shard PackedSpec under model-axis block sharding: same
    dim/packing, 1/msize of the storage blocks (exact because
    table_partition_axis demanded divisibility)."""
    from elasticdl_tpu.parallel.mesh import MODEL_AXIS

    msize = int(mesh.shape[MODEL_AXIS])
    return PackedSpec(spec.vocab_padded // msize, spec.dim)


def _batch_spec(n: int, mesh):
    """PartitionSpec for a batch-derived dim0 of static size `n`: shard
    over `data` when it divides (the trainers' padded batches always
    do), else replicate — either split is CORRECT (routing/combine
    never depend on which ids land on which data shard), sharding just
    avoids redundant per-device work."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel.mesh import DATA_AXIS

    dp = int(mesh.shape.get(DATA_AXIS, 1))
    return P(DATA_AXIS) if n % dp == 0 else P()


# ----------------------------------------------------------------------
# shared host-side prologue helpers
# ----------------------------------------------------------------------


def _pad_to_tile(n: int, tile: int) -> int:
    return -(-n // tile) * tile


def _block_and_lane(spec: PackedSpec, ids):
    """(block_ids, lane0) int32 for the kernels' row DMA: storage block
    CLAMPED to [0, num_blocks) — a deliberate choice for out-of-range
    ids (every DMA must target a real row), NOT pk.lookup's semantics
    there (its jnp.take default fill-mode reads NaN for OOB-high and
    wraps negatives).  Bit-equivalence with pk.lookup therefore holds
    for ids in [0, vocab_padded); out-of-range ids are the Embedding
    layer's job (safe ids + validity mask), behind which the engines
    are bit-identical — see fused_lookup's docstring and
    tests/test_sparse_kernels.py.  The slot lane comes from floor-mod,
    matching the one-hot select for every id."""
    ids = ids.astype(jnp.int32)
    r = spec.rows_per_block
    blocks = jnp.clip(ids // r, 0, spec.num_blocks - 1)
    lane0 = (ids % r) * spec.dim_padded
    return blocks, lane0


# ----------------------------------------------------------------------
# fused lookup: gather + lane select in one kernel
# ----------------------------------------------------------------------


def _lookup_kernel(blocks_ref, lane0_ref, table_ref, out_ref, rows, sem,
                   *, tile, dim_padded):
    """One grid step: `tile` ids.  Per id, DMA its 512 B storage row
    HBM->VMEM (double-buffered: row i+1's fetch overlaps row i's
    select) and write only the slot's dim_padded lanes to the compact
    output block."""
    g = pl.program_id(0)

    def fetch(i, slot):
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(blocks_ref[g * tile + i], 1), :],
            rows.at[slot],
            sem.at[slot],
        )

    fetch(0, 0).start()

    def body(i, _):
        slot = jax.lax.rem(i, 2)

        @pl.when(i + 1 < tile)
        def _prefetch():
            fetch(i + 1, 1 - slot).start()

        fetch(i, slot).wait()
        row = rows[slot, 0, :]
        sel = jax.lax.dynamic_slice(
            row, (lane0_ref[g * tile + i],), (dim_padded,)
        )
        out_ref[pl.ds(i, 1), :] = sel[None, :]
        return 0

    jax.lax.fori_loop(0, tile, body, 0)


def _lookup_impl(spec: PackedSpec, interpret: bool, tile: int, packed, ids):
    n = ids.shape[0]
    if n == 0:
        return jnp.zeros((0, spec.dim), packed.dtype)
    tile = min(tile, _pad_to_tile(n, 8))
    n_pad = _pad_to_tile(n, tile)
    ids_pad = jnp.pad(ids.astype(jnp.int32), (0, n_pad - n))
    blocks, lane0 = _block_and_lane(spec, ids_pad)
    out = pl.pallas_call(
        functools.partial(
            _lookup_kernel, tile=tile, dim_padded=spec.dim_padded
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(n_pad // tile,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(
                (tile, spec.dim_padded), lambda g, *_: (g, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((2, 1, spec.block_width), packed.dtype),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((n_pad, spec.dim_padded), packed.dtype),
        interpret=interpret,
    )(blocks, lane0, packed)
    return out[:n, : spec.dim]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _lookup_diff(spec, interpret, tile, packed, ids):
    return _lookup_impl(spec, interpret, tile, packed, ids)


def _lookup_fwd(spec, interpret, tile, packed, ids):
    out = _lookup_impl(spec, interpret, tile, packed, ids)
    return out, ids


def _lookup_bwd(spec, interpret, tile, ids, g):
    # Same cotangent the packed scatter path owns: duplicate ids sum,
    # out-of-range ids drop.  (pk.lookup's fill-mode backward would
    # drop/wrap OOV cotangents differently, but every caller masks
    # invalid positions to zero gradient first — the Embedding layer's
    # validity mask — so the two backwards agree where gradients are
    # nonzero.)
    d_packed = pk.grad_accumulate(
        spec, jnp.zeros(spec.packed_shape, g.dtype), ids, g
    )
    return d_packed, jnp.zeros(ids.shape, jax.dtypes.float0)


_lookup_diff.defvjp(_lookup_fwd, _lookup_bwd)


def _sharded_lookup_impl(spec, interpret, tile, mesh, packed, ids):
    """shard_map route of the lookup: table blocks P(model), ids
    routed to their owning shard, per-shard kernel bodies, psum
    combine.  Out-of-range ids read ZEROS here (no shard owns them)
    where the single-device kernel clamp-reads a real row — identical
    through the Embedding layer's validity mask, which is the only
    sanctioned consumer of out-of-range ids."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel import compile as pc
    from elasticdl_tpu.parallel.mesh import MODEL_AXIS

    axis = table_partition_axis(spec.num_blocks, mesh)
    local_spec = _shard_local_spec(spec, mesh) if axis else spec
    data = _batch_spec(ids.shape[0], mesh)

    def body(packed_l, ids_l):
        if axis is None:
            return _lookup_impl(spec, interpret, tile, packed_l, ids_l)
        rows_local = local_spec.vocab_padded
        start = jax.lax.axis_index(MODEL_AXIS) * rows_local
        local = ids_l.astype(jnp.int32) - start
        inshard = (local >= 0) & (local < rows_local)
        rows = _lookup_impl(
            local_spec, interpret, tile, packed_l,
            jnp.where(inshard, local, 0),
        )
        rows = rows * inshard[:, None].astype(rows.dtype)
        # Each valid id is owned by exactly one shard; the psum adds
        # exact zeros elsewhere, so owner bits pass through untouched.
        return jax.lax.psum(rows, MODEL_AXIS)

    return pc.shard_map_call(
        body, mesh,
        in_specs=(P(axis), data),
        out_specs=data,
        check_vma=False,
    )(packed, ids)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _sharded_lookup_diff(spec, interpret, tile, mesh, packed, ids):
    return _sharded_lookup_impl(spec, interpret, tile, mesh, packed, ids)


def _sharded_lookup_fwd(spec, interpret, tile, mesh, packed, ids):
    out = _sharded_lookup_impl(spec, interpret, tile, mesh, packed, ids)
    return out, ids


def _sharded_lookup_bwd(spec, interpret, tile, mesh, ids, g):
    # Same global segment-sum cotangent as the single-device route —
    # plain XLA scatters, which the SPMD partitioner shards fine (the
    # custom_vjp keeps the backward OUTSIDE shard_map on purpose).
    d_packed = pk.grad_accumulate(
        spec, jnp.zeros(spec.packed_shape, g.dtype), ids, g
    )
    return d_packed, jnp.zeros(ids.shape, jax.dtypes.float0)


_sharded_lookup_diff.defvjp(_sharded_lookup_fwd, _sharded_lookup_bwd)


def fused_lookup(
    spec: PackedSpec,
    packed,
    ids,
    *,
    mesh=None,
    interpret: Optional[bool] = None,
    tile: int = DEFAULT_IDS_PER_TILE,
):
    """Drop-in for ``packed.lookup``: ids [n] int32 -> [n, dim].

    Bit-exact vs pk.lookup for every id in ``[0, vocab_padded)`` (the
    one-hot einsum at precision=HIGHEST is an exact f32 select; so is
    the kernel's lane slice).  Out-of-range ids — which every caller
    masks BEFORE the lookup (the Embedding layer's safe-id contract) —
    read a clamped storage row here, where pk.lookup's jnp.take
    fill-mode reads NaN (OOB-high) or wraps (negative); through the
    Embedding layer the two paths are bit-identical because the
    validity mask zeroes those positions either way (pinned by
    tests/test_sparse_kernels.py).  Differentiable in the table
    (sparse segment-sum cotangent).

    `mesh`: a multi-device mesh routes through shard_map (per-shard
    kernel bodies over model-axis table shards, psum combine — module
    docstring "Sharded dispatch"); None / single device keeps the
    plain pallas_call.
    """
    interpret = _use_interpret() if interpret is None else interpret
    if dispatch_route(mesh) == "shard_map":
        return _sharded_lookup_diff(spec, interpret, tile, mesh, packed, ids)
    return _lookup_diff(spec, interpret, tile, packed, ids)


# ----------------------------------------------------------------------
# fused dedup + optimizer apply
# ----------------------------------------------------------------------

#: Table-shaped operands per optimizer kind, in kernel-operand order.
#: The table itself is always first; the rest are the slot names.
_KIND_SLOTS: Dict[str, Tuple[str, ...]] = {
    "sgd": (),
    "momentum": ("momentum",),
    "adagrad": ("accumulator",),
    "adam": ("m", "v", "t"),
    "adam_global": ("m", "v"),
}


def _apply_math(kind, hyper, lane_mask, g, subs, tr):
    """Per-representative optimizer math on dim_padded lane vectors.

    Returns the DELTAS to add to each operand's lanes (table first, then
    slots in _KIND_SLOTS order) — delta form so the written values are
    bit-identical to the scatter path's read-modify-write adds.  `g` is
    the summed gradient (pad lanes zero), `subs` the current lane
    vectors, `tr` the adam bias-correction step count (scalar).
    """
    lr = hyper["learning_rate"]
    if kind == "sgd":
        return (-lr * g,)
    if kind == "momentum":
        mu = hyper["momentum"]
        v = subs[1]
        v_new = mu * v + g
        step = (mu * v_new + g) if hyper["nesterov"] else v_new
        return (-lr * step, v_new - v)
    if kind == "adagrad":
        eps = hyper["epsilon"]
        acc = subs[1]
        gg = g * g
        new_acc = acc + gg
        update = -lr * g / (jnp.sqrt(new_acc) + eps)
        return (update, gg)
    # adam / adam_global
    b1, b2, eps = hyper["beta_1"], hyper["beta_2"], hyper["epsilon"]
    m, v = subs[1], subs[2]
    m_new = b1 * m + (1 - b1) * g
    v_new = b2 * v + (1 - b2) * g * g
    m_hat = m_new / (1 - b1 ** tr)
    v_hat = v_new / (1 - b2 ** tr)
    update = -lr * m_hat / (jnp.sqrt(v_hat) + eps)
    if kind == "adam":
        # Per-row t increments by 1 on REAL lanes only (pad lanes stay
        # zero — the packed-invariant the scatter path keeps too).
        return (update, m_new - m, v_new - v, lane_mask)
    return (update, m_new - m, v_new - v)


def _dedup_apply_kernel(blocks_ref, lane0_ref, touched_ref, gsum_ref,
                        tr_ref, *refs, kind, hyper, tile, dim_padded,
                        dim, n_tables):
    """Grid step over `tile` representatives.  For each touched one:
    DMA the table row + slot rows HBM->VMEM (all fetches in flight
    together), apply the optimizer math to the slot's lanes, DMA the
    updated rows back.  The TPU grid is sequential, so two
    representatives sharing a storage row serialize correctly."""
    # refs layout: n_tables ANY-space input refs, n_tables output refs
    # (input_output_aliases makes each pair one buffer — read and write
    # through the OUTPUT ref), then scratch: rows VMEM
    # [n_tables, 1, block_width] and the in/out DMA semaphores.
    tables = refs[n_tables : 2 * n_tables]
    rows, sem_in, sem_out = refs[2 * n_tables :]
    g = pl.program_id(0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, dim_padded), 1)[0]
    lane_mask = (lane < dim).astype(gsum_ref.dtype)

    def body(i, _):
        pos = g * tile + i

        @pl.when(touched_ref[pos] != 0)
        def _apply():
            block = blocks_ref[pos]
            lane0 = lane0_ref[pos]
            for t in range(n_tables):
                pltpu.make_async_copy(
                    tables[t].at[pl.ds(block, 1), :],
                    rows.at[t],
                    sem_in.at[t],
                ).start()
            for t in range(n_tables):
                pltpu.make_async_copy(
                    tables[t].at[pl.ds(block, 1), :],
                    rows.at[t],
                    sem_in.at[t],
                ).wait()
            subs = tuple(
                jax.lax.dynamic_slice(
                    rows[t, 0, :], (lane0,), (dim_padded,)
                )
                for t in range(n_tables)
            )
            gvec = gsum_ref[i, :]
            tr = tr_ref[0, 0]
            if kind == "adam":
                # Scatter-path contract: tr = max(t_before + 1, 1) read
                # from the count slot's first real lane.
                tr = jnp.maximum(subs[3][0] + 1.0, 1.0)
            deltas = _apply_math(kind, hyper, lane_mask, gvec, subs, tr)
            for t in range(n_tables):
                updated = jax.lax.dynamic_update_slice(
                    rows[t, 0, :], subs[t] + deltas[t], (lane0,)
                )
                rows[t, 0, :] = updated
                pltpu.make_async_copy(
                    rows.at[t],
                    tables[t].at[pl.ds(block, 1), :],
                    sem_out.at[t],
                ).start()
            for t in range(n_tables):
                pltpu.make_async_copy(
                    rows.at[t],
                    tables[t].at[pl.ds(block, 1), :],
                    sem_out.at[t],
                ).wait()

        return 0

    jax.lax.fori_loop(0, tile, body, 0)


def _dedup_apply_core(spec, kind, hyper, tables, ids, grads, tr,
                      interpret, tile):
    """The dedup prologue + ONE kernel pass over `tables` (packed table
    first, then slot arrays in _KIND_SLOTS order), all in the given
    spec's (possibly per-shard) coordinate space.  Returns the updated
    arrays in operand order."""
    safe, gsum, touched = pk.dedup_representatives(spec, ids, grads)
    tch = touched.astype(tables[0].dtype)[:, None]
    gsum = gsum * tch  # the scatter path's masking, same bits

    n = safe.shape[0]
    tile = min(tile, _pad_to_tile(max(n, 1), 8))
    n_pad = _pad_to_tile(max(n, 1), tile)
    pad = n_pad - n
    safe_pad = jnp.pad(safe, (0, pad))
    touched_pad = jnp.pad(touched.astype(jnp.int32), (0, pad))
    blocks, lane0 = _block_and_lane(spec, safe_pad)
    if spec.dim != spec.dim_padded:
        gsum = jnp.pad(gsum, ((0, 0), (0, spec.dim_padded - spec.dim)))
    gsum_pad = jnp.pad(gsum, ((0, pad), (0, 0)))

    n_tables = len(tables)
    # Operand order: 3 prefetch scalars, gsum tile, tr scalar, then the
    # aliased table refs.  input_output_aliases indexes INCLUDE the
    # prefetch operands.
    aliases = {5 + t: t for t in range(n_tables)}
    outs = pl.pallas_call(
        functools.partial(
            _dedup_apply_kernel,
            kind=kind,
            hyper=hyper,
            tile=tile,
            dim_padded=spec.dim_padded,
            dim=spec.dim,
            n_tables=n_tables,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=3,
            grid=(n_pad // tile,),
            in_specs=[
                pl.BlockSpec((tile, spec.dim_padded), lambda g, *_: (g, 0)),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ]
            + [pl.BlockSpec(memory_space=pltpu.ANY)] * n_tables,
            out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * n_tables,
            scratch_shapes=[
                pltpu.VMEM(
                    (n_tables, 1, spec.block_width), tables[0].dtype
                ),
                pltpu.SemaphoreType.DMA((n_tables,)),
                pltpu.SemaphoreType.DMA((n_tables,)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(t.shape, t.dtype) for t in tables
        ],
        input_output_aliases=aliases,
        interpret=interpret,
    )(blocks, lane0, touched_pad, gsum_pad, tr, *tables)
    return tuple(outs)


def _sharded_dedup_apply(spec, kind, hyper, tables, ids, grads, tr, mesh,
                         interpret, tile):
    """shard_map route of the optimizer apply: table + slot blocks
    P(model), the batch (ids, grads) all-gathered over `data` so every
    replica of a table shard applies the IDENTICAL update (the dedup
    sees the same global occurrence order as single-device — same
    summed-gradient bits), ids routed to their owning shard (-1 =
    dropped by the dedup prologue, exactly like padding ids).  No
    cross-shard combine: each shard owns its rows' writes."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel import compile as pc
    from elasticdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    axis = table_partition_axis(spec.num_blocks, mesh)
    local_spec = _shard_local_spec(spec, mesh) if axis else spec
    data = _batch_spec(ids.shape[0], mesh)
    data_sharded = data != P()

    def body(ids_l, grads_l, tr_l, *tables_l):
        if data_sharded:
            ids_g = jax.lax.all_gather(ids_l, DATA_AXIS, tiled=True)
            grads_g = jax.lax.all_gather(grads_l, DATA_AXIS, tiled=True)
        else:
            ids_g, grads_g = ids_l, grads_l
        if axis is None:
            return _dedup_apply_core(
                spec, kind, hyper, tables_l, ids_g, grads_g, tr_l,
                interpret, tile,
            )
        rows_local = local_spec.vocab_padded
        start = jax.lax.axis_index(MODEL_AXIS) * rows_local
        local = ids_g.astype(jnp.int32) - start
        inshard = (local >= 0) & (local < rows_local)
        routed = jnp.where(inshard, local, -1)
        return _dedup_apply_core(
            local_spec, kind, hyper, tables_l, routed, grads_g, tr_l,
            interpret, tile,
        )

    table_p = P(axis) if axis else P()
    return pc.shard_map_call(
        body, mesh,
        in_specs=(data, data, P()) + (table_p,) * len(tables),
        out_specs=(table_p,) * len(tables),
        check_vma=False,
    )(ids, grads, tr, *tables)


def fused_dedup_apply(
    spec: PackedSpec,
    kind: str,
    hyper: dict,
    packed_table,
    slots: dict,
    ids,
    grads,
    *,
    mesh=None,
    interpret: Optional[bool] = None,
    tile: int = DEFAULT_IDS_PER_TILE,
):
    """One-pass sparse optimizer step: ``(ids, grads)`` in,
    ``(new_table, new_slots)`` out, matching
    ``dedup_representatives + scatter_apply``.

    Exactness contract (pinned by tests/test_sparse_kernels.py): the
    kernel replays the scatter path's arithmetic operation-for-
    operation — the same segment-combined gradients (identical bits:
    the dedup prologue IS the scatter path's), the same elementwise
    slot math, and delta-form writes (``old + fl(new - old)``, the
    scatter path's read-modify-write adds).  In exact arithmetic the
    two are identical; in compiled f32 they agree to <= 1 ulp, because
    XLA is free to fuse any multiply-feeding-an-add into an FMA (one
    rounding) on either side of the comparison and no kernel
    formulation can pin which.  Documented tolerance: rtol 3e-7
    (observed diffs: 0 on most elements, 1 ulp on the rest — e.g.
    adagrad's ``acc + g*g`` inside the update chain).

    The sort-free segment-combine (two O(n) scatters; the SAME
    scatter-max mechanism the scatter path uses, so the summed
    gradients carry identical bits) runs as an XLA prologue; the
    gather/update/scatter trips it used to feed — 2-4 packed lookups
    plus 3-4 expand_updates scatters, each an ``[n, 128]`` HBM
    intermediate — collapse into one kernel that round-trips only the
    touched rows' 512 B storage rows through VMEM.

    `mesh`: a multi-device mesh routes the whole pass through shard_map
    (module docstring "Sharded dispatch") — same arithmetic per shard,
    identical update on every replica of a table shard.
    """
    if kind == "adam" and "t" not in slots:
        kind = "adam_global"
    if kind not in _KIND_SLOTS:
        raise ValueError(f"unknown sparse optimizer kind {kind!r}")
    interpret = _use_interpret() if interpret is None else interpret
    slot_names = _KIND_SLOTS[kind]
    new_slots = dict(slots)

    if kind == "adam_global":
        # Global bias correction: one shared apply counter, incremented
        # unconditionally per apply (the reference Go Adam's contract).
        # Replicated scalar — updated OUTSIDE any shard_map.
        t_global = slots["t_global"] + 1.0
        new_slots["t_global"] = t_global
        tr = jnp.reshape(t_global.astype(jnp.float32), (1, 1))
    else:
        tr = jnp.zeros((1, 1), jnp.float32)  # per-row tr reads in-kernel

    tables = (packed_table,) + tuple(slots[name] for name in slot_names)
    if dispatch_route(mesh) == "shard_map":
        outs = _sharded_dedup_apply(
            spec, kind, hyper, tables, ids, grads, tr, mesh, interpret,
            tile,
        )
    else:
        outs = _dedup_apply_core(
            spec, kind, hyper, tables, ids, grads, tr, interpret, tile
        )
    new_table = outs[0]
    for name, arr in zip(slot_names, outs[1:]):
        new_slots[name] = arr
    return new_table, new_slots


# ----------------------------------------------------------------------
# fused lookup -> FM interaction (DeepFM's combined 1+dim table)
# ----------------------------------------------------------------------


def _fm_kernel(blocks_ref, lane0_ref, bet_ref, valid_ref, table_ref,
               acts_ref, first_ref, sumv_ref, sumsq_ref, rows, sem,
               *, batch_tile, fields, dim):
    """Grid step over `batch_tile` examples x `fields` ids: DMA each
    field's storage row once, add the perturbation capture, mask
    validity, and accumulate the first-order sum + FM partial sums in
    VMEM registers while the activations stream to their output block —
    the FM term never re-reads [batch, fields, dim] from HBM."""
    g = pl.program_id(0)

    def fetch(pos, slot):
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(blocks_ref[pos], 1), :],
            rows.at[slot],
            sem.at[slot],
        )

    def example(b, _):
        base = (g * batch_tile + b) * fields
        fetch(base, 0).start()

        def field(f, carry):
            first, sv, ss = carry
            slot = jax.lax.rem(f, 2)

            @pl.when(f + 1 < fields)
            def _prefetch():
                fetch(base + f + 1, 1 - slot).start()

            fetch(base + f, slot).wait()
            sel = jax.lax.dynamic_slice(
                rows[slot, 0, :], (lane0_ref[base + f],), (dim,)
            )
            a = (sel + bet_ref[b, f, :]) * valid_ref[b, f]
            acts_ref[b, f, :] = a
            v = a[1:]
            return first + a[0], sv + v, ss + v * v

        first, sv, ss = jax.lax.fori_loop(
            0,
            fields,
            field,
            (
                jnp.zeros((), acts_ref.dtype),
                jnp.zeros((dim - 1,), acts_ref.dtype),
                jnp.zeros((dim - 1,), acts_ref.dtype),
            ),
        )
        first_ref[b, 0] = first
        sumv_ref[b, :] = sv
        sumsq_ref[b, :] = ss
        return 0

    jax.lax.fori_loop(0, batch_tile, example, 0)


def _fm_impl(spec, interpret, batch_tile, packed, bet, ids, valid):
    batch, fields = ids.shape
    dim = spec.dim
    batch_tile = min(batch_tile, max(batch, 1))
    b_pad = _pad_to_tile(max(batch, 1), batch_tile)
    pad = b_pad - batch
    ids_pad = jnp.pad(ids.astype(jnp.int32), ((0, pad), (0, 0)))
    blocks, lane0 = _block_and_lane(spec, ids_pad.reshape((-1,)))
    bet_pad = jnp.pad(
        bet.astype(packed.dtype), ((0, pad), (0, 0), (0, 0))
    )
    valid_pad = jnp.pad(
        valid.astype(packed.dtype), ((0, pad), (0, 0))
    )
    acts, first, sumv, sumsq = pl.pallas_call(
        functools.partial(
            _fm_kernel, batch_tile=batch_tile, fields=fields, dim=dim
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b_pad // batch_tile,),
            in_specs=[
                pl.BlockSpec(
                    (batch_tile, fields, dim), lambda g, *_: (g, 0, 0)
                ),
                pl.BlockSpec((batch_tile, fields), lambda g, *_: (g, 0)),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=[
                pl.BlockSpec(
                    (batch_tile, fields, dim), lambda g, *_: (g, 0, 0)
                ),
                pl.BlockSpec((batch_tile, 1), lambda g, *_: (g, 0)),
                pl.BlockSpec((batch_tile, dim - 1), lambda g, *_: (g, 0)),
                pl.BlockSpec((batch_tile, dim - 1), lambda g, *_: (g, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, 1, spec.block_width), packed.dtype),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((b_pad, fields, dim), packed.dtype),
            jax.ShapeDtypeStruct((b_pad, 1), packed.dtype),
            jax.ShapeDtypeStruct((b_pad, dim - 1), packed.dtype),
            jax.ShapeDtypeStruct((b_pad, dim - 1), packed.dtype),
        ],
        interpret=interpret,
    )(blocks, lane0, bet_pad, valid_pad, packed)
    return (
        acts[:batch],
        first[:batch, 0],
        sumv[:batch],
        sumsq[:batch],
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _fm_diff(spec, interpret, batch_tile, packed, bet, ids, valid):
    return _fm_impl(spec, interpret, batch_tile, packed, bet, ids, valid)


def _fm_fwd(spec, interpret, batch_tile, packed, bet, ids, valid):
    out = _fm_impl(spec, interpret, batch_tile, packed, bet, ids, valid)
    acts = out[0]
    return out, (acts, ids, valid)


def _fm_bwd_math(spec, res, cots):
    """Shared backward of both FM routes (single-device and sharded):
    pure XLA ops over the GLOBAL residuals, so the custom_vjp never
    transposes through shard_map."""
    acts, ids, valid = res
    dtype = acts.dtype
    d_acts, d_first, d_sumv, d_sumsq = cots
    # acts = (row + bet) * valid; first/sum_v/sum_sq are plain sums of
    # acts components, so every cotangent folds into one per-field
    # activation cotangent (the 2*v term is the sum-of-squares
    # jacobian) — the same quantity the unfused layer's perturbation
    # capture would receive.
    d_field = d_acts.astype(dtype)
    d_field = d_field.at[..., 0].add(d_first.astype(dtype)[:, None])
    d_field = d_field.at[..., 1:].add(
        d_sumv.astype(dtype)[:, None, :]
        + 2.0 * acts[..., 1:] * d_sumsq.astype(dtype)[:, None, :]
    )
    d_field = d_field * valid.astype(dtype)[..., None]
    d_packed = pk.grad_accumulate(
        spec,
        jnp.zeros(spec.packed_shape, dtype),
        ids.reshape((-1,)),
        d_field.reshape((-1, spec.dim)),
    )
    return (
        d_packed,
        d_field,
        jnp.zeros(ids.shape, jax.dtypes.float0),
        jnp.zeros(valid.shape, jax.dtypes.float0),
    )


def _fm_bwd(spec, interpret, batch_tile, res, cots):
    return _fm_bwd_math(spec, res, cots)


_fm_diff.defvjp(_fm_fwd, _fm_bwd)


def _sharded_fm_impl(spec, interpret, batch_tile, mesh, packed, bet, ids,
                     valid):
    """shard_map route of the FM kernel: table blocks P(model), batch
    P(data), per-shard validity = valid AND owned-here, psum combine.
    Field sums are additive with one owning shard per field, so the
    combined quadruple matches single-device up to the documented
    reduction-order tolerance (psum adds exact zeros for acts)."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel import compile as pc
    from elasticdl_tpu.parallel.mesh import MODEL_AXIS

    axis = table_partition_axis(spec.num_blocks, mesh)
    local_spec = _shard_local_spec(spec, mesh) if axis else spec
    data = _batch_spec(ids.shape[0], mesh)

    def body(packed_l, bet_l, ids_l, valid_l):
        if axis is None:
            return _fm_impl(
                spec, interpret, batch_tile, packed_l, bet_l, ids_l,
                valid_l,
            )
        rows_local = local_spec.vocab_padded
        start = jax.lax.axis_index(MODEL_AXIS) * rows_local
        local = ids_l.astype(jnp.int32) - start
        inshard = valid_l & (local >= 0) & (local < rows_local)
        out = _fm_impl(
            local_spec, interpret, batch_tile, packed_l, bet_l,
            jnp.where(inshard, local, 0), inshard,
        )
        return tuple(jax.lax.psum(x, MODEL_AXIS) for x in out)

    return pc.shard_map_call(
        body, mesh,
        in_specs=(P(axis), data, data, data),
        out_specs=(data, data, data, data),
        check_vma=False,
    )(packed, bet, ids, valid)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _sharded_fm_diff(spec, interpret, batch_tile, mesh, packed, bet, ids,
                     valid):
    return _sharded_fm_impl(
        spec, interpret, batch_tile, mesh, packed, bet, ids, valid
    )


def _sharded_fm_fwd(spec, interpret, batch_tile, mesh, packed, bet, ids,
                    valid):
    out = _sharded_fm_impl(
        spec, interpret, batch_tile, mesh, packed, bet, ids, valid
    )
    return out, (out[0], ids, valid)


def _sharded_fm_bwd(spec, interpret, batch_tile, mesh, res, cots):
    return _fm_bwd_math(spec, res, cots)


_sharded_fm_diff.defvjp(_sharded_fm_fwd, _sharded_fm_bwd)


def fused_lookup_fm(
    spec: PackedSpec,
    packed,
    bet,
    ids,
    valid,
    *,
    mesh=None,
    interpret: Optional[bool] = None,
    batch_tile: int = DEFAULT_FM_BATCH_TILE,
):
    """Combined ``1+dim`` lookup + FM partial sums in one pass.

    ids [batch, fields] int32 (already offset), valid [batch, fields]
    bool, bet [batch, fields, dim] — the perturbation-capture variable
    (zeros at runtime; its cotangent IS the sparse gradient).  Returns
    ``(acts [batch, fields, dim], first [batch], sum_v [batch, dim-1],
    sum_sq [batch, dim-1])`` where acts lane 0 is the first-order
    weight and lanes 1..dim the FM field vector:

        second_order = 0.5 * sum_d(sum_v^2 - sum_sq)

    composable with dense-field sums (DeepFM adds its 13 projected
    numeric fields before squaring).  The activations are emitted for
    the deep tower; the FM sums accumulate in VMEM during the same
    pass, so the ``[batch, fields, dim]`` tensor is written once and
    never re-read on the FM path.  ``fm_stats_xla`` is the reference
    twin (same contract, XLA ops) — the two agree on acts bit-for-bit
    and on the sums to reduction-order tolerance (documented in
    docs/design.md).
    """
    if spec.dim < 2:
        raise ValueError(
            f"fused_lookup_fm needs a combined table of dim >= 2 "
            f"(1 linear lane + FM lanes), got dim={spec.dim}"
        )
    interpret = _use_interpret() if interpret is None else interpret
    if dispatch_route(mesh) == "shard_map":
        return _sharded_fm_diff(
            spec, interpret, batch_tile, mesh, packed, bet, ids, valid
        )
    return _fm_diff(spec, interpret, batch_tile, packed, bet, ids, valid)


def fm_stats_xla(acts):
    """The XLA twin of fused_lookup_fm's statistics: acts
    [batch, fields, dim] -> (first, sum_v, sum_sq).  Same contract;
    jnp reductions instead of the kernel's sequential field loop (the
    documented reduction-order tolerance between the two)."""
    first = jnp.sum(acts[..., 0], axis=-1)
    v = acts[..., 1:]
    return first, jnp.sum(v, axis=1), jnp.sum(v * v, axis=1)
