"""Pallas TPU flash-attention kernel (forward + backward).

The hot op of the long-context path on a single chip (the cross-chip
ring in parallel/ring_attention.py currently uses its own XLA block
math — fusing this kernel into the ring steps would require exposing
the m/l accumulators and is future work).  A hand-scheduled Pallas
kernel instead of the XLA-fused blockwise einsum
because attention's online-softmax recurrence is exactly the pattern XLA
can't restructure itself: the [T, T] score slab must never exist, scores
must stay resident in VMEM between the two matmuls, and the causal
upper-triangle must be SKIPPED (not computed-then-masked).  Standard
flash-attention scheme (grid over (batch, heads, q-blocks), K/V streamed
block-wise from VMEM, f32 running max/denominator carried in registers),
with the standard two-kernel backward (dq pass over q-blocks, dk/dv pass
over k-blocks, recomputing probabilities from the saved logsumexp).

`flash_attention` is a drop-in for `blockwise_attention`'s self-attention
case: [B, T, H, D] in, [B, T, H, D] out, differentiable via custom_vjp.
Off-TPU (tests, CPU meshes) the kernels run in Pallas interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
# Measured on the v5e (B4 T2048 H8 D128, causal): fwd 256->4.18ms,
# 512->3.88ms, 1024/512->4.01ms; XLA blockwise 5.88ms.  512 wins.
DEFAULT_BLOCK = 512


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pos(block: int, index, dim: int):
    """Global positions of a block's rows as a 2-D iota (TPU needs >=2D)."""
    return index * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, 1) if dim == 0 else (1, block), dim
    )


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, D]
    t_k = k_ref.shape[2]
    n_k = t_k // block_k
    if causal:
        # K blocks strictly above the diagonal are never touched.
        n_k = jnp.minimum(n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
    q_pos = _pos(block_q, qi, 0)  # [block_q, 1]

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            k_pos = _pos(block_k, j, 1)  # [1, block_k]
            s = jnp.where(k_pos > q_pos, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * correction + pv

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)  # [block_q, 1]


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    grid = (b, h, t // block_q)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, t, d), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, t, d), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ----------------------------------------------------------------------
# backward: dq pass (grid over q-blocks), dk/dv pass (grid over k-blocks)
# ----------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, block_q, block_k):
    """Grid (B, H, n_q, n_k), k innermost: each step adds one KV block's
    contribution to this q-block's gradient.  The f32 accumulator lives
    in VMEM scratch across the inner grid steps (TPU grids are
    sequential), and only the final [block_q, D] block is written out —
    no full-[T, D] buffer ever sits in VMEM, so T scales past the
    scoped-VMEM ceiling the fori-loop-over-full-KV formulation hit."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    # Fully-masked (q-block entirely before k-block): skip the matmuls.
    live = (qi + 1) * block_q > kj * block_k if causal else True

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # [block_q, 1]
        delta = delta_ref[0, 0]
        k_blk = k_ref[0, 0].astype(jnp.float32)  # [block_k, D]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q * scale, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            mask = _pos(block_k, kj, 1) > _pos(block_q, qi, 0)
            s = jnp.where(mask, NEG_INF, s)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dq_acc[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == n_k - 1)
    def _emit():
        dq_ref[0, 0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                scale, causal, block_q, block_k):
    """Grid (B, H, n_k, n_q), q innermost; mirror of _dq_kernel with the
    roles swapped — see its docstring for the accumulation scheme."""
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (qi + 1) * block_q > kj * block_k if causal else True

    @pl.when(live)
    def _accumulate():
        k_blk = k_ref[0, 0].astype(jnp.float32)  # [block_k, D]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0][None, :]  # [1, block_q]
        delta = delta_ref[0, 0][:, 0][None, :]
        # Transposed layout: s_t [block_k, block_q].
        s_t = jax.lax.dot_general(
            k_blk, q * scale, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            mask = _pos(block_k, kj, 0) > _pos(block_q, qi, 1)
            s_t = jnp.where(mask, NEG_INF, s_t)
        p_t = jnp.exp(s_t - lse)  # [block_k, block_q]
        dv_acc[...] += jax.lax.dot_general(
            p_t, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v_blk, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta)
        dk_acc[...] += jax.lax.dot_general(
            ds_t, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _emit():
        dk_ref[0, 0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    b, h, t, d = q.shape
    do = g.astype(jnp.float32)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term.
    delta = jnp.sum(
        do * out.astype(jnp.float32), axis=-1, keepdims=True
    )  # [B, H, T, 1]

    from jax.experimental.pallas import tpu as pltpu

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(b, h, t // block_q, t // block_k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(b, h, t // block_k, t // block_q),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b, h, j, i: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b, h, j, i: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------------------
# public entry
# ----------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


# The forward kernel keeps each (batch, head)'s FULL [T, D] K and V
# resident in VMEM (the backward kernels stream block-wise).  Cap the K+V
# footprint auto-mode will accept: 8 MiB leaves room for the Q/output
# blocks and the f32 accumulators inside the default ~16 MiB scoped-VMEM
# budget (T=16384 x D=64 sits exactly at the cap and is measured to work;
# beyond it, lowering fails unless the operator raises
# LIBTPU_INIT_ARGS=--xla_tpu_scoped_vmem_limit_kib).  Explicit
# flash_attention() calls are not bounded — only supports(), which
# attention_impl='auto' consults before preferring the kernel over
# blockwise_attention.
_KV_VMEM_BYTES_MAX = 8 * 1024 * 1024


def supports(t: int, d: int, block: int = DEFAULT_BLOCK) -> bool:
    """Whether the kernel handles this (seq_len, head_dim) shape within
    the default VMEM budget (see _KV_VMEM_BYTES_MAX)."""
    block = min(block, t)
    return (
        t % block == 0
        and t % 8 == 0
        and d % 8 == 0
        and 2 * t * d * 4 <= _KV_VMEM_BYTES_MAX
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
    interpret: Optional[bool] = None,
):
    """Self-attention [B, T, H, D] -> [B, T, H, D], Pallas kernels.

    T must be a multiple of block_q/block_k (`supports()` checks); use
    parallel.ring_attention.blockwise_attention for irregular shapes.
    """
    b, t, h, d = q.shape
    # Short sequences: shrink blocks to the sequence (T itself is a valid
    # single block when sublane-aligned).
    block_q, block_k = min(block_q, t), min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(
            f"seq len {t} must be a multiple of block sizes "
            f"({block_q}, {block_k})"
        )
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    interpret = _use_interpret() if interpret is None else interpret
    # Kernels run in [B, H, T, D].
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _flash(qt, kt, vt, scale, causal, block_q, block_k, interpret)
    return out.transpose(0, 2, 1, 3)
