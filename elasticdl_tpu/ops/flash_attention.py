"""Pallas TPU flash-attention kernel (forward + backward).

The hot op of the long-context path — single-chip (`flash_attention`)
AND per-ring-step inside the cross-chip ring (`flash_ring_step_carry` /
`flash_ring_step_bwd`, consumed by parallel/ring_attention's pallas
impl; measured 1.25x-3x over the ring's XLA block math as T_local grows
2048 -> 16384, BASELINE.md).  A hand-scheduled Pallas
kernel instead of the XLA-fused blockwise einsum
because attention's online-softmax recurrence is exactly the pattern XLA
can't restructure itself: the [T, T] score slab must never exist, scores
must stay resident in VMEM between the two matmuls, and the causal
upper-triangle must be SKIPPED (not computed-then-masked).  Standard
flash-attention scheme (grid over (batch, heads, q-blocks), K/V streamed
block-wise from VMEM, f32 running max/denominator carried in registers),
with the standard two-kernel backward (dq pass over q-blocks, dk/dv pass
over k-blocks, recomputing probabilities from the saved logsumexp).

`flash_attention` is a drop-in for `blockwise_attention`'s self-attention
case: [B, T, H, D] in, [B, T, H, D] out, differentiable via custom_vjp.
Off-TPU (tests, CPU meshes) the kernels run in Pallas interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30
# Measured on the v5e (B4 T2048 H8 D128, causal): fwd 256->4.18ms,
# 512->3.88ms, 1024/512->4.01ms; XLA blockwise 5.88ms.  512 wins.
DEFAULT_BLOCK = 512


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pos(block: int, index, dim: int):
    """Global positions of a block's rows as a 2-D iota (TPU needs >=2D)."""
    return index * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, 1) if dim == 0 else (1, block), dim
    )


# ----------------------------------------------------------------------
# forward
# ----------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_q, block_k):
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, D]
    t_k = k_ref.shape[2]
    n_k = t_k // block_k
    if causal:
        # K blocks strictly above the diagonal are never touched.
        n_k = jnp.minimum(n_k, ((qi + 1) * block_q + block_k - 1) // block_k)
    q_pos = _pos(block_q, qi, 0)  # [block_q, 1]

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        if causal:
            k_pos = _pos(block_k, j, 1)  # [1, block_k]
            s = jnp.where(k_pos > q_pos, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * correction + pv

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)  # [block_q, 1]


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    grid = (b, h, t // block_q)
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, t, d), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, t, d), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ----------------------------------------------------------------------
# backward: dq pass (grid over q-blocks), dk/dv pass (grid over k-blocks)
# ----------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               dq_acc, *, scale, causal, block_q, block_k):
    """Grid (B, H, n_q, n_k), k innermost: each step adds one KV block's
    contribution to this q-block's gradient.  The f32 accumulator lives
    in VMEM scratch across the inner grid steps (TPU grids are
    sequential), and only the final [block_q, D] block is written out —
    no full-[T, D] buffer ever sits in VMEM, so T scales past the
    scoped-VMEM ceiling the fori-loop-over-full-KV formulation hit."""
    qi = pl.program_id(2)
    kj = pl.program_id(3)
    n_k = pl.num_programs(3)

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    # Fully-masked (q-block entirely before k-block): skip the matmuls.
    live = (qi + 1) * block_q > kj * block_k if causal else True

    @pl.when(live)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0]  # [block_q, 1]
        delta = delta_ref[0, 0]
        k_blk = k_ref[0, 0].astype(jnp.float32)  # [block_k, D]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q * scale, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            mask = _pos(block_k, kj, 1) > _pos(block_q, qi, 0)
            s = jnp.where(mask, NEG_INF, s)
        p = jnp.exp(s - lse)  # [block_q, block_k]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        dq_acc[...] += jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kj == n_k - 1)
    def _emit():
        dq_ref[0, 0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                scale, causal, block_q, block_k):
    """Grid (B, H, n_k, n_q), q innermost; mirror of _dq_kernel with the
    roles swapped — see its docstring for the accumulation scheme."""
    kj = pl.program_id(2)
    qi = pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    live = (qi + 1) * block_q > kj * block_k if causal else True

    @pl.when(live)
    def _accumulate():
        k_blk = k_ref[0, 0].astype(jnp.float32)  # [block_k, D]
        v_blk = v_ref[0, 0].astype(jnp.float32)
        q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, 0][None, :]  # [1, block_q]
        delta = delta_ref[0, 0][:, 0][None, :]
        # Transposed layout: s_t [block_k, block_q].
        s_t = jax.lax.dot_general(
            k_blk, q * scale, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            mask = _pos(block_k, kj, 0) > _pos(block_q, qi, 1)
            s_t = jnp.where(mask, NEG_INF, s_t)
        p_t = jnp.exp(s_t - lse)  # [block_k, block_q]
        dv_acc[...] += jax.lax.dot_general(
            p_t, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v_blk, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta)
        dk_acc[...] += jax.lax.dot_general(
            ds_t, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _emit():
        dk_ref[0, 0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    b, h, t, d = q.shape
    do = g.astype(jnp.float32)
    # delta_i = rowsum(dO_i * O_i) — the softmax-jacobian diagonal term.
    delta = jnp.sum(
        do * out.astype(jnp.float32), axis=-1, keepdims=True
    )  # [B, H, T, 1]

    from jax.experimental.pallas import tpu as pltpu

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(b, h, t // block_q, t // block_k),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, i, j: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q, d), lambda b, h, i, j: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, t, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, g, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k,
        ),
        grid=(b, h, t // block_k, t // block_q),
        in_specs=[
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b, h, j, i: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, d), lambda b, h, j, i: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k, d), lambda b, h, j, i: (b, h, j, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, t, d), k.dtype),
            jax.ShapeDtypeStruct((b, h, t, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, g, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------------------
# ring-step kernels (parallel/ring_attention.py's per-step engine)
#
# Same math as the kernels above with two ring-specific twists:
# - causal masking uses EXPLICIT position arrays (q_pos [Tq], k_pos [Tk])
#   instead of block-index arithmetic — a rotating KV block's global
#   positions depend on its source shard, and the zigzag layout's are not
#   even affine;
# - the forward RETURNS (out_i, lse_i) unnormalized-combinable partials:
#   the ring recombines steps exactly via
#       lse = logaddexp(lse_c, lse_i)
#       acc = acc * exp(lse_c - lse) + out_i * exp(lse_i - lse)
#   so no m/l state ever crosses the kernel boundary (a fully-masked
#   step's lse_i = NEG_INF contributes exp(-inf) = 0 automatically).
# The backward reuses the flash identity P = exp(S - lse_final): each
# ring step's (dq contribution, dk/dv of the rotating block) needs only
# the FINAL lse + delta, so the step kernels stay stateless.
# ----------------------------------------------------------------------


def _fwd_ring_carry_kernel(q_ref, k_ref, v_ref, acc_ref, lsec_ref,
                           qpos_ref, kpos_ref, acc_out, lse_out, *,
                           scale, causal, block_k):
    """_fwd_ring_kernel with the lse-space COMBINE fused in: takes the
    running (acc, lse) carry as inputs (aliased to the outputs — no
    fresh HBM buffers) and emits the updated carry directly, saving the
    separate [B,H,T,D]-sized combine pass per ring step."""
    q = q_ref[0, 0].astype(jnp.float32) * scale  # [block_q, D]
    t_k = k_ref.shape[2]
    n_k = t_k // block_k
    block_q = q.shape[0]

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = qpos_ref[0, 0]  # [block_q, 1]
            k_pos = kpos_ref[0, 0, :, pl.ds(j * block_k, block_k)]
            s = jnp.where(k_pos > q_pos, NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
        p = jnp.exp(s - safe_m)
        if causal:
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        correction = jnp.where(
            m <= NEG_INF / 2, 0.0, jnp.exp(m - safe_m)
        )
        l_new = l * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * correction + pv

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_i = acc / l_safe
    lse_i = jnp.where(
        l == 0.0, NEG_INF,
        jnp.where(m <= NEG_INF / 2, 0.0, m) + jnp.log(l_safe),
    )
    # Fused lse-space combine with the incoming carry.
    lse_c = lsec_ref[0, 0]  # [block_q, 1]
    lse_new = jnp.logaddexp(lse_c, lse_i)
    safe = jnp.where(lse_new <= NEG_INF / 2, 0.0, lse_new)
    alpha = jnp.exp(jnp.where(lse_c <= NEG_INF / 2, NEG_INF, lse_c) - safe)
    beta = jnp.exp(jnp.where(lse_i <= NEG_INF / 2, NEG_INF, lse_i) - safe)
    acc_out[0, 0] = acc_ref[0, 0] * alpha + o_i * beta
    lse_out[0, 0] = lse_new


def flash_ring_step_carry(q, k_blk, v_blk, acc, lse, q_pos, k_pos, *,
                          causal, scale, block_q=DEFAULT_BLOCK,
                          block_k=DEFAULT_BLOCK, interpret=None):
    """One ring step, combine fused: (acc [B,H,Tq,D] f32, lse [B,H,Tq,1]
    f32) in -> updated (acc, lse) out, buffers aliased in place."""
    b, h, tq, d = q.shape
    tk = k_blk.shape[2]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tq % block_q or tk % block_k:
        raise ValueError(
            f"ring-step kernel needs block-divisible shard lengths; got "
            f"Tq={tq} (block {block_q}), Tk={tk} (block {block_k})"
        )
    interpret = _use_interpret() if interpret is None else interpret
    qp = _match_vma(q_pos.astype(jnp.int32).reshape(1, 1, tq, 1), q)
    kp = _match_vma(k_pos.astype(jnp.int32).reshape(1, 1, 1, tk), q)
    acc_new, lse_new = pl.pallas_call(
        functools.partial(
            _fwd_ring_carry_kernel, scale=scale, causal=causal,
            block_k=block_k,
        ),
        grid=(b, h, tq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, tk, d), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, tk, d), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (0, 0, i, 0)),
            pl.BlockSpec((1, 1, 1, tk), lambda b, h, i: (0, 0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            _out_struct((b, h, tq, d), jnp.float32, q),
            _out_struct((b, h, tq, 1), jnp.float32, q),
        ],
        input_output_aliases={3: 0, 4: 1},
        interpret=interpret,
    )(q, k_blk, v_blk, acc, lse, qp, kp)
    return acc_new, lse_new


def _vma_of(x):
    """`x`'s varying-mesh-axes type, or None on jax versions without
    `jax.typeof` (pre-typed-vma releases: there is no vma type system
    to satisfy, and the ring runs shard_map with the check disabled via
    the check_rep fallback — see parallel/compile.shard_map_call)."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return None
    return getattr(typeof(x), "vma", None)


def _out_struct(shape, dtype, like):
    """ShapeDtypeStruct that inherits `like`'s varying-mesh-axes type —
    required when these kernels run inside shard_map (the ring), where
    check_vma demands explicit output vma."""
    vma = _vma_of(like)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _match_vma(x, like):
    """Give `x` at least `like`'s varying-mesh-axes type (shard_map's
    check_vma requires all kernel operands to agree; position arrays are
    only `model`-varying while q varies over the data axis too)."""
    want = _vma_of(like)
    if not want:
        return x
    have = _vma_of(x) or frozenset()
    missing = tuple(set(want) - set(have))
    return jax.lax.pvary(x, missing) if missing else x


def _dq_ring_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    qpos_ref, kpos_ref, dq_ref, *,
                    scale, causal, block_k):
    """dq contribution of ONE ring step's KV block (grid over q-blocks,
    inner fori over this block's KV): P = exp(S - lse_final)."""
    q = q_ref[0, 0].astype(jnp.float32)  # [block_q, D]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]  # [block_q, 1]
    delta = delta_ref[0, 0]
    t_k = k_ref.shape[2]
    n_k = t_k // block_k

    def body(j, acc):
        k_blk = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32
        )
        v_blk = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(
            jnp.float32
        )
        s = jax.lax.dot_general(
            q * scale, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if causal:
            q_pos = qpos_ref[0, 0]  # [block_q, 1]
            k_pos = kpos_ref[0, 0, :, pl.ds(j * block_k, block_k)]  # [1, block_k]
            s = jnp.where(k_pos > q_pos, NEG_INF, s)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        return acc + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    acc0 = jnp.zeros((q.shape[0], q.shape[1]), jnp.float32)
    dq_ref[0, 0] = (
        jax.lax.fori_loop(0, n_k, body, acc0) * scale
    ).astype(dq_ref.dtype)


def _dkv_ring_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     qpos_ref, kpos_ref, dk_ref, dv_ref,
                     *, scale, causal, block_q):
    """dk/dv of ONE ring step's KV block vs the local q shard (grid over
    k-blocks, inner fori over q-blocks)."""
    k_blk = k_ref[0, 0].astype(jnp.float32)  # [block_kk, D]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    t_q = q_ref.shape[2]
    n_q = t_q // block_q

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(
            jnp.float32
        )
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), 0][None, :]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q), 0][None, :]
        s_t = jax.lax.dot_general(
            k_blk, q * scale, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_kk, block_q]
        if causal:
            q_pos = qpos_ref[0, 0, :, pl.ds(i * block_q, block_q)]  # [1, block_q]
            k_pos = kpos_ref[0, 0]  # [block_kk, 1]
            s_t = jnp.where(k_pos > q_pos, NEG_INF, s_t)
        p_t = jnp.exp(s_t - lse)
        dv = dv + jax.lax.dot_general(
            p_t, do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp_t = jax.lax.dot_general(
            v_blk, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds_t = p_t * (dp_t - delta)
        dk = dk + jax.lax.dot_general(
            ds_t, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    z = jnp.zeros((k_blk.shape[0], k_blk.shape[1]), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_q, body, (z, z))
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def flash_ring_step_bwd(q, k_blk, v_blk, do, lse, delta, q_pos, k_pos, *,
                        causal, scale, block_q=DEFAULT_BLOCK,
                        block_k=DEFAULT_BLOCK, interpret=None):
    """One ring step's backward: (dq contribution [B,H,Tq,D] f32,
    dk [B,H,Tk,D] f32, dv [B,H,Tk,D] f32).  `lse`/`delta` are the FINAL
    ring-combined stats [B,H,Tq,1]."""
    b, h, tq, d = q.shape
    tk = k_blk.shape[2]
    block_q_ = min(block_q, tq)
    block_k_ = min(block_k, tk)
    if tq % block_q_ or tk % block_k_:
        raise ValueError(
            f"ring-step backward needs block-divisible shard lengths; got "
            f"Tq={tq} (block {block_q_}), Tk={tk} (block {block_k_})"
        )
    interpret = _use_interpret() if interpret is None else interpret
    qp = _match_vma(q_pos.astype(jnp.int32).reshape(1, 1, tq, 1), q)
    kp_lanes = _match_vma(k_pos.astype(jnp.int32).reshape(1, 1, 1, tk), q)
    qp_lanes = _match_vma(q_pos.astype(jnp.int32).reshape(1, 1, 1, tq), q)
    kp = _match_vma(k_pos.astype(jnp.int32).reshape(1, 1, tk, 1), q)

    from jax.experimental.pallas import tpu as pltpu

    dq = pl.pallas_call(
        functools.partial(
            _dq_ring_kernel, scale=scale, causal=causal, block_k=block_k_
        ),
        grid=(b, h, tq // block_q_),
        in_specs=[
            pl.BlockSpec((1, 1, block_q_, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, tk, d), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, tk, d), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_q_, d), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q_, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q_, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q_, 1), lambda b, h, i: (0, 0, i, 0)),
            pl.BlockSpec((1, 1, 1, tk), lambda b, h, i: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, block_q_, d), lambda b, h, i: (b, h, i, 0)
        ),
        out_shape=_out_struct((b, h, tq, d), jnp.float32, q),
        interpret=interpret,
    )(q, k_blk, v_blk, do, lse, delta, qp, kp_lanes)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_ring_kernel, scale=scale, causal=causal, block_q=block_q_
        ),
        grid=(b, h, tk // block_k_),
        in_specs=[
            pl.BlockSpec((1, 1, tq, d), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_k_, d), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_k_, d), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, tq, d), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, tq, 1), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, tq, 1), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, 1, tq), lambda b, h, j: (0, 0, 0, 0)),
            pl.BlockSpec((1, 1, block_k_, 1), lambda b, h, j: (0, 0, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec(
                (1, 1, block_k_, d), lambda b, h, j: (b, h, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_k_, d), lambda b, h, j: (b, h, j, 0)
            ),
        ],
        out_shape=[
            _out_struct((b, h, tk, d), jnp.float32, k_blk),
            _out_struct((b, h, tk, d), jnp.float32, k_blk),
        ],
        interpret=interpret,
    )(q, k_blk, v_blk, do, lse, delta, qp_lanes, kp)
    return dq, dk, dv


# ----------------------------------------------------------------------
# public entry
# ----------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    out, _ = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    out, lse = _fwd(q, k, v, scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


# The forward kernel keeps each (batch, head)'s FULL [T, D] K and V
# resident in VMEM (the backward kernels stream block-wise).  Cap the K+V
# footprint auto-mode will accept: half the scoped-VMEM budget leaves
# room for the Q/output blocks and the f32 accumulators (at the default
# ~16 MiB budget that is 8 MiB: T=16384 x D=64 sits exactly at the cap
# and is measured to work).  Explicit flash_attention() calls are not
# bounded — only supports(), which attention_impl='auto' consults before
# preferring the kernel over blockwise_attention.
_DEFAULT_SCOPED_VMEM_KIB = 16 * 1024


def _configured_scoped_vmem_kib() -> int:
    """The scoped-VMEM budget the operator actually configured: parse
    --xla_tpu_scoped_vmem_limit_kib out of LIBTPU_INIT_ARGS (round 4 —
    previously auto mode capped at the FLAG-FREE bound even when the
    operator had raised the limit, so the kernel silently fell back at
    exactly the long-T shapes the flag exists for)."""
    import os
    import re

    match = re.search(
        r"--xla_tpu_scoped_vmem_limit_kib=(\d+)",
        os.environ.get("LIBTPU_INIT_ARGS", ""),
    )
    return int(match.group(1)) if match else _DEFAULT_SCOPED_VMEM_KIB


def _kv_vmem_bytes_max() -> int:
    return _configured_scoped_vmem_kib() * 1024 // 2


def shape_aligned(t: int, d: int, block: int = DEFAULT_BLOCK) -> bool:
    """The pure shape-capability half of `supports()` (block/sublane
    alignment), independent of the VMEM budget."""
    block = min(block, t)
    return t % block == 0 and t % 8 == 0 and d % 8 == 0


def supports(t: int, d: int, block: int = DEFAULT_BLOCK) -> bool:
    """Whether the kernel handles this (seq_len, head_dim) shape within
    the CONFIGURED scoped-VMEM budget (LIBTPU_INIT_ARGS-aware)."""
    return shape_aligned(t, d, block) and not kv_vmem_exceeded(t, d)


def kv_vmem_exceeded(t: int, d: int) -> bool:
    """True when the KV block exceeds the configured scoped-VMEM budget —
    the operator can raise it with
    LIBTPU_INIT_ARGS=--xla_tpu_scoped_vmem_limit_kib (65536 is the
    measured-working value at T=16384; BASELINE.md ring table), and auto
    mode then accepts the shape without forcing attn_impl.  Auto-mode
    callers warn when this is the SOLE blocker (check `shape_aligned`
    too — advising the flag on a misaligned shape would point at a
    kernel that still cannot run)."""
    return 2 * t * d * 4 > _kv_vmem_bytes_max()


# The measured-working scoped-VMEM limit for the long-T kernel shapes
# (T=16384 D=64 and up; BASELINE.md ring table).
VMEM_FLAG_ADVICE = "LIBTPU_INIT_ARGS=--xla_tpu_scoped_vmem_limit_kib=65536"


def warn_if_vmem_is_sole_blocker(logger_name: str, t: int, d: int) -> bool:
    """Auto-mode honesty contract: when the Pallas kernel is rejected
    ONLY by the VMEM budget (shape alignment fine), log the flag that
    unlocks it — a silent fallback at long T leaves up to ~3x on the
    table exactly where the kernel matters most.  Returns whether the
    warning fired (trace-time, so once per compile)."""
    if not (shape_aligned(t, d) and kv_vmem_exceeded(t, d)):
        return False
    from elasticdl_tpu.common.log_utils import get_logger

    get_logger(logger_name).warning(
        "attn impl=auto fell back to the XLA block engine at T=%d D=%d: "
        "the KV block (%.1f MiB f32) exceeds the flag-free scoped-VMEM "
        "budget. Set %s and force attn_impl=pallas to unlock the Pallas "
        "kernel (up to ~3x at long T; BASELINE.md ring-attention table).",
        t, d, 2 * t * d * 4 / 2**20, VMEM_FLAG_ADVICE,
    )
    return True


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK,
    block_k: int = DEFAULT_BLOCK,
    interpret: Optional[bool] = None,
):
    """Self-attention [B, T, H, D] -> [B, T, H, D], Pallas kernels.

    T must be a multiple of block_q/block_k (`supports()` checks); use
    parallel.ring_attention.blockwise_attention for irregular shapes.
    """
    b, t, h, d = q.shape
    # Short sequences: shrink blocks to the sequence (T itself is a valid
    # single block when sublane-aligned).
    block_q, block_k = min(block_q, t), min(block_k, t)
    if t % block_q or t % block_k:
        raise ValueError(
            f"seq len {t} must be a multiple of block sizes "
            f"({block_q}, {block_k})"
        )
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    interpret = _use_interpret() if interpret is None else interpret
    # Kernels run in [B, H, T, D].
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _flash(qt, kt, vt, scale, causal, block_q, block_k, interpret)
    return out.transpose(0, 2, 1, 3)
