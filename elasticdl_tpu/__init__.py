"""elasticdl_tpu: a TPU-native elastic distributed training framework.

A ground-up JAX/XLA rebuild of the capabilities of ElasticDL (reference:
zerocurve/elasticdl): a master that owns dynamic data sharding and elastic
worker membership, fault-tolerant data-parallel training (the reference's
FTlib/Horovod NCCL AllReduce re-emitted as XLA `psum` collectives over ICI),
and parameter-server-style embedding tables re-emitted as HBM-sharded arrays
with `all_to_all` lookup compiled into the jit step.
"""

__version__ = "0.1.0"
