"""Served-vs-dropped availability ledger for the serving plane.

The goodput ledger (obs/goodput.py) answers "what fraction of job
wall-clock trained"; this is its serving twin: "what fraction of
admitted traffic was served" plus where request wall time went.  Every
finished request books:

- an outcome (``served`` / ``dropped`` / ``shed`` / ``error`` — a
  bounded enum, so it may ride a metric label), and
- its per-phase seconds over the request-phase taxonomy
  (obs/stepstats.REQUEST_PHASES: queue / batch / execute / respond).

Exported via the obs registry (scraped by the replica's exporter and
rendered by ``obs.top --serving``):

- ``elasticdl_serving_availability_ratio`` — served / (served+dropped+
  shed+error) over the process lifetime;
- ``elasticdl_serving_requests_total{outcome=}`` and
  ``elasticdl_serving_rows_total{outcome=}``;
- ``elasticdl_serving_phase_seconds_total{phase=}``;
- ``elasticdl_serving_latency_p50_ms`` / ``..._p99_ms`` — host-side
  percentiles over a sliding window (a Prometheus histogram's fixed
  buckets are too coarse for a p99 SLO readout);
- ``elasticdl_serving_qps`` — served requests/s over the same window.

Thread-safety: requests finish on the batcher thread while the exporter
scrapes from its own; the lock covers the sliding window and counters.
Gauge callbacks read under the ledger lock — percentile math over a
bounded deque, never a device sync, so a scrape cannot stall serving.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from elasticdl_tpu import obs
from elasticdl_tpu.analysis.runtime import make_lock
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.obs.stepstats import REQUEST_PHASES

logger = get_logger("serving.ledger")

#: Bounded outcome enum (metric-label safe).
OUTCOMES = ("served", "dropped", "shed", "error")

#: Sliding latency/QPS window (requests).
WINDOW = 2048


class AvailabilityLedger:
    """Process-wide accounting of request outcomes and phase time."""

    def __init__(self, clock=time.monotonic, registry=None):
        # `registry` defaults to the process obs registry (the replica
        # path).  Tests and the SLO-plane e2e inject private registries
        # so several replica-shaped ledgers can coexist in one process.
        if registry is None:
            registry = obs.registry()
        self._clock = clock
        self._lock = make_lock("AvailabilityLedger._lock")
        self._outcomes = {o: 0 for o in OUTCOMES}  # guarded-by: _lock
        self._rows = {o: 0 for o in OUTCOMES}  # guarded-by: _lock
        self._phase_s = {p: 0.0 for p in REQUEST_PHASES}  # guarded-by: _lock
        # (finish_ts, latency_s) of recent served requests.
        self._window: deque = deque(maxlen=WINDOW)  # guarded-by: _lock
        self._m_requests = registry.counter(
            "elasticdl_serving_requests_total",
            "Finished predict requests, by outcome",
            labelnames=("outcome",),
        )
        self._m_rows = registry.counter(
            "elasticdl_serving_rows_total",
            "Finished predict rows, by outcome",
            labelnames=("outcome",),
        )
        self._m_phase = registry.counter(
            "elasticdl_serving_phase_seconds_total",
            "Cumulative request wall time, by request phase",
            labelnames=("phase",),
        )
        registry.gauge(
            "elasticdl_serving_availability_ratio",
            "served / all finished requests (1.0 = nothing dropped)",
        ).set_function(self.availability_ratio)
        registry.gauge(
            "elasticdl_serving_latency_p50_ms",
            "p50 served-request latency over the sliding window",
        ).set_function(lambda: self.latency_percentile_ms(50.0))
        registry.gauge(
            "elasticdl_serving_latency_p99_ms",
            "p99 served-request latency over the sliding window",
        ).set_function(lambda: self.latency_percentile_ms(99.0))
        registry.gauge(
            "elasticdl_serving_qps",
            "Served requests/s over the sliding window",
        ).set_function(self.qps)

    # -- recording ------------------------------------------------------

    def record_request(
        self, phases: Dict[str, float], outcome: str, rows: int = 1
    ):
        """Book one finished request (the MicroBatcher's on_request
        callback signature).  Unknown phases are ignored; unknown
        outcomes count as 'error' rather than raising on the batcher
        thread."""
        if outcome not in self._outcomes:
            outcome = "error"
        latency = sum(
            float(phases.get(p, 0.0)) for p in REQUEST_PHASES
        )
        now = self._clock()
        with self._lock:
            self._outcomes[outcome] += 1
            self._rows[outcome] += int(rows)
            for phase in REQUEST_PHASES:
                if phase in phases:
                    self._phase_s[phase] += float(phases[phase])
            if outcome == "served":
                self._window.append((now, latency))
        self._m_requests.inc(outcome=outcome)
        self._m_rows.inc(int(rows), outcome=outcome)
        for phase in REQUEST_PHASES:
            if phase in phases:
                self._m_phase.inc(float(phases[phase]), phase=phase)

    def record_shed(self, rows: int = 1):
        """Book an admission-rejected request (the MicroBatcher's
        on_shed callback; the batcher itself journals the
        ``request_shed`` event)."""
        self.record_request({}, "shed", rows)

    # -- readouts -------------------------------------------------------

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._outcomes)

    def phase_seconds(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._phase_s)

    def availability_ratio(self) -> float:
        with self._lock:
            total = sum(self._outcomes.values())
            if total == 0:
                return 1.0
            return self._outcomes["served"] / total

    def latency_percentile_ms(self, pct: float) -> float:
        with self._lock:
            latencies = sorted(latency for _, latency in self._window)
        if not latencies:
            return 0.0
        rank = min(
            len(latencies) - 1, int(round(pct / 100.0 * (len(latencies) - 1)))
        )
        return latencies[rank] * 1e3

    def qps(self, horizon_s: float = 10.0) -> float:
        now = self._clock()
        with self._lock:
            recent = [ts for ts, _ in self._window if now - ts <= horizon_s]
        if not recent:
            return 0.0
        span = max(1e-6, now - min(recent))
        return len(recent) / span

    def snapshot(self) -> dict:
        """One bounded dict for the replica's serving_telemetry journal
        event (per-replica detail rides the journal, never labels)."""
        with self._lock:
            counts = dict(self._outcomes)
            phases = {p: round(s, 6) for p, s in self._phase_s.items()}
        return {
            "counts": counts,
            "phase_seconds": phases,
            "availability_ratio": round(self.availability_ratio(), 6),
            "p50_ms": round(self.latency_percentile_ms(50.0), 3),
            "p99_ms": round(self.latency_percentile_ms(99.0), 3),
            "qps": round(self.qps(), 2),
        }


_ledger: Optional[AvailabilityLedger] = None


def ledger() -> AvailabilityLedger:
    """The process singleton (one serving replica per process)."""
    global _ledger
    if _ledger is None:
        _ledger = AvailabilityLedger()
    return _ledger


def reset_ledger():
    """Test hook: drop the singleton so a fresh registry snapshot can
    re-register its gauges."""
    global _ledger
    _ledger = None
