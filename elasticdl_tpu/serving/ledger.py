"""Served-vs-dropped availability ledger for the serving plane.

The goodput ledger (obs/goodput.py) answers "what fraction of job
wall-clock trained"; this is its serving twin: "what fraction of
admitted traffic was served" plus where request wall time went.  Every
finished request books:

- an outcome (``served`` / ``dropped`` / ``shed`` / ``error`` — a
  bounded enum, so it may ride a metric label), and
- its per-phase seconds over the request-phase taxonomy
  (obs/stepstats.REQUEST_PHASES: queue / batch / execute / respond).

The ledger also hosts the request-level tracing sensor
(``ExemplarSampler`` below): tracing every request at production QPS is
unaffordable, so completed request records land in a bounded in-memory
ring and only SAMPLED requests journal — a deterministic 1-in-N head
sample (the steady-state waterfall supply), every request whose latency
crosses the SLO-p99-tied tail threshold, and every non-served outcome
(shed/dropped/error are always evidence).  Journaling cost is therefore
O(sampled), never O(requests), and the decision is pure in the request
stream (a counter, a threshold — no wall-clock randomness).  Trace ids
are unbounded identifiers: they ride the journal (``request_trace``
events, span records) and never metric labels (cardinality rule).

Exported via the obs registry (scraped by the replica's exporter and
rendered by ``obs.top --serving``):

- ``elasticdl_serving_availability_ratio`` — served / (served+dropped+
  shed+error) over the process lifetime;
- ``elasticdl_serving_requests_total{outcome=}`` and
  ``elasticdl_serving_rows_total{outcome=}``;
- ``elasticdl_serving_phase_seconds_total{phase=}``;
- ``elasticdl_serving_latency_p50_ms`` / ``..._p99_ms`` — host-side
  percentiles over a sliding window (a Prometheus histogram's fixed
  buckets are too coarse for a p99 SLO readout);
- ``elasticdl_serving_qps`` — served requests/s over the same window.

Thread-safety: requests finish on the batcher thread while the exporter
scrapes from its own; the lock covers the sliding window and counters.
Gauge callbacks read under the ledger lock — percentile math over a
bounded deque, never a device sync, so a scrape cannot stall serving.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, Optional

from elasticdl_tpu import obs
from elasticdl_tpu.analysis.runtime import make_lock
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.obs.stepstats import REQUEST_PHASES

logger = get_logger("serving.ledger")

#: Bounded outcome enum (metric-label safe).
OUTCOMES = ("served", "dropped", "shed", "error")

#: Sliding latency/QPS window (requests).
WINDOW = 2048


class AvailabilityLedger:
    """Process-wide accounting of request outcomes and phase time."""

    def __init__(self, clock=time.monotonic, registry=None):
        # `registry` defaults to the process obs registry (the replica
        # path).  Tests and the SLO-plane e2e inject private registries
        # so several replica-shaped ledgers can coexist in one process.
        if registry is None:
            registry = obs.registry()
        self._clock = clock
        self._lock = make_lock("AvailabilityLedger._lock")
        self._outcomes = {o: 0 for o in OUTCOMES}  # guarded-by: _lock
        self._rows = {o: 0 for o in OUTCOMES}  # guarded-by: _lock
        self._phase_s = {p: 0.0 for p in REQUEST_PHASES}  # guarded-by: _lock
        # (finish_ts, latency_s, phases) of recent served requests; the
        # per-request phases dict feeds the per-phase p99 split that
        # obs.top --serving renders as QU/BA/EX/RE columns.
        self._window: deque = deque(maxlen=WINDOW)  # guarded-by: _lock
        self._m_requests = registry.counter(
            "elasticdl_serving_requests_total",
            "Finished predict requests, by outcome",
            labelnames=("outcome",),
        )
        self._m_rows = registry.counter(
            "elasticdl_serving_rows_total",
            "Finished predict rows, by outcome",
            labelnames=("outcome",),
        )
        self._m_phase = registry.counter(
            "elasticdl_serving_phase_seconds_total",
            "Cumulative request wall time, by request phase",
            labelnames=("phase",),
        )
        registry.gauge(
            "elasticdl_serving_availability_ratio",
            "served / all finished requests (1.0 = nothing dropped)",
        ).set_function(self.availability_ratio)
        registry.gauge(
            "elasticdl_serving_latency_p50_ms",
            "p50 served-request latency over the sliding window",
        ).set_function(lambda: self.latency_percentile_ms(50.0))
        registry.gauge(
            "elasticdl_serving_latency_p99_ms",
            "p99 served-request latency over the sliding window",
        ).set_function(lambda: self.latency_percentile_ms(99.0))
        registry.gauge(
            "elasticdl_serving_qps",
            "Served requests/s over the sliding window",
        ).set_function(self.qps)

    # -- recording ------------------------------------------------------

    def record_request(
        self, phases: Dict[str, float], outcome: str, rows: int = 1
    ):
        """Book one finished request (the MicroBatcher's on_request
        callback signature).  Unknown phases are ignored; unknown
        outcomes count as 'error' rather than raising on the batcher
        thread."""
        if outcome not in self._outcomes:
            outcome = "error"
        latency = sum(
            float(phases.get(p, 0.0)) for p in REQUEST_PHASES
        )
        now = self._clock()
        with self._lock:
            self._outcomes[outcome] += 1
            self._rows[outcome] += int(rows)
            for phase in REQUEST_PHASES:
                if phase in phases:
                    self._phase_s[phase] += float(phases[phase])
            if outcome == "served":
                self._window.append((now, latency, dict(phases)))
        self._m_requests.inc(outcome=outcome)
        self._m_rows.inc(int(rows), outcome=outcome)
        for phase in REQUEST_PHASES:
            if phase in phases:
                self._m_phase.inc(float(phases[phase]), phase=phase)

    def record_shed(self, rows: int = 1):
        """Book an admission-rejected request (the MicroBatcher's
        on_shed callback; the batcher itself journals the
        ``request_shed`` event)."""
        self.record_request({}, "shed", rows)

    # -- readouts -------------------------------------------------------

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._outcomes)

    def phase_seconds(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._phase_s)

    def availability_ratio(self) -> float:
        with self._lock:
            total = sum(self._outcomes.values())
            if total == 0:
                return 1.0
            return self._outcomes["served"] / total

    def latency_percentile_ms(self, pct: float) -> float:
        with self._lock:
            latencies = sorted(latency for _, latency, _ in self._window)
        if not latencies:
            return 0.0
        rank = min(
            len(latencies) - 1, int(round(pct / 100.0 * (len(latencies) - 1)))
        )
        return latencies[rank] * 1e3

    def phase_percentile_ms(self, pct: float) -> Dict[str, float]:
        """Per-phase percentile over the served sliding window — the
        p99 phase-attribution split ("p99 is mostly queue")."""
        with self._lock:
            samples = [phases for _, _, phases in self._window]
        split: Dict[str, float] = {}
        for phase in REQUEST_PHASES:
            values = sorted(float(p.get(phase, 0.0)) for p in samples)
            if not values:
                split[phase] = 0.0
                continue
            rank = min(
                len(values) - 1,
                int(round(pct / 100.0 * (len(values) - 1))),
            )
            split[phase] = values[rank] * 1e3
        return split

    def qps(self, horizon_s: float = 10.0) -> float:
        now = self._clock()
        with self._lock:
            recent = [ts for ts, _, _ in self._window if now - ts <= horizon_s]
        if not recent:
            return 0.0
        span = max(1e-6, now - min(recent))
        return len(recent) / span

    def snapshot(self) -> dict:
        """One bounded dict for the replica's serving_telemetry journal
        event (per-replica detail rides the journal, never labels)."""
        with self._lock:
            counts = dict(self._outcomes)
            phases = {p: round(s, 6) for p, s in self._phase_s.items()}
        return {
            "counts": counts,
            "phase_seconds": phases,
            "availability_ratio": round(self.availability_ratio(), 6),
            "p50_ms": round(self.latency_percentile_ms(50.0), 3),
            "p99_ms": round(self.latency_percentile_ms(99.0), 3),
            "phase_p99_ms": {
                p: round(v, 3)
                for p, v in self.phase_percentile_ms(99.0).items()
            },
            "qps": round(self.qps(), 2),
        }


# ---------------------------------------------------------------------------
# Tail-based exemplar sampler (request-level tracing sensor)
# ---------------------------------------------------------------------------


class ExemplarSampler:
    """Bounded ring of completed request records with a three-policy
    sampling decision (docs/observability.md "Request tracing &
    exemplars"):

    - **head**: deterministic 1-in-``head_every`` of traced requests
      (a counter, not a coin flip — the same request stream always
      journals the same head set);
    - **tail**: latency above ``tail_threshold_ms`` (wired to the
      replica's ``--slo_p99_ms`` target, so "slow" means "slow against
      the SLO the fleet pages on");
    - **outcome**: every shed / dropped / error request (failures are
      always evidence).

    A sampled request journals one ``request_trace`` event plus its
    deferred span set (``rpc.predict`` -> ``serve.queue`` ->
    ``serve.execute`` -> ``serve.respond``), and the shared
    ``serve.batch`` span its bucket rode — journaled ONCE per batch, on
    the first sampled member (a bounded id ring dedupes).  Unsampled
    requests write nothing: journaling stays O(sampled).

    Requests without a trace id (clients that sent no
    ``TRACE_METADATA_KEY``) are invisible to the sampler — there is no
    id to journal, and skipping them keeps the head counter pure in the
    *traced* stream.

    All clocks are read by the CALLER (frontend/batcher host code) and
    arrive as wall stamps inside the prepared span payloads; this class
    only counts, compares, and journals — nothing here runs inside
    traced/jitted code (trace-purity rule).
    """

    def __init__(
        self,
        head_every: int = 128,
        tail_threshold_ms: float = 0.0,
        capacity: int = 64,
        replica_id: Optional[int] = None,
        journal=None,
        quality=None,
        quality_clock=time.monotonic,
    ):
        self._head_every = max(0, int(head_every))
        self._tail_threshold_ms = float(tail_threshold_ms)
        self._capacity = max(1, int(capacity))
        self._replica_id = replica_id
        self._journal = journal
        # Model-quality label-join ledger (obs/quality.py): sampled
        # SERVED requests' predictions enter its pending-join ring, so
        # the quality plane rides the same O(sampled) decision this
        # sampler already makes — no second sampling policy to tune.
        self._quality = quality
        self._quality_clock = quality_clock
        self._lock = make_lock("ExemplarSampler._lock")
        self._count = 0  # traced requests seen, guarded-by: _lock
        self._sampled = 0  # guarded-by: _lock
        self._ring: deque = deque(maxlen=self._capacity)  # guarded-by: _lock
        # Shared-batch-span dedup: ids already journaled (bounded LRU;
        # no deque maxlen — eviction must also clean the set).
        self._batch_ids: deque = deque()  # guarded-by: _lock
        self._batch_id_set = set()  # guarded-by: _lock

    def _journal_ref(self):
        if self._journal is not None:
            return self._journal
        return obs.journal()

    # -- the sampling decision ------------------------------------------

    def observe(
        self,
        trace_id: str,
        phases: Dict[str, float],
        outcome: str,
        rows: int = 1,
        latency_s: Optional[float] = None,
        spans=None,
        batch: Optional[dict] = None,
        generation: Optional[int] = None,
        bucket: Optional[int] = None,
        predictions=None,
        features=None,
    ) -> str:
        """Feed one completed request; returns the sampling reason
        (``head`` / ``tail`` / ``outcome``) or ``""`` when unsampled.

        ``spans`` is the deferred span payload list (record_span kwargs,
        prepared by the frontend with wall stamps already read);
        ``batch`` is the shared serve.batch payload (must carry
        ``span_id``).  Both journal only on a sample.

        ``predictions``/``features`` (host arrays, already synced by
        the caller) feed the quality ledger's pending-join ring when
        this sampler has one — only for sampled SERVED requests, so
        label joins score exactly the population the trace plane
        exemplifies."""
        if not trace_id:
            return ""
        if latency_s is None:
            latency_s = sum(
                float(phases.get(p, 0.0)) for p in REQUEST_PHASES
            )
        latency_ms = float(latency_s) * 1e3
        with self._lock:
            self._count += 1
            if outcome != "served":
                sampled_by = "outcome"
            elif (
                self._tail_threshold_ms > 0
                and latency_ms > self._tail_threshold_ms
            ):
                sampled_by = "tail"
            elif (
                self._head_every > 0
                and (self._count - 1) % self._head_every == 0
            ):
                sampled_by = "head"
            else:
                return ""
            self._sampled += 1
            batch_is_new = False
            if batch is not None and batch.get("span_id"):
                batch_id = batch["span_id"]
                if batch_id not in self._batch_id_set:
                    batch_is_new = True
                    self._batch_ids.append(batch_id)
                    self._batch_id_set.add(batch_id)
                    while len(self._batch_ids) > self._capacity:
                        self._batch_id_set.discard(self._batch_ids.popleft())
            phases_ms = {
                p: round(float(phases[p]) * 1e3, 3)
                for p in REQUEST_PHASES
                if p in phases
            }
            dominant = (
                max(phases_ms, key=phases_ms.get) if phases_ms else ""
            )
            record = {
                "trace_id": trace_id,
                "outcome": outcome,
                "sampled_by": sampled_by,
                "latency_ms": round(latency_ms, 3),
                "phases": phases_ms,
                "dominant_phase": dominant,
                "rows": int(rows),
            }
            self._ring.append(dict(record))
        # Journal OUTSIDE the lock: the journal has its own lock and a
        # slow disk must not serialize the gRPC handler threads here.
        extra = {}
        if self._replica_id is not None:
            extra["replica_id"] = self._replica_id
        if generation is not None:
            extra["generation"] = generation
        if bucket is not None:
            extra["bucket"] = bucket
        self._journal_ref().record("request_trace", **record, **extra)
        from elasticdl_tpu.obs import tracing

        if batch_is_new:
            tracing.record_span(**batch)
        for payload in spans or ():
            tracing.record_span(**payload)
        if (
            self._quality is not None
            and outcome == "served"
            and predictions is not None
        ):
            try:
                self._quality.note_prediction(
                    trace_id, predictions, now=self._quality_clock(),
                    features=features,
                )
            except Exception:
                logger.exception("quality note_prediction failed (ignored)")
        return sampled_by

    # -- readouts -------------------------------------------------------

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {"observed": self._count, "sampled": self._sampled}

    def exemplars(self) -> list:
        """Ring contents, oldest first (bounded copies)."""
        with self._lock:
            return [dict(r) for r in self._ring]

    def slowest(self) -> Optional[dict]:
        """The slowest request currently in the ring (the obs.top
        footer / serving_telemetry ``exemplar`` field)."""
        with self._lock:
            if not self._ring:
                return None
            return dict(max(self._ring, key=lambda r: r["latency_ms"]))

    def trace_ids(self, k: int = 4) -> list:
        """Up to ``k`` exemplar trace ids, slowest first — the
        offending-request evidence a fired latency ``slo_alert``
        attaches."""
        with self._lock:
            ranked = sorted(
                self._ring, key=lambda r: -r["latency_ms"]
            )
        return [r["trace_id"] for r in ranked[: max(0, int(k))]]


_ledger: Optional[AvailabilityLedger] = None


def ledger() -> AvailabilityLedger:
    """The process singleton (one serving replica per process)."""
    global _ledger
    if _ledger is None:
        _ledger = AvailabilityLedger()
    return _ledger


def reset_ledger():
    """Test hook: drop the singleton so a fresh registry snapshot can
    re-register its gauges."""
    global _ledger
    _ledger = None
