"""Continuous serving: keep a replica tracking the published delta chain.

The training side publishes into a *pub dir* (checkpoint/delta.py): full
serving artifacts plus delta links chaining forward from them.  The
`DeltaWatcher` is the serving-side consumer: each poll resolves the
newest good chain (corrupt links are quarantined by `resolve_chain`
itself) and walks the replica forward —

- behind the newest full  -> one hot-swap `reload` to the full,
- then every delta link    -> `apply_delta` (no reload, no recompile),
- a failed/corrupt apply   -> STOP.  The replica keeps serving its
  current generation (runtime.apply_delta already rolled back and
  journaled `model_swap` outcome=rolled_back); the next poll retries,
  and a compaction publish repairs the gap.

That is the degradation ladder's middle rung: *stale-serving* — behind
the stream but answering every request, visible in the freshness lag
metric, never down.

With a **canary gate** (`obs/quality.py`, `--quality_join_window_s` on
the replica), every delta link is shadow-evaluated BEFORE the swap:
`build_delta_generation` constructs the candidate off to the side, the
gate scores live-vs-candidate logloss/AUC on recently joined labeled
batches, and a beyond-threshold regression HELDs the link — candidate
discarded, old generation keeps serving, journaled `quality_gate`
outcome=held, retried next poll (a republished healthy delta at the
same step passes).  Unknown quality (label outage, cold buffer)
resolves by the gate's explicit policy, so a broken label pipe never
wedges the chain silently.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu import obs
from elasticdl_tpu.checkpoint.delta import resolve_chain
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.pipeline import bucket_for, pad_features

logger = get_logger("serving.continuous")


def _parse_steps(path: str) -> Tuple[Optional[int], Optional[int]]:
    """(base_step, step) for a delta dir, (None, step) for a full dir."""
    name = os.path.basename(path.rstrip("/"))
    if name.startswith("full_"):
        return None, int(name[len("full_"):])
    if name.startswith("delta_"):
        base, step = name[len("delta_"):].split("_")[:2]
        return int(base), int(step)
    raise ValueError(f"not a chain artifact: {path}")


class DeltaWatcher:
    """Polls a pub dir and advances one ServingReplica along the chain.

    `poll_once()` is the whole protocol (deterministic, driver-callable
    from tests); `start(interval_s)` runs it on a daemon thread for real
    replicas.  `freshness` (an obs.freshness.FreshnessTracker) is
    optional: when present, every applied generation feeds its
    serving-side event-time frontier.  `gate` (an obs.quality.CanaryGate)
    is optional: when present, every delta link is shadow-evaluated on
    `buckets`-padded replay batches before its swap (see module
    docstring)."""

    def __init__(self, replica, pub_dir: str, freshness=None,
                 gate=None, buckets: Optional[Sequence[int]] = None,
                 origin: str = ""):
        self._replica = replica
        self._pub_dir = pub_dir
        self._freshness = freshness
        self._gate = gate
        self._buckets = tuple(buckets) if buckets else None
        self._origin = origin
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _shadow_fn(self, generation):
        """Predictions for a raw replay batch against an explicit
        generation: pad to a warmed bucket (no stray retrace during the
        gate), shadow-execute off the serving pointer, slice the pad
        rows back off."""
        def predict(features):
            rows = next(iter(features.values())).shape[0]
            bucket = (bucket_for(rows, self._buckets)
                      if self._buckets else rows)
            outputs = self._replica.shadow_execute(
                pad_features(features, bucket), generation=generation)
            return np.asarray(outputs).reshape(bucket, -1)[:rows].ravel()
        return predict

    def _gate_delta(self, delta_dir: str, delta_step: int):
        """Build-evaluate-commit for one delta link under the gate.
        Returns the verdict dict (outcome passed|held|forced); raises
        on build failure, same as the ungated `apply_delta` path."""
        candidate = self._replica.build_delta_generation(delta_dir)
        live = self._replica.generation
        verdict = self._gate.evaluate(
            self._shadow_fn(live), self._shadow_fn(candidate))
        extra = {
            key: verdict[key]
            for key in ("reason", "rows", "quality", "baseline_logloss",
                        "candidate_logloss", "baseline_auc",
                        "candidate_auc")
            if verdict.get(key) is not None
        }
        obs.journal().record(
            "quality_gate",
            outcome=verdict["outcome"],
            step=int(delta_step),
            delta_dir=delta_dir,
            origin=self._origin,
            **extra,
        )
        if verdict["outcome"] == "held":
            logger.warning(
                "Canary gate HELD delta %s (step %d): %s",
                delta_dir, delta_step, verdict.get("reason", ""),
            )
            return verdict
        self._replica.commit_generation(candidate, delta_dir)
        return verdict

    def poll_once(self) -> dict:
        """One resolve-and-advance pass.  Never raises: a failed link
        leaves the replica stale-serving and is retried next poll.

        The summary is a structured outcome, not just counters:
        ``outcome`` is ``applied`` (any forward progress),
        ``held`` (the canary gate stopped a link), ``rolled_back`` (a
        link's apply failed and rolled back), ``error`` (the chain
        resolve itself failed), or ``noop``; ``reason`` carries the
        offending path / gate reason so supervisors and tests assert
        the gate path without tailing the journal."""
        summary = {
            "reloaded_full": False,
            "applied_deltas": 0,
            "failed": None,
            "held": None,
            "outcome": "noop",
            "reason": None,
            "step": self._replica.generation.step,
        }
        try:
            base_dir, chain = resolve_chain(self._pub_dir)
        except OSError as exc:
            logger.exception("Chain resolve failed (transient I/O?)")
            summary["outcome"] = "error"
            summary["reason"] = repr(exc)
            return summary
        if base_dir is None:
            return summary
        _none, base_step = _parse_steps(base_dir)
        current = self._replica.generation.step
        if current < base_step:
            # Behind the newest full (cold start, or a quarantine gap a
            # compaction just repaired): one full hot-swap catches up.
            try:
                self._replica.reload(base_dir)
            except Exception as exc:
                summary["failed"] = base_dir
                summary["reason"] = repr(exc)
                return self._resolve_outcome(summary)
            current = self._replica.generation.step
            summary["reloaded_full"] = True
            self._note_freshness()
        for delta_dir in chain:
            delta_base, delta_step = _parse_steps(delta_dir)
            if delta_step <= current:
                continue  # already ahead of this link
            if delta_base != current:
                break  # gap relative to our position; wait for compaction
            try:
                if self._gate is not None:
                    verdict = self._gate_delta(delta_dir, delta_step)
                    if verdict["outcome"] == "held":
                        summary["held"] = delta_dir
                        summary["reason"] = verdict.get("reason")
                        break
                else:
                    self._replica.apply_delta(delta_dir)
            except Exception as exc:
                # Rolled back (journaled by the runtime).  Stale-serving
                # from here; the next poll retries the link.
                summary["failed"] = delta_dir
                summary["reason"] = repr(exc)
                break
            current = delta_step
            summary["applied_deltas"] += 1
            self._note_freshness()
        return self._resolve_outcome(summary)

    def _resolve_outcome(self, summary: dict) -> dict:
        summary["step"] = self._replica.generation.step
        if summary["failed"] is not None:
            summary["outcome"] = "rolled_back"
        elif summary["held"] is not None:
            summary["outcome"] = "held"
        elif summary["reloaded_full"] or summary["applied_deltas"]:
            summary["outcome"] = "applied"
        else:
            summary["outcome"] = "noop"
        return summary

    def _note_freshness(self):
        if self._freshness is not None:
            gen = self._replica.generation
            self._freshness.note_served(gen.gen_id, gen.step, gen.event_time)

    # -- background operation -------------------------------------------

    def start(self, interval_s: float = 2.0) -> "DeltaWatcher":
        def _loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll_once()
                except Exception:
                    logger.exception("Delta watcher poll failed; will retry")

        self._thread = threading.Thread(
            target=_loop, name="delta-watcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
