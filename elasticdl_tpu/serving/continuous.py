"""Continuous serving: keep a replica tracking the published delta chain.

The training side publishes into a *pub dir* (checkpoint/delta.py): full
serving artifacts plus delta links chaining forward from them.  The
`DeltaWatcher` is the serving-side consumer: each poll resolves the
newest good chain (corrupt links are quarantined by `resolve_chain`
itself) and walks the replica forward —

- behind the newest full  -> one hot-swap `reload` to the full,
- then every delta link    -> `apply_delta` (no reload, no recompile),
- a failed/corrupt apply   -> STOP.  The replica keeps serving its
  current generation (runtime.apply_delta already rolled back and
  journaled `model_swap` outcome=rolled_back); the next poll retries,
  and a compaction publish repairs the gap.

That is the degradation ladder's middle rung: *stale-serving* — behind
the stream but answering every request, visible in the freshness lag
metric, never down.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Tuple

from elasticdl_tpu.checkpoint.delta import resolve_chain
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving.continuous")


def _parse_steps(path: str) -> Tuple[Optional[int], Optional[int]]:
    """(base_step, step) for a delta dir, (None, step) for a full dir."""
    name = os.path.basename(path.rstrip("/"))
    if name.startswith("full_"):
        return None, int(name[len("full_"):])
    if name.startswith("delta_"):
        base, step = name[len("delta_"):].split("_")[:2]
        return int(base), int(step)
    raise ValueError(f"not a chain artifact: {path}")


class DeltaWatcher:
    """Polls a pub dir and advances one ServingReplica along the chain.

    `poll_once()` is the whole protocol (deterministic, driver-callable
    from tests); `start(interval_s)` runs it on a daemon thread for real
    replicas.  `freshness` (an obs.freshness.FreshnessTracker) is
    optional: when present, every applied generation feeds its
    serving-side event-time frontier."""

    def __init__(self, replica, pub_dir: str, freshness=None):
        self._replica = replica
        self._pub_dir = pub_dir
        self._freshness = freshness
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> dict:
        """One resolve-and-advance pass.  Never raises: a failed link
        leaves the replica stale-serving and is retried next poll."""
        summary = {
            "reloaded_full": False,
            "applied_deltas": 0,
            "failed": None,
            "step": self._replica.generation.step,
        }
        try:
            base_dir, chain = resolve_chain(self._pub_dir)
        except OSError:
            logger.exception("Chain resolve failed (transient I/O?)")
            return summary
        if base_dir is None:
            return summary
        _none, base_step = _parse_steps(base_dir)
        current = self._replica.generation.step
        if current < base_step:
            # Behind the newest full (cold start, or a quarantine gap a
            # compaction just repaired): one full hot-swap catches up.
            try:
                self._replica.reload(base_dir)
            except Exception:
                summary["failed"] = base_dir
                summary["step"] = self._replica.generation.step
                return summary
            current = self._replica.generation.step
            summary["reloaded_full"] = True
            self._note_freshness()
        for delta_dir in chain:
            delta_base, delta_step = _parse_steps(delta_dir)
            if delta_step <= current:
                continue  # already ahead of this link
            if delta_base != current:
                break  # gap relative to our position; wait for compaction
            try:
                self._replica.apply_delta(delta_dir)
            except Exception:
                # Rolled back (journaled by the runtime).  Stale-serving
                # from here; the next poll retries the link.
                summary["failed"] = delta_dir
                break
            current = delta_step
            summary["applied_deltas"] += 1
            self._note_freshness()
        summary["step"] = self._replica.generation.step
        return summary

    def _note_freshness(self):
        if self._freshness is not None:
            gen = self._replica.generation
            self._freshness.note_served(gen.gen_id, gen.step, gen.event_time)

    # -- background operation -------------------------------------------

    def start(self, interval_s: float = 2.0) -> "DeltaWatcher":
        def _loop():
            while not self._stop.wait(interval_s):
                try:
                    self.poll_once()
                except Exception:
                    logger.exception("Delta watcher poll failed; will retry")

        self._thread = threading.Thread(
            target=_loop, name="delta-watcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
