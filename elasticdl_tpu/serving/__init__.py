from elasticdl_tpu.serving.export import (  # noqa: F401
    ServingModel,
    export_model,
    load_for_serving,
)

# The online runtime (batched inference + hot swap + supervision) lives
# in submodules imported lazily by callers — serving/export.py must stay
# importable without grpc for offline export tooling:
#   serving.runtime    ServingReplica, serving_rules
#   serving.batcher    MicroBatcher, BatcherConfig, QueueFullError
#   serving.ledger     AvailabilityLedger, ledger
#   serving.frontend   ServingFrontend, PredictClient
#   serving.supervisor ServingReplicaManager, start_serving_fleet
