from elasticdl_tpu.serving.export import (  # noqa: F401
    ServingModel,
    export_model,
    load_for_serving,
)
