"""Serving replica runtime: the compiled batched inference step with
hot-swap model generations.

A `ServingReplica` owns the device side of the serving plane:

- **Loading.**  Each model generation is an `export.py` artifact loaded
  with `load_for_serving`, its variables placed on the replica's mesh by
  a serving `RuleTable` (embedding tables block-shard on dim0 when their
  storage rows divide the mesh — HBM capacity, same policy as the PS
  trainer's table placement; everything else replicates).
- **Compiling.**  The inference step is compiled ONCE per generation
  through `CompilePlan` (parallel/compile.py), so its placement is
  declared and journaled (`compile_plan` event, trainer="serving") like
  every training entry point.  The step is the model's eval path
  (`_model_apply(train=False, mutable=False)`) — under
  `--sparse_kernel fused` the Embedding layers route lookups through
  `fused_lookup_fm`'s forward (single-device Pallas or the shard_map
  dispatch when a multi-device dispatch mesh is registered); no backward
  is ever traced.
- **Hot-swap.**  `reload(model_dir)` builds the NEW generation fully
  (load, place, compile) before an atomic pointer swap; dispatches
  already riding the old generation drain on its in-flight counter
  before it is released, so a swap drops zero in-flight requests.  The
  swap is journaled as a schema-registered `model_swap` event.

Trace purity: the compiled step body touches only the model apply —
journaling, locks, and clocks all live on the host side of the
dispatch boundary (`make check-invariants` gates this).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu import obs
from elasticdl_tpu.analysis.runtime import make_lock
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.parallel import compile as pc
from elasticdl_tpu.serving.batcher import pad_features
from elasticdl_tpu.serving.export import ServingModel, load_for_serving

logger = get_logger("serving.runtime")


def serving_rules(mesh, sparse_kernel: str = "xla") -> pc.RuleTable:
    """Placement policy for serving variables as a rule table: dense
    params and batch stats replicate (they are small and every device
    reads them each step); embedding tables — the leaves the Embedding
    layer names ``embedding`` — are the one shape-aware entry:

    - xla engine: storage blocks across the WHOLE mesh when dim0
      divides it (maximum HBM capacity; the partitioner turns the
      lookup gather into collectives), else replicate — a table too
      small to split evenly is by definition tiny.
    - fused engine: blocks over the ``model`` axis only, the layout the
      shard_map'd kernel dispatch declares
      (ops/sparse_embedding.table_partition_axis), so the per-shard
      pallas bodies see exactly their resident blocks.
    """
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.ops import sparse_embedding as ske
    from elasticdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    fused = sparse_kernel == "fused"
    total = int(mesh.devices.size)

    def table_blocks(path, shape):
        if fused:
            axis = ske.table_partition_axis(shape[0], mesh)
            if axis is None:
                return P()
            return P(axis, *([None] * (len(shape) - 1)))
        if shape[0] % total != 0:
            return P()
        return P((DATA_AXIS, MODEL_AXIS), *([None] * (len(shape) - 1)))

    return pc.RuleTable(
        [
            pc.Rule(r"(^|/)embedding$", table_blocks),
            pc.Rule(".*", P()),
        ],
        name="serving-fused" if fused else "serving-xla",
    )


class Generation:
    """One loaded model generation: the artifact, its device-placed
    variables, and the compiled step — plus an in-flight dispatch count
    so hot-swap can drain it before release."""

    def __init__(
        self,
        gen_id: int,
        model_dir: str,
        served: ServingModel,
        variables,
        serve_fn,
        shardings=None,
        event_time: float = 0.0,
    ):
        self.gen_id = gen_id
        self.model_dir = model_dir
        self.served = served
        self.variables = variables
        self.serve_fn = serve_fn
        # Placement tree + event-time frontier: what delta apply needs to
        # re-place patched variables and what the freshness SLO reads.
        self.shardings = shardings
        self.event_time = float(event_time)
        self._lock = make_lock("Generation._lock")
        self._inflight = 0  # guarded-by: _lock
        self._idle = threading.Condition(self._lock)

    @property
    def step(self) -> int:
        return int(self.served.signature.get("step", 0))

    def begin(self):
        with self._lock:
            self._inflight += 1

    def end(self):
        with self._lock:
            self._inflight -= 1
            self._idle.notify_all()

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drain(self, timeout_s: float = 30.0) -> int:
        """Block until in-flight dispatches finish (or timeout); returns
        the count still in flight (0 = fully drained)."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._idle.wait(timeout=remaining)
            return self._inflight


class ServingReplica:
    """The device half of one serving replica process.

    `execute(features, n_valid)` is the MicroBatcher's execute callable:
    it rides the CURRENT generation (acquired under the swap lock, so a
    concurrent `reload` can never free variables out from under a
    dispatch).  `reload(model_dir)` performs the hot swap.
    """

    def __init__(
        self,
        model_dir: str,
        mesh=None,
        sparse_kernel: Optional[str] = None,
        model_zoo: str = "",
        mmap: bool = True,
        drain_timeout_s: float = 30.0,
    ):
        from elasticdl_tpu.ops import sparse_embedding as ske
        from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh

        self._mesh = mesh if mesh is not None else build_mesh(MeshConfig())
        self._kernel = ske.resolve_kernel(sparse_kernel)
        self._model_zoo = model_zoo
        self._mmap = mmap
        self._drain_timeout_s = drain_timeout_s
        if self._kernel == "fused" and int(self._mesh.devices.size) > 1:
            # The Embedding layer consults the process dispatch mesh for
            # its shard_map'd fused route (worker/main.py does the same
            # registration on the training side).
            ske.set_dispatch_mesh(self._mesh)
        self._lock = make_lock("ServingReplica._lock")
        self._next_gen_id = 1  # guarded-by: _lock
        self._generation: Optional[Generation] = None  # guarded-by: _lock
        self._generation = self._load_generation(model_dir)
        logger.info(
            "Serving replica up: generation %d (step %d) from %s, "
            "kernel=%s, %d device(s)",
            self._generation.gen_id,
            self._generation.step,
            model_dir,
            self._kernel,
            int(self._mesh.devices.size),
        )

    # -- loading / compiling --------------------------------------------

    def _load_generation(self, model_dir: str) -> Generation:
        import jax

        served = load_for_serving(
            model_dir, model_zoo=self._model_zoo, mmap=self._mmap
        )
        rules = serving_rules(self._mesh, self._kernel)
        plan = pc.CompilePlan(self._mesh, rules, trainer="serving")
        shardings = plan.state_shardings(served.variables)
        variables = jax.device_put(served.variables, shardings)
        model = served.model

        def _serve_step(variables, features):
            from elasticdl_tpu.worker.trainer import _model_apply

            outputs, _ = _model_apply(
                model, variables, features, train=False, mutable=False
            )
            return outputs

        serve_fn = plan.compile(
            _serve_step,
            name="serve_step",
            in_shardings=(shardings, plan.replicated()),
            out_shardings=plan.replicated(),
        )
        with self._lock:
            gen_id = self._next_gen_id
            self._next_gen_id += 1
        return Generation(
            gen_id,
            model_dir,
            served,
            variables,
            serve_fn,
            shardings=shardings,
            event_time=float(served.signature.get("event_time", 0.0)),
        )

    # -- the dispatch path ----------------------------------------------

    def _acquire(self) -> Generation:
        with self._lock:
            gen = self._generation
            gen.begin()
            return gen

    def execute(self, features: Dict[str, np.ndarray], n_valid: int):
        """Run the compiled step on one (padded) batch — the
        MicroBatcher's execute_fn.  Returns host outputs (the asarray is
        the device sync, outside every lock)."""
        gen = self._acquire()
        try:
            return np.asarray(gen.serve_fn(gen.variables, features))
        finally:
            gen.end()

    def warmup(self, features: Dict[str, np.ndarray], buckets: Sequence[int]):
        """Pre-trace every padded-bucket shape so live traffic never
        waits on a compile (and the RetraceWatcher baseline is clean)."""
        for size in buckets:
            self.execute(pad_features(features, size), n_valid=0)

    # -- hot swap --------------------------------------------------------

    def reload(self, model_dir: str) -> Generation:
        """Atomic generation swap: the new generation is fully built
        (loaded, placed, compiled) BEFORE the pointer moves, then the
        old generation drains its in-flight dispatches — zero in-flight
        requests are dropped by a swap.

        A failed build — corrupt artifact, bad pickle, compile error —
        never touches the generation pointer: the old generation keeps
        serving (stale, ledger-visible, never down) and the rollback is
        journaled as a `model_swap` with ``outcome=rolled_back``."""
        try:
            new_gen = self._load_generation(model_dir)
        except Exception as exc:
            old_gen = self.generation
            obs.journal().record(
                "model_swap",
                kind="full",
                outcome="rolled_back",
                generation=old_gen.gen_id,
                step=old_gen.step,
                old_generation=old_gen.gen_id,
                old_step=old_gen.step,
                model_dir=model_dir,
                reason=repr(exc),
            )
            logger.exception(
                "Reload from %s failed; generation %d (step %d) keeps "
                "serving", model_dir, old_gen.gen_id, old_gen.step,
            )
            raise
        return self._swap(new_gen, model_dir, kind="full")

    def _swap(self, new_gen: Generation, model_dir: str, kind: str) -> Generation:
        with self._lock:
            old_gen = self._generation
            self._generation = new_gen
        inflight_at_swap = old_gen.inflight()
        leftover = old_gen.drain(self._drain_timeout_s)
        if leftover:
            logger.warning(
                "Generation %d still has %d dispatch(es) in flight after "
                "%.1fs drain", old_gen.gen_id, leftover, self._drain_timeout_s
            )
        obs.journal().record(
            "model_swap",
            kind=kind,
            outcome="applied",
            generation=new_gen.gen_id,
            step=new_gen.step,
            old_generation=old_gen.gen_id,
            old_step=old_gen.step,
            model_dir=model_dir,
            drained_inflight=inflight_at_swap,
            undrained=leftover,
            event_time=new_gen.event_time,
        )
        logger.info(
            "Hot-swapped (%s) generation %d (step %d) -> %d (step %d); "
            "drained %d in-flight dispatch(es)",
            kind, old_gen.gen_id, old_gen.step, new_gen.gen_id,
            new_gen.step, inflight_at_swap,
        )
        return new_gen

    def shadow_execute(self, features: Dict[str, np.ndarray],
                       generation: Optional[Generation] = None):
        """Run the compiled step against an EXPLICIT generation without
        touching the serving pointer — the canary gate's evaluation
        path: a built-but-uncommitted candidate generation answers the
        replay batches while the live one keeps serving.  Defaults to
        the current generation (the gate's baseline side)."""
        gen = generation if generation is not None else self.generation
        gen.begin()
        try:
            return np.asarray(gen.serve_fn(gen.variables, features))
        finally:
            gen.end()

    def build_delta_generation(self, delta_dir: str) -> Generation:
        """Build (but do NOT serve) the generation a delta checkpoint
        would produce: patch the current generation's host tables
        row-wise, re-place them with the generation's own shardings,
        and reuse its compiled step (shapes and placement are unchanged
        by construction — no recompile, no retrace).  The serving
        pointer is untouched; `commit_generation` performs the swap.
        Splitting build from commit is what lets the canary gate
        shadow-evaluate the candidate BEFORE any traffic sees it — a
        held candidate is simply dropped (its gen id burns; ids are
        monotone, not dense).

        Any failure — injected `serving.delta_apply` fault, integrity
        mismatch (the delta is quarantined), a chain gap (base_step !=
        the serving step) — leaves the old generation serving and
        journals a `model_swap` with ``outcome=rolled_back``, then
        re-raises."""
        from elasticdl_tpu.common import faults
        from elasticdl_tpu.checkpoint import delta as deltas
        from elasticdl_tpu.checkpoint.saver import verify_integrity
        import jax

        old_gen = self.generation
        try:
            spec = faults.fire("serving.delta_apply")
            if spec is not None and spec.kind == "error":
                raise RuntimeError(
                    f"FAULT INJECTION: delta apply failed ({spec.arg or 'error'})"
                )
            reason = verify_integrity(delta_dir)
            if reason is not None:
                deltas.quarantine_artifact(delta_dir, reason)
                raise ValueError(f"corrupt delta {delta_dir}: {reason}")
            loaded = deltas.load_delta(delta_dir)
            manifest = loaded["manifest"]
            if int(manifest["base_step"]) != old_gen.step:
                raise ValueError(
                    f"delta {delta_dir} chains from step "
                    f"{manifest['base_step']} but generation "
                    f"{old_gen.gen_id} serves step {old_gen.step}"
                )
            # Patch copies of the current host tables row-wise.
            new_tables = {}
            for key, (rows, vals, _meta) in loaded["tables"].items():
                base = old_gen.served.tables.get(key)
                if base is None:
                    raise ValueError(
                        f"delta {delta_dir} patches unknown table {key!r}"
                    )
                patched = np.array(base)
                if rows.size:
                    patched[rows] = vals
                new_tables[key] = patched
            # Resolve the delta's dense ref-tree against the patched
            # tables (refs are "tables/<i>.npy" paths; index -> key via
            # the manifest).
            key_by_file = {
                f"tables/{meta['index']}.npy": key
                for key, (_r, _v, meta) in loaded["tables"].items()
            }

            def resolve(leaf):
                if isinstance(leaf, dict) and "__table__" in leaf:
                    key = key_by_file.get(leaf["__table__"])
                    if key is None or key not in new_tables:
                        raise ValueError(
                            f"delta dense tree references unknown table "
                            f"file {leaf['__table__']!r}"
                        )
                    return new_tables[key]
                return leaf

            from elasticdl_tpu.serving.export import _map_tree_with_refs

            host_variables = _map_tree_with_refs(loaded["dense"], resolve)
            variables = jax.device_put(host_variables, old_gen.shardings)
            signature = dict(old_gen.served.signature)
            signature["step"] = int(manifest["step"])
            signature["event_time"] = float(manifest.get("event_time", 0.0))
            served = ServingModel(
                old_gen.served.model,
                host_variables,
                signature,
                old_gen.served.base_dir,
                tables=new_tables,
            )
            with self._lock:
                if self._generation is not old_gen:
                    raise RuntimeError(
                        "generation changed under delta apply; re-resolve "
                        "the chain"
                    )
                gen_id = self._next_gen_id
                self._next_gen_id += 1
            new_gen = Generation(
                gen_id,
                delta_dir,
                served,
                variables,
                old_gen.serve_fn,  # same shapes+placement: reuse the compile
                shardings=old_gen.shardings,
                event_time=float(manifest.get("event_time", 0.0)),
            )
        except Exception as exc:
            obs.journal().record(
                "model_swap",
                kind="delta",
                outcome="rolled_back",
                generation=old_gen.gen_id,
                step=old_gen.step,
                old_generation=old_gen.gen_id,
                old_step=old_gen.step,
                model_dir=delta_dir,
                reason=repr(exc),
            )
            logger.exception(
                "Delta apply from %s failed; generation %d (step %d) "
                "keeps serving", delta_dir, old_gen.gen_id, old_gen.step,
            )
            raise
        return new_gen

    def commit_generation(self, new_gen: Generation,
                          model_dir: str) -> Generation:
        """Serve a generation built by `build_delta_generation`: the
        same pointer-swap + drain protocol as `reload` (journaled
        `model_swap` kind="delta" outcome="applied")."""
        return self._swap(new_gen, model_dir, kind="delta")

    def apply_delta(self, delta_dir: str) -> Generation:
        """Build + commit in one step — the ungated path (and the
        original API).  See `build_delta_generation` for the failure
        contract."""
        return self.commit_generation(
            self.build_delta_generation(delta_dir), delta_dir)

    # -- readouts --------------------------------------------------------

    @property
    def mesh(self):
        return self._mesh

    @property
    def sparse_kernel(self) -> str:
        return self._kernel

    @property
    def generation(self) -> Generation:
        """The currently-serving generation.  Besides the hot-swap
        plane, request tracing reads ``generation.gen_id`` per sampled
        request (frontend.py) so exemplars journaled across a swap
        attribute their latency to the model that actually served
        them."""
        with self._lock:
            return self._generation

    def jitted_entrypoints(self) -> Dict[str, Any]:
        """Provider for the step-anatomy RetraceWatcher: the current
        generation's compiled step (a fresh generation starts a fresh
        jit cache, so watch baselines reset at swap)."""
        with self._lock:
            gen = self._generation
        return {"serve_step": gen.serve_fn}

    def stats(self) -> dict:
        """Bounded host-side snapshot for the frontend's Stats RPC and
        the serving_telemetry journal event."""
        with self._lock:
            gen = self._generation
        return {
            "generation": gen.gen_id,
            "step": gen.step,
            "model_dir": gen.model_dir,
            "inflight": gen.inflight(),
            "sparse_kernel": self._kernel,
            "devices": int(self._mesh.devices.size),
            # Event-time frontier of the servable model: the freshness
            # SLO's serving-side input (0.0 for pre-delta artifacts).
            "model_event_time": gen.event_time,
        }
