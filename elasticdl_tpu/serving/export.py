"""Model export for serving.

Parity: elasticdl/python/common/model_handler.py `get_model_to_export` in
the reference — pull trained parameters, materialize the distributed
embedding tables, and write a self-contained servable artifact.  There the
artifact is a TF SavedModel; here it is a directory a fresh process can
load with `load_for_serving` and run inference from, bit-identical to the
trainer's own eval outputs.

Layout:

    <out_dir>/
      signature.json   - model identity (zoo/def/params), array inventory,
                         framework version: everything needed to rebuild
                         the flax module and bind the variables
      variables.pkl    - nested variables tree (dense params + batch
                         stats); embedding-table leaves are replaced by
                         {"__table__": "tables/<i>.npy"} references
      tables/<i>.npy   - one memmap-friendly .npy per embedding table,
                         written in bounded row chunks (a mesh-sharded
                         table is streamed out range-by-range; the
                         exporting host never holds more than chunk_rows
                         of it in memory)

Tables are stored in the model's own packed lane-tiled layout
(parallel/packed.py) so serving applies the exact variables training used;
`ServingModel.logical_tables()` exposes the unpacked [vocab, dim] view for
external consumers (feature stores, ANN indexes).
"""

from __future__ import annotations

import json
import os
import pickle
from types import SimpleNamespace
from typing import Any, Dict, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving.export")

_SIGNATURE = "signature.json"
_VARIABLES = "variables.pkl"
_TABLES_DIR = "tables"
_TABLE_REF = "__table__"


def _set_in_tree(tree: Dict, path, value):
    node = tree
    for part in path[:-1]:
        node = node[part]
    node[path[-1]] = value


def _stream_table_to_npy(array, path: str, chunk_rows: int, write: bool):
    """Write a (possibly mesh-sharded, device-resident) array to .npy in
    row chunks: each chunk fetches only those rows to host, so export
    memory stays bounded regardless of table size.

    COLLECTIVE for process-spanning arrays: a chunk whose rows live on
    another process's devices is not addressable here, so every process
    must call this (the per-chunk gather is a collective); only the
    `write`-ing process (rank 0) touches the file."""
    import jax

    out = None
    if write:
        out = np.lib.format.open_memmap(
            path,
            mode="w+",
            dtype=np.dtype(str(array.dtype)),
            shape=array.shape,
        )
    rows = array.shape[0]
    for lo in range(0, rows, chunk_rows):
        hi = min(rows, lo + chunk_rows)
        chunk = array[lo:hi]
        if getattr(chunk, "is_fully_addressable", True):
            host = np.asarray(chunk)
        else:
            from jax.experimental import multihost_utils

            host = np.asarray(
                multihost_utils.process_allgather(chunk, tiled=True)
            )
        if out is not None:
            out[lo:hi] = host
    if out is not None:
        out.flush()
        del out


def export_model(
    trainer,
    out_dir: str,
    model_zoo: str = "",
    model_def: str = "",
    model_params: str = "",
    chunk_rows: int = 65536,
) -> str:
    """Write the servable artifact for a trained Trainer /
    DataParallelTrainer / ShardedEmbeddingTrainer.

    In a multi-process world EVERY process must call this (PS-mode tables
    are sharded across all processes, so materializing them is a
    collective row-gather); only rank 0 writes files.
    """
    state = trainer.state
    if state is None:
        raise ValueError("Cannot export: model was never initialized")
    import jax

    write = jax.process_index() == 0
    if write:
        os.makedirs(out_dir, exist_ok=True)
    if hasattr(state, "tables"):
        # PS mode: dense params are replicated (tables handled below).
        params = jax.device_get(state.params)
        model_state = jax.device_get(state.model_state)
    else:
        # Gather ONLY what serving needs (params + batch stats) — never
        # the optimizer state, which doubles-or-triples the transfer for
        # nothing.  gather_to_host is a collective for FSDP-sharded
        # leaves and a plain host fetch for replicated/local state.
        from elasticdl_tpu.parallel import sharding as _shd

        host = _shd.gather_to_host(
            {"params": state.params, "model_state": state.model_state}
        )
        params = host["params"]
        model_state = host["model_state"]
    # Unfreeze so table placeholders can be replaced by refs in place.
    params = jax.tree.map(lambda x: x, params)

    tables_meta = []
    if hasattr(state, "tables") and state.tables:
        # PS mode: placeholders sit where the packed tables belong
        # (ps_trainer splits them out at init); stream each device-sharded
        # table to its own file and point the tree at it.
        if write:
            os.makedirs(os.path.join(out_dir, _TABLES_DIR), exist_ok=True)
        for i, (key, array) in enumerate(sorted(state.tables.items())):
            rel = f"{_TABLES_DIR}/{i}.npy"
            _stream_table_to_npy(
                array, os.path.join(out_dir, rel), chunk_rows, write
            )
            spec = trainer._table_specs[key]
            tables_meta.append(
                {
                    "key": key,
                    "file": rel,
                    "vocab_size": spec.vocab_size,
                    "dim": spec.dim,
                    "packed_shape": list(array.shape),
                }
            )
            _set_in_tree(
                params, trainer._table_paths[key], {_TABLE_REF: rel}
            )

    if not write:
        return out_dir

    variables = {"params": params, **model_state}
    with open(os.path.join(out_dir, _VARIABLES), "wb") as f:
        pickle.dump(variables, f)

    import elasticdl_tpu

    signature = {
        "format": "elasticdl_tpu_serving/1",
        "framework_version": elasticdl_tpu.__version__,
        "model_zoo": model_zoo,
        "model_def": model_def,
        "model_params": model_params,
        "tables": tables_meta,
        "step": int(np.asarray(jax.device_get(state.step))),
    }
    with open(os.path.join(out_dir, _SIGNATURE), "w") as f:
        json.dump(signature, f, indent=2)
    logger.info(
        "Exported servable model to %s (step %d, %d embedding table(s))",
        out_dir,
        signature["step"],
        len(tables_meta),
    )
    return out_dir


class ServingModel:
    """A loaded artifact: rebuildable module + bound variables.

    `predict` runs the model's inference path (train=False, no mutable
    collections — the Embedding layers' training-only sows are no-ops), so
    outputs are bit-identical to the trainer's eval for the same inputs.
    """

    def __init__(
        self,
        model,
        variables: Dict,
        signature: dict,
        base_dir: str,
        tables: Optional[Dict[str, np.ndarray]] = None,
    ):
        self._model = model
        self._variables = variables
        self.signature = signature
        self._base_dir = base_dir
        # key -> resolved packed table (host view); what delta apply
        # patches row-wise (serving/runtime.py).  Empty for artifacts
        # loaded by callers that never delta-apply.
        self.tables: Dict[str, np.ndarray] = tables or {}

    def predict(self, features):
        from elasticdl_tpu.worker.trainer import _model_apply

        outputs, _ = _model_apply(
            self._model, self._variables, features, train=False, mutable=False
        )
        return outputs

    @property
    def model(self):
        return self._model

    @property
    def variables(self) -> Dict:
        return self._variables

    @property
    def base_dir(self) -> str:
        return self._base_dir

    def logical_tables(self) -> Dict[str, np.ndarray]:
        """Unpacked [vocab, dim] embedding tables (external-consumer view:
        feature stores, ANN indexes).  Materializes each table on host."""
        from elasticdl_tpu.parallel import packed as pk
        from elasticdl_tpu.parallel.packed import PackedSpec

        out = {}
        for meta in self.signature["tables"]:
            packed = np.load(
                os.path.join(self._base_dir, meta["file"]), mmap_mode="r"
            )
            spec = PackedSpec(meta["vocab_size"], meta["dim"])
            out[meta["key"]] = np.asarray(pk.unpack(spec, packed))
        return out


def load_for_serving(
    out_dir: str,
    model_zoo: str = "",
    mmap: bool = True,
) -> ServingModel:
    """Load an artifact in a fresh process.  `model_zoo` overrides the
    recorded zoo path when the artifact moved between machines."""
    from elasticdl_tpu.common.model_utils import load_model_spec

    with open(os.path.join(out_dir, _SIGNATURE)) as f:
        signature = json.load(f)
    with open(os.path.join(out_dir, _VARIABLES), "rb") as f:
        variables = pickle.load(f)

    key_by_file = {m["file"]: m["key"] for m in signature.get("tables", [])}
    tables: Dict[str, np.ndarray] = {}

    def resolve(leaf):
        if isinstance(leaf, dict) and _TABLE_REF in leaf:
            array = np.load(
                os.path.join(out_dir, leaf[_TABLE_REF]),
                mmap_mode="r" if mmap else None,
            )
            key = key_by_file.get(leaf[_TABLE_REF])
            if key is not None:
                tables[key] = array
            return array
        return leaf

    variables = _map_tree_with_refs(variables, resolve)
    spec_args = SimpleNamespace(
        model_zoo=model_zoo or signature["model_zoo"],
        model_def=signature["model_def"],
        model_params=signature["model_params"],
        loss="loss",
        optimizer="optimizer",
        dataset_fn="dataset_fn",
        eval_metrics_fn="",
        callbacks="",
        custom_data_reader="",
    )
    model = load_model_spec(spec_args).build_model()
    return ServingModel(model, variables, signature, out_dir, tables=tables)


def _map_tree_with_refs(tree, fn):
    """tree.map that treats {"__table__": ...} dicts as leaves."""
    if isinstance(tree, dict):
        if _TABLE_REF in tree:
            return fn(tree)
        return {k: _map_tree_with_refs(v, fn) for k, v in tree.items()}
    return fn(tree)
