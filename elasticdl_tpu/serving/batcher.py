"""Micro-batching front door for the serving plane.

Concurrent predict requests are aggregated into one device dispatch
under a latency budget: a batch closes when it reaches
``max_batch_size`` OR when its oldest request has waited
``max_wait_us``, whichever comes first (the classic serving trade —
throughput wants big batches, tail latency wants prompt ones).

Two properties are load-bearing:

- **Padded-bucket shapes.**  Every dispatched batch is padded up to a
  fixed bucket size (powers of two up to ``max_batch_size``), so the
  compiled inference step sees at most ``len(buckets)`` distinct batch
  shapes — after warmup, no retraces (the PR 8 ``RetraceWatcher`` gates
  this in tests/test_serving.py).  Model rows are independent (the
  DeepFM contract: one logit per row), so padding rows cannot perturb
  real rows; pad outputs are sliced off before requests complete.
- **Explicit load shedding.**  Admission is a bounded queue
  (``queue_limit``); a request arriving at a full queue is REJECTED
  immediately with ``QueueFullError`` — journaled as a schema-registered
  ``request_shed`` event — instead of silently growing an unbounded
  backlog whose every entry would miss its deadline anyway (the
  availability ledger counts it dropped; docs/serving.md).

All clocks are host-side and the batcher thread never holds its lock
across the execute callable (a device dispatch under the admission lock
would couple enqueue latency to device latency).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu import obs
from elasticdl_tpu.analysis.runtime import make_lock
from elasticdl_tpu.common import faults
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.obs import tracing

logger = get_logger("serving.batcher")

_SHED = obs.counter(
    "elasticdl_serving_shed_total",
    "Requests rejected at admission, by cause",
    labelnames=("reason",),
)


class QueueFullError(RuntimeError):
    """Admission queue at capacity: the request was shed, not queued."""


class RequestError(RuntimeError):
    """The batch this request rode failed to execute."""


# Pad-and-stage is the shared staging engine's (data/pipeline.py) —
# training and serving use ONE implementation.  Re-exported here because
# the serving plane's callers (runtime, tests) import them from the
# batcher, the serving-side name for the same step.
from elasticdl_tpu.data.pipeline import (  # noqa: F401  (re-exports)
    bucket_for,
    bucket_sizes,
    pad_and_stage,
    pad_features,
)


@dataclass(eq=False)  # identity semantics: fields hold numpy arrays
class _Pending:
    """One admitted request riding the queue."""

    features: Dict[str, np.ndarray]
    rows: int
    enqueued_at: float
    deadline: Optional[float]  # monotonic; None = no deadline
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[np.ndarray] = None
    error: Optional[BaseException] = None
    # Phase clocks filled in by the batcher thread (queue/batch/execute/
    # respond — obs/stepstats.REQUEST_PHASES).
    phases: Dict[str, float] = field(default_factory=dict)
    # Request-trace context (client-propagated trace id + the frontend's
    # rpc.predict span id) and the WALL-clock enqueue stamp — phase
    # durations ride the monotonic clock above, but deferred span
    # records need a common wall timescale (obs/tracing.py).
    trace_id: str = ""
    parent_span_id: str = ""
    enqueued_ts: float = 0.0
    # The shared serve.batch span payload for the dispatch this request
    # rode (one minted span per batch; every member points at it).
    batch_info: Optional[dict] = None

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("predict result not ready in time")
        if self.error is not None:
            raise self.error
        return self.result


@dataclass(frozen=True)
class BatcherConfig:
    max_batch_size: int = 64
    max_wait_us: int = 2000
    queue_limit: int = 256


class MicroBatcher:
    """Aggregates admitted requests into padded-bucket dispatches.

    ``execute_fn(features, n_valid)`` runs the compiled inference step on
    a padded batch and returns outputs with the batch on axis 0;
    ``on_request(phases, outcome, rows)`` (optional) feeds the
    availability ledger.  Start/stop own the single batcher thread.
    """

    def __init__(
        self,
        execute_fn: Callable[[Dict[str, np.ndarray], int], np.ndarray],
        config: BatcherConfig = BatcherConfig(),
        on_request: Optional[Callable[[Dict[str, float], str, int], None]] = None,
        on_shed: Optional[Callable[[int], None]] = None,
        on_batch: Optional[Callable[[Dict[str, np.ndarray]], None]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self._execute_fn = execute_fn
        self._config = config
        self._on_request = on_request
        self._on_shed = on_shed
        self._on_batch = on_batch
        self._clock = clock
        self._buckets = bucket_sizes(config.max_batch_size)
        self._lock = make_lock("MicroBatcher._lock")
        self._queue: deque = deque()  # guarded-by: _lock
        self._queued_rows = 0  # guarded-by: _lock
        self._wakeup = threading.Condition(self._lock)
        self._stopped = False  # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None
        self._m_depth = obs.gauge(
            "elasticdl_serving_queue_depth",
            "Requests currently waiting for a batch slot",
        )
        self._m_depth.set_function(lambda: len(self._queue))
        self._m_batch_rows = obs.histogram(
            "elasticdl_serving_batch_rows",
            "Real (unpadded) rows per dispatched batch",
            buckets=tuple(float(b) for b in self._buckets),
        )

    @property
    def buckets(self) -> Tuple[int, ...]:
        return self._buckets

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "MicroBatcher":
        self._thread = threading.Thread(
            target=self._run, name="serving-batcher", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        with self._lock:
            self._stopped = True
            self._wakeup.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # Fail any stragglers still queued so no caller blocks forever.
        with self._lock:
            pending = list(self._queue)
            self._queue.clear()
            self._queued_rows = 0
        for req in pending:
            req.error = RequestError("batcher stopped")
            req.done.set()

    # -- admission ------------------------------------------------------

    def submit(
        self,
        features: Dict[str, np.ndarray],
        deadline_s: Optional[float] = None,
        trace_id: str = "",
        parent_span_id: str = "",
    ) -> _Pending:
        """Admit one request (all arrays share axis-0 row count).  Raises
        QueueFullError when the admission queue is at capacity — the
        explicit shed, never a silent unbounded backlog.  ``trace_id``
        and ``parent_span_id`` (the caller's rpc.predict span) ride the
        pending record so sampled requests can journal their phase spans
        after the fact."""
        rows = int(np.asarray(next(iter(features.values()))).shape[0])
        if rows > self._config.max_batch_size:
            raise ValueError(
                f"request rows {rows} exceed max_batch_size "
                f"{self._config.max_batch_size}; split the request"
            )
        now = self._clock()
        req = _Pending(
            features={k: np.asarray(v) for k, v in features.items()},
            rows=rows,
            enqueued_at=now,
            deadline=(now + deadline_s) if deadline_s else None,
            trace_id=str(trace_id),
            parent_span_id=str(parent_span_id),
            enqueued_ts=time.time(),
        )
        with self._lock:
            if self._stopped:
                raise RequestError("batcher stopped")
            if len(self._queue) >= self._config.queue_limit:
                depth = len(self._queue)
                shed = True
            else:
                shed = False
                self._queue.append(req)
                self._queued_rows += rows
                self._wakeup.notify()
        if shed:
            _SHED.inc(reason="queue_full")
            obs.journal().record(
                "request_shed",
                reason="queue_full",
                queue_depth=depth,
                queue_limit=self._config.queue_limit,
                rows=rows,
            )
            if self._on_shed is not None:
                self._on_shed(rows)
            raise QueueFullError(
                f"admission queue full ({depth}/{self._config.queue_limit})"
            )
        return req

    def predict(
        self,
        features: Dict[str, np.ndarray],
        deadline_s: Optional[float] = None,
        wait_timeout_s: Optional[float] = 60.0,
        trace_id: str = "",
        parent_span_id: str = "",
    ) -> np.ndarray:
        """submit + wait, the synchronous convenience used by the
        frontend's request handler threads."""
        return self.submit(
            features, deadline_s,
            trace_id=trace_id, parent_span_id=parent_span_id,
        ).wait(wait_timeout_s)

    # -- the batcher thread ---------------------------------------------

    def _take_batch(self) -> List[_Pending]:
        """Block until a batch is due (full, or the oldest admitted
        request has waited max_wait_us), then pop it.  Empty list on
        stop."""
        max_wait_s = self._config.max_wait_us / 1e6
        with self._lock:
            while True:
                if self._stopped:
                    return []
                if self._queued_rows >= self._config.max_batch_size:
                    break
                if self._queue:
                    age = self._clock() - self._queue[0].enqueued_at
                    if age >= max_wait_s:
                        break
                    self._wakeup.wait(timeout=max_wait_s - age)
                else:
                    self._wakeup.wait(timeout=0.1)
            batch: List[_Pending] = []
            rows = 0
            while self._queue:
                if rows + self._queue[0].rows > self._config.max_batch_size:
                    break
                req = self._queue.popleft()
                self._queued_rows -= req.rows
                rows += req.rows
                batch.append(req)
            return batch

    def _run(self):
        while True:
            batch = self._take_batch()
            if not batch:
                return
            try:
                self._dispatch(batch)
            except Exception:  # never kill the batcher thread
                logger.exception("batch dispatch failed")

    def _dispatch(self, batch: List[_Pending]):
        t_batch = self._clock()
        for req in batch:
            req.phases["queue"] = max(0.0, t_batch - req.enqueued_at)
        expired = [
            r for r in batch if r.deadline is not None and t_batch > r.deadline
        ]
        live = [r for r in batch if r not in expired]
        for req in expired:
            _SHED.inc(reason="deadline")
            obs.journal().record(
                "request_shed", reason="deadline", rows=req.rows,
                waited_s=round(req.phases["queue"], 6),
            )
            self._finish(req, None, RequestError("deadline expired in queue"),
                         outcome="dropped")
        if not live:
            return
        rows = sum(r.rows for r in live)
        stacked = {
            key: np.concatenate(
                [np.asarray(r.features[key]) for r in live], axis=0
            )
            for key in live[0].features
        }
        if self._on_batch is not None:
            # Serve-side quality sketch hook: sees the REAL (unpadded)
            # stacked features — pad rows would skew the id-frequency
            # sketch toward id 0.  Host-side numpy only; its failure
            # must never fail the batch.
            try:
                self._on_batch(stacked)
            except Exception:
                logger.exception("on_batch hook failed (ignored)")
        wall_batch = time.time()
        padded, _ = pad_and_stage(stacked, rows, self._buckets)
        bucket = bucket_for(rows, self._buckets)
        t_exec = self._clock()
        batch_s = t_exec - t_batch
        self._m_batch_rows.observe(float(rows))
        try:
            # Chaos site: a `serving.execute` latency fault stalls the
            # batcher thread (the queue piles up behind it — the
            # injected-queue-stall e2e); an error fault fails the batch.
            spec = faults.fire("serving.execute")
            if spec is not None:
                if spec.kind == "latency":
                    time.sleep(float(spec.arg or 0.1))
                elif spec.kind == "error":
                    raise RuntimeError(
                        f"FAULT INJECTION: serving execute failed "
                        f"({spec.arg or 'error'})"
                    )
            outputs = np.asarray(self._execute_fn(padded, rows))
        except Exception as exc:
            t_done = self._clock()
            self._stamp_batch(live, wall_batch, batch_s, t_done - t_exec,
                              rows, bucket)
            for req in live:
                req.phases["batch"] = batch_s
                req.phases["execute"] = t_done - t_exec
                self._finish(req, None, RequestError(f"execute failed: {exc}"),
                             outcome="error")
            raise
        t_respond = self._clock()
        execute_s = t_respond - t_exec
        self._stamp_batch(live, wall_batch, batch_s, execute_s, rows, bucket)
        offset = 0
        for req in live:
            req.phases["batch"] = batch_s
            req.phases["execute"] = execute_s
            result = outputs[offset:offset + req.rows]
            offset += req.rows
            self._finish(req, result, None, outcome="served")

    def _stamp_batch(self, live: List[_Pending], wall_batch: float,
                     batch_s: float, execute_s: float, rows: int,
                     bucket: int):
        """Attach ONE shared serve.batch span payload to every traced
        member of a dispatch (a minted span id, never journaled here —
        the exemplar sampler journals it once iff a member samples, so
        span cost stays O(sampled))."""
        if not any(r.trace_id for r in live):
            return
        info = {
            "name": "serve.batch",
            "start_ts": wall_batch,
            "duration_s": batch_s + execute_s,
            "span_id": tracing.tracer().mint_span_id(),
            "batch_rows": rows,
            "bucket": bucket,
            "requests": len(live),
        }
        for req in live:
            req.batch_info = info

    def _finish(self, req: _Pending, result, error, outcome: str):
        t0 = self._clock()
        req.result = result
        req.error = error
        req.done.set()
        req.phases["respond"] = self._clock() - t0
        if self._on_request is not None:
            try:
                self._on_request(dict(req.phases), outcome, req.rows)
            except Exception:
                logger.exception("availability-ledger callback failed")
