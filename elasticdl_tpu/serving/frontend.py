"""Threaded gRPC predict frontend for one serving replica.

The environment ships `protoc` without the gRPC plugin, so — like
proto/service.py for the Master service — the stub/servicer glue is
written by hand.  Predict payloads are not protobuf messages at all:
features and outputs ride the npz/npy wire codec below (numpy's own
portable serialization) through identity byte serializers, which keeps
the proto surface at zero while staying a real gRPC service (deadlines,
status codes, metadata all work normally).

Methods (service ``elasticdl_tpu.Predict``):

- ``predict``: npz-encoded features dict -> npy-encoded outputs.  The
  server derives the batcher deadline from the CLIENT's gRPC deadline
  (``context.time_remaining()``), so per-request deadlines are set in
  exactly one place — the caller's `RetryPolicy.timeout_s`
  (common/grpc_utils.py).  A shed request returns RESOURCE_EXHAUSTED
  (the explicit backpressure signal); a deadline lapse returns
  DEADLINE_EXCEEDED.
- ``reload``: JSON ``{"model_dir": ...}`` -> JSON replica stats after
  the hot swap (serving/runtime.py does the generation dance).
- ``stats``: JSON replica + availability-ledger snapshot (the loadgen
  and obs.top's serving mode read the same numbers from the exporter;
  this RPC is for point debugging).
- ``labels``: delayed feedback labels for earlier predict calls — an
  npz dict keyed by TRACE ID (the join key the quality ledger holds
  sampled predictions under), value = that request's label array.
  Replies JSON ``{"received", "joined", "enabled"}``; a replica
  without a quality ledger accepts and ignores (``enabled: false``),
  so label feeds are wire-compatible with pre-quality replicas.
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Dict, Optional

import grpc
import numpy as np

from elasticdl_tpu.common import grpc_utils
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.obs import tracing
from elasticdl_tpu.serving.batcher import MicroBatcher, QueueFullError

logger = get_logger("serving.frontend")

_SERVICE_NAME = "elasticdl_tpu.Predict"
_METHODS = ("predict", "reload", "stats", "labels")

#: Server-side floor under the client deadline: leave headroom for the
#: response to travel back instead of computing a result nobody waits for.
_DEADLINE_HEADROOM_S = 0.005


# ---------------------------------------------------------------------------
# Wire codec: numpy's own portable serialization as the message format
# ---------------------------------------------------------------------------


def encode_features(features: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in features.items()})
    return buf.getvalue()


def decode_features(payload: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(payload)) as npz:
        return {k: npz[k] for k in npz.files}


def encode_array(array: np.ndarray) -> bytes:
    buf = io.BytesIO()
    np.save(buf, np.asarray(array))
    return buf.getvalue()


def decode_array(payload: bytes) -> np.ndarray:
    return np.load(io.BytesIO(payload))


def _identity(b: bytes) -> bytes:
    return b


# ---------------------------------------------------------------------------
# Servicer + server
# ---------------------------------------------------------------------------


class PredictServicer:
    """Request handlers running on the gRPC thread pool; the batcher
    thread owns the device, so handlers only block on `_Pending.wait`.

    Request tracing: a client-propagated trace id
    (``TRACE_METADATA_KEY``) makes this handler the server edge of the
    request's trace — an ``rpc.predict`` span covering the whole RPC,
    parented under the client's span (``SPAN_METADATA_KEY``, the trace
    root by the loadgen convention).  Spans are NOT journaled inline:
    the completed request (every outcome, including queue-full sheds
    that never reach the batcher) feeds the ``ExemplarSampler``
    (serving/ledger.py), which journals the span set only for sampled
    requests — O(sampled), never O(requests)."""

    def __init__(self, replica, batcher: MicroBatcher, sampler=None,
                 quality=None, quality_clock=time.monotonic):
        self._replica = replica
        self._batcher = batcher
        self._sampler = sampler
        self._quality = quality
        self._quality_clock = quality_clock

    def predict(self, request: bytes, context) -> bytes:
        try:
            features = decode_features(request)
        except Exception as exc:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, f"bad features payload: {exc}"
            )
        remaining = context.time_remaining()
        deadline_s = None
        if remaining is not None and remaining < 3600:
            deadline_s = max(0.0, remaining - _DEADLINE_HEADROOM_S)
        trace_id = grpc_utils.trace_id_from_context(context)
        client_span_id = grpc_utils.span_id_from_context(context)
        rpc_span_id = tracing.tracer().mint_span_id() if trace_id else ""
        start_ts = time.time()
        start_mono = time.monotonic()
        req = None
        outcome = "served"
        abort = None  # deferred (code, message): observe BEFORE abort raises
        outputs = None
        try:
            req = self._batcher.submit(
                features,
                deadline_s=deadline_s,
                trace_id=trace_id,
                parent_span_id=rpc_span_id,
            )
            outputs = req.wait(remaining if remaining is not None else 60.0)
        except QueueFullError as exc:
            outcome = "shed"
            abort = (grpc.StatusCode.RESOURCE_EXHAUSTED, str(exc))
        except TimeoutError as exc:
            outcome = "dropped"
            abort = (grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
        except ValueError as exc:
            outcome = "error"
            abort = (grpc.StatusCode.INVALID_ARGUMENT, str(exc))
        except RuntimeError as exc:
            # RequestError: dropped on deadline in queue, or execute failed.
            if "deadline" in str(exc):
                outcome = "dropped"
                abort = (grpc.StatusCode.DEADLINE_EXCEEDED, str(exc))
            else:
                outcome = "error"
                abort = (grpc.StatusCode.INTERNAL, str(exc))
        self._observe_trace(
            trace_id, client_span_id, rpc_span_id, req, outcome,
            start_ts, max(0.0, time.monotonic() - start_mono),
            outputs=outputs, features=features,
        )
        if abort is not None:
            context.abort(*abort)
        return encode_array(outputs)

    def _observe_trace(self, trace_id: str, client_span_id: str,
                       rpc_span_id: str, req, outcome: str,
                       start_ts: float, duration_s: float,
                       outputs=None, features=None):
        """Assemble the request's deferred span set — rpc.predict, the
        phase spans derived from the batcher's stamps, the shared
        serve.batch link — and feed the sampler.  All clocks were read
        by the handler/batcher already; a failure here must never fail
        the RPC."""
        sampler = self._sampler
        if sampler is None or not trace_id:
            return
        try:
            phases = dict(req.phases) if req is not None else {}
            rows = req.rows if req is not None else 1
            rpc_span = {
                "name": "rpc.predict",
                "start_ts": start_ts,
                "duration_s": duration_s,
                "trace_id": trace_id,
                "span_id": rpc_span_id,
                "parent_id": client_span_id,
                "rows": rows,
                "outcome": outcome,
            }
            spans = [rpc_span]
            batch = None
            bucket = None
            if req is not None and "queue" in phases:
                spans.append({
                    "name": "serve.queue",
                    "start_ts": req.enqueued_ts,
                    "duration_s": phases["queue"],
                    "trace_id": trace_id,
                    "parent_id": rpc_span_id,
                })
            if req is not None and req.batch_info is not None:
                batch = dict(req.batch_info)
                bucket = batch.get("bucket")
                rpc_span["batch_span_id"] = batch["span_id"]
                exec_start = req.enqueued_ts + phases.get("queue", 0.0) \
                    + phases.get("batch", 0.0)
                if "execute" in phases:
                    exec_span_id = tracing.tracer().mint_span_id()
                    spans.append({
                        "name": "serve.execute",
                        "start_ts": exec_start,
                        "duration_s": phases["execute"],
                        "trace_id": trace_id,
                        "span_id": exec_span_id,
                        "parent_id": batch["span_id"],
                        "batch_span_id": batch["span_id"],
                        "rows": rows,
                    })
                    if "respond" in phases:
                        # Parented under rpc.predict, NOT the execute
                        # span: respond starts where execute ends, and
                        # the assembler's monotonic clamp would squash a
                        # child that lives past its parent's end.
                        spans.append({
                            "name": "serve.respond",
                            "start_ts": exec_start + phases["execute"],
                            "duration_s": phases["respond"],
                            "trace_id": trace_id,
                            "parent_id": rpc_span_id,
                        })
            generation = None
            try:
                generation = int(self._replica.generation.gen_id)
            except Exception:
                pass
            if batch is not None and generation is not None:
                batch["generation"] = generation
            sampler.observe(
                trace_id,
                phases,
                outcome,
                rows=rows,
                latency_s=(duration_s if not phases else None),
                spans=spans,
                batch=batch,
                generation=generation,
                bucket=bucket,
                predictions=outputs,
                features=features,
            )
        except Exception:
            logger.exception("request-trace observe failed")

    def reload(self, request: bytes, context) -> bytes:
        try:
            model_dir = json.loads(request.decode("utf-8"))["model_dir"]
        except Exception as exc:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, f"bad reload payload: {exc}"
            )
        # A delta link (checkpoint/delta.py artifact) applies in place —
        # no full reload; a failed apply rolled back, the old generation
        # still answers, and the INTERNAL status tells the caller so.
        is_delta = os.path.exists(os.path.join(model_dir, "delta.json"))
        try:
            if is_delta:
                self._replica.apply_delta(model_dir)
            else:
                self._replica.reload(model_dir)
        except Exception as exc:
            logger.exception(
                "%s failed", "delta apply" if is_delta else "hot-swap reload"
            )
            context.abort(grpc.StatusCode.INTERNAL, f"reload failed: {exc}")
        return self.stats(b"", context)

    def stats(self, request: bytes, context) -> bytes:
        from elasticdl_tpu.serving.ledger import ledger

        payload = dict(self._replica.stats())
        payload["queue_depth"] = self._batcher.queue_depth()
        payload["ledger"] = ledger().snapshot()
        return json.dumps(payload).encode("utf-8")

    def labels(self, request: bytes, context) -> bytes:
        """Delayed feedback labels: npz keyed by trace id.  Unknown
        trace ids (unsampled, expired, or pre-quality replica) are
        absorbed, never errors — an at-least-once label feed must be
        safe to replay against any replica."""
        try:
            mapping = decode_features(request)
        except Exception as exc:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT, f"bad labels payload: {exc}"
            )
        quality = self._quality
        joined = 0
        if quality is not None:
            now = self._quality_clock()
            for trace_id, label_arr in mapping.items():
                try:
                    if quality.note_label(trace_id, label_arr, now=now):
                        joined += 1
                except Exception:
                    logger.exception("label join failed for %s", trace_id)
        return json.dumps({
            "received": len(mapping),
            "joined": joined,
            "enabled": quality is not None,
        }).encode("utf-8")


def add_PredictServicer_to_server(servicer, server):
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=_identity,
            response_serializer=_identity,
        )
        for name in _METHODS
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(_SERVICE_NAME, handlers),)
    )


class PredictStub:
    """Raw client stub (bytes in/bytes out); most callers want
    `PredictClient` below."""

    def __init__(self, channel: grpc.Channel):
        for name in _METHODS:
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{_SERVICE_NAME}/{name}",
                    request_serializer=_identity,
                    response_deserializer=_identity,
                ),
            )


class ServingFrontend:
    """The replica's listening edge: grpc_utils server + PredictServicer.
    `start()` binds (port 0 = ephemeral) and returns the bound port."""

    def __init__(
        self,
        replica,
        batcher: MicroBatcher,
        port: int = 0,
        max_workers: int = 16,
        sampler=None,
        quality=None,
        quality_clock=time.monotonic,
    ):
        self._servicer = PredictServicer(
            replica, batcher, sampler=sampler, quality=quality,
            quality_clock=quality_clock,
        )
        self._server = grpc_utils.build_server(max_workers=max_workers)
        add_PredictServicer_to_server(self._servicer, self._server)
        self._requested_port = port
        self.port: Optional[int] = None

    def start(self) -> int:
        self.port = self._server.add_insecure_port(
            f"[::]:{self._requested_port}"
        )
        self._server.start()
        logger.info("Predict frontend listening on port %d", self.port)
        return self.port

    def stop(self, grace: float = 2.0):
        self._server.stop(grace).wait()


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


class PredictClient:
    """Typed client over the byte stub: codec + per-request deadline +
    the shared retry plane (predict is idempotent — a retried request
    recomputes the same rows)."""

    def __init__(self, addr: str, deadline_s: float = 10.0):
        self._addr = addr
        self._channel = grpc_utils.build_channel(addr)
        self._stub = PredictStub(self._channel)
        self._policy = grpc_utils.RetryPolicy(
            timeout_s=deadline_s,
            max_attempts=grpc_utils.IDEMPOTENT_POLICY.max_attempts,
            wait_for_ready=True,
        )
        self._stats = grpc_utils.RetryStats()

    def predict(
        self,
        features: Dict[str, np.ndarray],
        deadline_s: Optional[float] = None,
        trace_id: str = "",
        span_id: str = "",
    ) -> np.ndarray:
        """``trace_id``/``span_id`` ride the call metadata
        (``TRACE_METADATA_KEY``/``SPAN_METADATA_KEY``) so the server's
        rpc.predict span joins the caller's trace; empty sends none —
        wire-compatible with pre-tracing servers."""
        policy = self._policy
        if deadline_s is not None:
            policy = grpc_utils.RetryPolicy(
                timeout_s=deadline_s,
                max_attempts=policy.max_attempts,
                wait_for_ready=True,
            )
        payload = grpc_utils.call_with_retry(
            self._stub.predict,
            encode_features(features),
            method="predict",
            policy=policy,
            stats=self._stats,
            seed=self._addr,
            metadata=grpc_utils.trace_metadata(trace_id, span_id),
        )
        return decode_array(payload)

    def reload(self, model_dir: str, deadline_s: float = 120.0) -> dict:
        # NOT retried: a reload that already landed should not re-run.
        payload = self._stub.reload(
            json.dumps({"model_dir": model_dir}).encode("utf-8"),
            timeout=deadline_s,
        )
        return json.loads(payload.decode("utf-8"))

    def send_labels(self, labels: Dict[str, np.ndarray],
                    deadline_s: float = 10.0) -> dict:
        """Deliver delayed feedback labels keyed by trace id.  Retried
        (at-least-once is safe: a duplicate delivery lands as an orphan
        on the server, never a double join)."""
        payload = grpc_utils.call_with_retry(
            self._stub.labels,
            encode_features(labels),
            method="labels",
            policy=grpc_utils.RetryPolicy(
                timeout_s=deadline_s, max_attempts=3, wait_for_ready=True
            ),
            stats=self._stats,
            seed=self._addr,
        )
        return json.loads(payload.decode("utf-8"))

    def stats(self, deadline_s: float = 10.0) -> dict:
        payload = grpc_utils.call_with_retry(
            self._stub.stats,
            b"",
            method="stats",
            policy=grpc_utils.RetryPolicy(
                timeout_s=deadline_s, max_attempts=2, wait_for_ready=True
            ),
            stats=self._stats,
            seed=self._addr,
        )
        return json.loads(payload.decode("utf-8"))

    def close(self):
        self._channel.close()
