"""Elastic supervision for serving replicas.

The paper's elastic control plane — pod manager, restart budget, policy
engine — is exactly the machinery a serving fleet needs, with ONE
semantic inversion: training workers form a collective (any death
invalidates the world: collectives wedge, so the pod manager restarts
everything), while serving replicas are independent.  A replica death
must NOT take the survivors down — they are what availability is made
of.  `ServingReplicaManager` therefore subclasses the subprocess
substrate and overrides only the churn handler: dead replicas are
replaced with FRESH ids (never reused, same as workers), survivors keep
serving, and the same `worker_churn` journal event records the repair.

Everything else is inherited unchanged: `kill_worker()` (the SIGKILL
e2e), `scale()` (elastic resize), the restart budget, the monitor
thread, and the policy-engine surface (`current_worker_ids`,
`kill_worker`, `scale`) — an `ElasticPolicyEngine` binds to this
manager exactly as it does to the training pod manager.

`start_serving_fleet` is the one-call assembly used by tests and
operators: journal into the shared serve dir, build the replica argv,
start the manager (and optionally a policy engine) — the serving twin
of master/main.start_master.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from elasticdl_tpu import obs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.master.pod_manager import (
    LocalProcessManager,
    _exit_reason,
)
from elasticdl_tpu.serving.replica_main import live_replicas

logger = get_logger("serving.supervisor")


class SLOAlertFollower:
    """Forwards replica-journaled ``slo_alert`` edges to the policy
    engine's `note_slo_alert` advisory input.

    Replicas are separate processes: their SLO planes (obs/slo.py)
    evaluate locally and journal into the SHARED serve-dir journal.
    The supervisor cannot get a callback across the process boundary,
    but it CAN tail that journal — which is already the fleet-wide
    event bus (`/journal`, `obs.top --serving`).  `poll_once()` is the
    deterministic entry point (tests drive it directly); `start()`
    runs it on a named daemon thread."""

    def __init__(self, policy, journal=None, poll_interval_s: float = 1.0,
                 tail_n: int = 400):
        self._policy = policy
        self._journal = journal if journal is not None else obs.journal()
        self._poll_interval_s = float(poll_interval_s)
        self._tail_n = int(tail_n)
        # (ts, slo, origin, state) of already-forwarded edges, bounded —
        # tail() re-serves old events every poll.
        self._seen: set = set()
        self._seen_order: List[tuple] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def poll_once(self) -> int:
        forwarded = 0
        for event in self._journal.tail(self._tail_n):
            if event.get("event") != "slo_alert":
                continue
            key = (event.get("ts"), event.get("slo"),
                   event.get("origin"), event.get("state"))
            if key in self._seen:
                continue
            self._seen.add(key)
            self._seen_order.append(key)
            while len(self._seen_order) > 4 * self._tail_n:
                self._seen.discard(self._seen_order.pop(0))
            evidence = {
                k: event[k] for k in
                ("grade", "burn_rates", "budget_remaining_ratio",
                 "offending", "origin") if k in event
            }
            try:
                self._policy.note_slo_alert(
                    event.get("slo", ""), event.get("state") == "fire",
                    evidence,
                )
                forwarded += 1
            except Exception:
                logger.exception("SLO alert forward failed")
        return forwarded

    def start(self) -> "SLOAlertFollower":
        if self._thread is not None:
            return self

        def _loop():
            while not self._stop.wait(self._poll_interval_s):
                try:
                    self.poll_once()
                except Exception:
                    logger.exception("SLO alert poll failed")

        self._thread = threading.Thread(
            target=_loop, name="slo-alert-follower", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None


class ServingReplicaManager(LocalProcessManager):
    """Subprocess pod manager with replace-the-dead (not
    restart-the-world) churn semantics."""

    #: Wired by start_serving_fleet when a policy engine is given; the
    #: manager owns its teardown (stop() drains it with the fleet).
    slo_follower: Optional[SLOAlertFollower] = None

    def stop(self):
        follower = self.slo_follower
        if follower is not None:
            follower.stop()
        super().stop()

    def _handle_churn_serialized(self, handles: List, crashed):
        dead_ids = {h.worker_id for h, _ in crashed}
        survivors = [h for h in handles if h.worker_id not in dead_ids]
        for h, code in crashed:
            logger.warning(
                "%s died (exit %s) — replacing it (survivors keep serving)",
                self._describe(h),
                code,
            )
            self._m_relaunches.inc(reason=_exit_reason(code))
        with self._lock:
            self._restarts_used += 1
            budget_left = self._restarts_used <= self._max_restarts
            n_new = len(dead_ids) if budget_left else 0
            new_ids = list(
                range(self._next_worker_id, self._next_worker_id + n_new)
            )
            self._next_worker_id += n_new
        obs.journal().record(
            "worker_churn",
            workers=sorted(dead_ids),
            exit_codes=[code for _, code in crashed],
            old_size=len(handles),
            restarts_used=self._restarts_used,
            budget_left=budget_left,
        )
        # Reap the dead processes (they have exited; this only closes
        # their handles) — never the survivors.
        self._substrate_terminate([h for h, _ in crashed])
        new_handles = self._substrate_launch(new_ids) if new_ids else []
        with self._lock:
            stopped = self._stopped
            if stopped:
                remaining = []
            else:
                self._handles = survivors + new_handles
                remaining = self._handles
        if stopped:
            # stop() raced the repair; don't leak the fresh replicas.
            self._substrate_terminate(new_handles)
            return
        if not remaining:
            with self._lock:
                self._failed_reason = reason = (
                    f"restart budget exhausted ({self._restarts_used - 1} "
                    "used) and no serving replicas left"
                )
                self._stopped = True
            logger.error("Serving fleet failed: %s", reason)
            obs.journal().record("job_failed", reason=reason)
            self._done_event.set()


def replica_argv_fn(
    model_dir: str,
    serve_dir: str,
    *,
    model_zoo: str = "",
    sparse_kernel: str = "auto",
    max_batch_size: int = 64,
    max_wait_us: int = 2000,
    queue_limit: int = 256,
    telemetry_interval_s: float = 1.0,
    warmup_features: str = "",
    pub_dir: str = "",
    pub_poll_interval_s: float = 2.0,
    freshness_slo_s: float = 0.0,
    slo_availability_target: float = 0.0,
    slo_p99_ms: float = 0.0,
    slo_compliance_window_s: float = 3600.0,
    trace_head_every: int = 128,
    trace_exemplar_capacity: int = 64,
    trace_tail_threshold_ms: float = 0.0,
    quality_join_window_s: float = 0.0,
    quality_window_size: int = 2048,
    quality_gate_max_logloss_regress: float = 0.10,
    quality_gate_max_auc_drop: float = 0.05,
    quality_gate_min_rows: int = 64,
    quality_unknown_policy: str = "open",
    quality_gate_force: bool = False,
    quality_drift_threshold: float = 0.25,
    quality_slo_logloss: float = 0.0,
    python: str = sys.executable,
) -> Callable[[int], List[str]]:
    """The pod manager's `worker_argv_fn` for serving replicas: the
    worker id IS the replica id (fresh per launch, never reused)."""

    def argv(worker_id: int) -> List[str]:
        cmd = [
            python, "-m", "elasticdl_tpu.serving.replica_main",
            "--model_dir", model_dir,
            "--serve_dir", serve_dir,
            "--replica_id", str(worker_id),
            "--model_zoo", model_zoo,
            "--sparse_kernel", sparse_kernel,
            "--max_batch_size", str(max_batch_size),
            "--max_wait_us", str(max_wait_us),
            "--queue_limit", str(queue_limit),
            "--telemetry_interval_s", str(telemetry_interval_s),
        ]
        if warmup_features:
            cmd += ["--warmup_features", warmup_features]
        if slo_availability_target > 0 or slo_p99_ms > 0:
            # The replica evaluates its SLOs locally and journals the
            # alert edges into the shared serve dir; the supervisor's
            # SLOAlertFollower turns those into policy advisories.
            cmd += [
                "--slo_availability_target", str(slo_availability_target),
                "--slo_p99_ms", str(slo_p99_ms),
                "--slo_compliance_window_s", str(slo_compliance_window_s),
            ]
        if pub_dir:
            # Continuous serving: each replica tracks the delta chain
            # itself (and evaluates the freshness SLO locally when set).
            cmd += [
                "--pub_dir", pub_dir,
                "--pub_poll_interval_s", str(pub_poll_interval_s),
                "--freshness_slo_s", str(freshness_slo_s),
            ]
        if (trace_head_every != 128 or trace_exemplar_capacity != 64
                or trace_tail_threshold_ms > 0):
            # Only forwarded when tuned away from the replica defaults,
            # so pre-tracing argv pins stay byte-identical.
            cmd += [
                "--trace_head_every", str(trace_head_every),
                "--trace_exemplar_capacity", str(trace_exemplar_capacity),
                "--trace_tail_threshold_ms", str(trace_tail_threshold_ms),
            ]
        if quality_join_window_s > 0:
            # Model-quality plane (obs/quality.py): the join window is
            # the master switch; only forwarded when armed, so
            # pre-quality argv pins stay byte-identical.
            cmd += [
                "--quality_join_window_s", str(quality_join_window_s),
                "--quality_window_size", str(quality_window_size),
                "--quality_gate_max_logloss_regress",
                str(quality_gate_max_logloss_regress),
                "--quality_gate_max_auc_drop",
                str(quality_gate_max_auc_drop),
                "--quality_gate_min_rows", str(quality_gate_min_rows),
                "--quality_unknown_policy", quality_unknown_policy,
                "--quality_drift_threshold", str(quality_drift_threshold),
                "--quality_slo_logloss", str(quality_slo_logloss),
            ]
            if quality_gate_force:
                cmd += ["--quality_gate_force"]
        return cmd

    return argv


def start_serving_fleet(
    num_replicas: int,
    model_dir: str,
    serve_dir: str,
    *,
    worker_env: Optional[Dict[str, str]] = None,
    log_dir: str = "",
    max_restarts: int = 3,
    policy=None,
    **argv_kwargs,
) -> ServingReplicaManager:
    """Assemble and start the fleet.  `policy` (an ElasticPolicyEngine)
    is bound to the manager and started when given."""
    os.makedirs(serve_dir, exist_ok=True)
    obs.init_journal(serve_dir)
    # Replica processes must import this package no matter where the
    # supervisor was launched from.
    import elasticdl_tpu

    pkg_root = os.path.dirname(os.path.dirname(elasticdl_tpu.__file__))
    env = dict(worker_env or {})
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (pkg_root, env.get("PYTHONPATH",
                                      os.environ.get("PYTHONPATH", "")))
        if p
    )
    manager = ServingReplicaManager(
        num_replicas,
        replica_argv_fn(model_dir, serve_dir, **argv_kwargs),
        worker_env=env,
        log_dir=log_dir or os.path.join(serve_dir, "logs"),
        max_restarts=max_restarts,
    )
    obs.journal().record(
        "serving_fleet_start",
        replicas=num_replicas,
        model_dir=model_dir,
        serve_dir=serve_dir,
    )
    manager.start()
    if policy is not None:
        policy.bind(manager).start()
        if hasattr(policy, "note_slo_alert"):
            # The sensor->policy edge: replica slo_alert events in the
            # shared journal become policy advisories.  The manager owns
            # the follower's teardown (ServingReplicaManager.stop).
            manager.slo_follower = SLOAlertFollower(policy).start()
    return manager


def wait_for_replicas(
    serve_dir: str,
    n: int,
    timeout_s: float = 120.0,
    poll_s: float = 0.2,
) -> List[dict]:
    """Block until `n` live replicas have published their ports (the
    discovery handshake loadgen and the e2e ride)."""
    deadline = time.monotonic() + timeout_s
    while True:
        live = live_replicas(serve_dir)
        if len(live) >= n:
            return live
        if time.monotonic() >= deadline:
            raise TimeoutError(
                f"only {len(live)}/{n} serving replicas published ports "
                f"within {timeout_s:.0f}s"
            )
        time.sleep(poll_s)
