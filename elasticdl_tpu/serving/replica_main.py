"""Serving replica process entrypoint.

One replica = one process = one `ServingReplica` (device runtime) + one
`MicroBatcher` (front door) + one `ServingFrontend` (gRPC edge), run
under the elastic pod manager exactly like a training worker
(`serving/supervisor.py` builds the argv; a SIGKILLed replica is
relaunched with a fresh replica id — ids are never reused).

Discovery rides the shared ``--serve_dir``:

- ``replica-<id>.json`` — this replica's bound predict port, metrics
  port, and pid (atomic tmp+rename write).  `live_replicas()` is the
  reader: it prunes entries whose pid is gone, so loadgen/e2e always
  see the surviving fleet across SIGKILL relaunches without a naming
  service.
- ``events.jsonl`` — every replica journals into the SHARED serve-dir
  journal (append mode), so `model_swap` / `request_shed` /
  ``serving_telemetry`` events from the whole fleet land in one
  timeline; any one exporter's ``/journal`` endpoint (or
  ``obs.top --serving``) then shows fleet-wide serving state.

Per-replica detail (qps/p50/p99/queue-depth/generation) is journaled as
``serving_telemetry`` once per ``--telemetry_interval_s`` — replica id
is unbounded, so it rides the journal, never a metric label
(metric-label-cardinality rule).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import tempfile
import threading
import time
from typing import Dict, List, Optional

from elasticdl_tpu import obs
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving.replica")


# ---------------------------------------------------------------------------
# Serve-dir discovery
# ---------------------------------------------------------------------------


def replica_info_file(serve_dir: str, replica_id: int) -> str:
    return os.path.join(serve_dir, f"replica-{replica_id}.json")


def write_replica_info(serve_dir: str, replica_id: int, info: dict) -> str:
    """Atomic tmp+rename publish (a reader never sees a torn write)."""
    path = replica_info_file(serve_dir, replica_id)
    fd, tmp = tempfile.mkstemp(prefix="replica.", dir=serve_dir)
    with os.fdopen(fd, "w") as f:
        json.dump(info, f)
    os.replace(tmp, path)
    return path


def live_replicas(serve_dir: str) -> List[dict]:
    """Every published replica whose pid is still alive, sorted by
    replica id.  Stale files from SIGKILLed replicas (their relaunch
    gets a FRESH id) are skipped, not deleted — the journal, not the
    serve dir, is the record of what happened."""
    out = []
    try:
        names = os.listdir(serve_dir)
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith("replica-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(serve_dir, name)) as f:
                info = json.load(f)
            os.kill(int(info["pid"]), 0)
        except (OSError, ValueError, KeyError):
            continue
        out.append(info)
    return sorted(out, key=lambda i: i.get("replica_id", 0))


# ---------------------------------------------------------------------------
# Entrypoint
# ---------------------------------------------------------------------------


def parse_replica_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="elasticdl_tpu serving replica")
    parser.add_argument("--model_dir", required=True,
                        help="export.py artifact to serve")
    parser.add_argument("--serve_dir", required=True,
                        help="shared discovery + journal directory")
    parser.add_argument("--replica_id", type=int, default=0)
    parser.add_argument("--port", type=int, default=0,
                        help="predict port (0 = ephemeral)")
    parser.add_argument("--metrics_port", type=int, default=0)
    parser.add_argument("--model_zoo", default="")
    parser.add_argument("--sparse_kernel", default="auto",
                        choices=("xla", "fused", "auto"))
    parser.add_argument("--max_batch_size", type=int, default=64)
    parser.add_argument("--max_wait_us", type=int, default=2000)
    parser.add_argument("--queue_limit", type=int, default=256)
    parser.add_argument("--telemetry_interval_s", type=float, default=1.0)
    parser.add_argument("--pub_dir", default="",
                        help="delta-chain publish dir (checkpoint/delta.py); "
                             "when set, a DeltaWatcher keeps this replica "
                             "tracking the newest servable generation")
    parser.add_argument("--pub_poll_interval_s", type=float, default=2.0)
    parser.add_argument("--freshness_slo_s", type=float, default=0.0,
                        help="event-time -> servable-model lag SLO; 0 "
                             "disables breach evaluation")
    parser.add_argument("--warmup_features", default="",
                        help="npz file of one example request; every "
                             "padded bucket is pre-traced from it")
    parser.add_argument("--slo_availability_target", type=float, default=0.0,
                        help="serving-availability SLO objective (e.g. "
                             "0.999); 0 registers no availability SLO")
    parser.add_argument("--slo_p99_ms", type=float, default=0.0,
                        help="p99 latency bound for the serving-latency "
                             "SLO; 0 registers no latency SLO")
    parser.add_argument("--slo_compliance_window_s", type=float,
                        default=3600.0,
                        help="rolling error-budget window for this "
                             "replica's SLOs")
    args, unknown = parser.parse_known_args(argv)
    if unknown:
        logger.warning("Ignoring unknown replica args: %s", unknown)
    return args


def _build_slo_plane(args):
    """This replica's SLO plane (obs/slo.py) over the process registry.
    The history sampler always runs (it feeds the exporter's /slo
    sparklines); SLO specs register only when their flags opt in.
    Ticked by the telemetry loop — one periodic thread, not two."""
    from elasticdl_tpu.obs.slo import (
        SLOPlane, freshness_slo, serving_availability_slo,
        serving_latency_slo,
    )

    specs = []
    window_s = float(args.slo_compliance_window_s)
    if args.slo_availability_target > 0:
        specs.append(serving_availability_slo(
            args.slo_availability_target, compliance_window_s=window_s
        ))
    if args.slo_p99_ms > 0:
        specs.append(serving_latency_slo(
            args.slo_p99_ms, compliance_window_s=window_s
        ))
    if args.freshness_slo_s > 0 and args.pub_dir:
        specs.append(freshness_slo(
            args.freshness_slo_s, compliance_window_s=window_s
        ))
    return SLOPlane(specs=specs, origin=f"replica_{args.replica_id}")


def _telemetry_loop(stop: threading.Event, interval_s: float, replica,
                    batcher, replica_id: int, slo_plane=None):
    from elasticdl_tpu.serving.ledger import ledger

    while not stop.wait(interval_s):
        if slo_plane is not None:
            try:
                slo_plane.tick()
            except Exception:
                logger.exception("SLO tick failed")
        snap = ledger().snapshot()
        stats = replica.stats()
        obs.journal().record(
            "serving_telemetry",
            replica_id=replica_id,
            generation=stats["generation"],
            step=stats["step"],
            model_event_time=stats.get("model_event_time", 0.0),
            inflight=stats["inflight"],
            queue_depth=batcher.queue_depth(),
            qps=snap["qps"],
            p50_ms=snap["p50_ms"],
            p99_ms=snap["p99_ms"],
            availability_ratio=snap["availability_ratio"],
            served=snap["counts"]["served"],
            dropped=snap["counts"]["dropped"],
            shed=snap["counts"]["shed"],
            errors=snap["counts"]["error"],
        )


def main(argv=None) -> int:
    args = parse_replica_args(argv)
    os.makedirs(args.serve_dir, exist_ok=True)
    obs.init_journal(args.serve_dir)

    from elasticdl_tpu.obs.exporter import MetricsExporter
    from elasticdl_tpu.serving.batcher import BatcherConfig, MicroBatcher
    from elasticdl_tpu.serving.frontend import ServingFrontend, decode_features
    from elasticdl_tpu.serving.ledger import ledger
    from elasticdl_tpu.serving.runtime import ServingReplica

    replica = ServingReplica(
        args.model_dir,
        sparse_kernel=args.sparse_kernel,
        model_zoo=args.model_zoo,
    )
    book = ledger()
    batcher = MicroBatcher(
        replica.execute,
        BatcherConfig(
            max_batch_size=args.max_batch_size,
            max_wait_us=args.max_wait_us,
            queue_limit=args.queue_limit,
        ),
        on_request=book.record_request,
        on_shed=book.record_shed,
    ).start()
    # Every resource below owns a daemon thread and/or a listening
    # socket; a failure anywhere between start() and the serve loop
    # (warmup decode, bind error, pub_dir scan) must still drain them
    # all, so teardown lives in one finally covering the whole lifetime.
    frontend = None
    exporter = None
    watcher = None
    telemetry = None
    slo_plane = None
    stop = threading.Event()
    try:
        if args.warmup_features:
            with open(args.warmup_features, "rb") as f:
                example = decode_features(f.read())
            replica.warmup(example, batcher.buckets)
            logger.info("Warmed %d bucket shapes", len(batcher.buckets))

        frontend = ServingFrontend(replica, batcher, port=args.port)
        port = frontend.start()
        slo_plane = _build_slo_plane(args)
        exporter = MetricsExporter(
            port=args.metrics_port, slo_plane=slo_plane
        ).start()
        write_replica_info(args.serve_dir, args.replica_id, {
            "replica_id": args.replica_id,
            "pid": os.getpid(),
            "port": port,
            "metrics_port": exporter.port,
            "model_dir": args.model_dir,
        })
        obs.journal().record(
            "serving_replica_start",
            replica_id=args.replica_id,
            port=port,
            model_dir=args.model_dir,
            generation=replica.stats()["generation"],
        )

        def _shutdown(signum, frame):
            logger.info("Replica %d: signal %d, shutting down",
                        args.replica_id, signum)
            stop.set()

        signal.signal(signal.SIGTERM, _shutdown)
        signal.signal(signal.SIGINT, _shutdown)

        telemetry = threading.Thread(
            target=_telemetry_loop,
            args=(stop, args.telemetry_interval_s, replica, batcher,
                  args.replica_id, slo_plane),
            name="serving-telemetry",
            daemon=True,
        )
        telemetry.start()

        if args.pub_dir:
            from elasticdl_tpu.obs.freshness import FreshnessTracker
            from elasticdl_tpu.serving.continuous import DeltaWatcher

            freshness = (
                FreshnessTracker(args.freshness_slo_s)
                if args.freshness_slo_s > 0
                else None
            )
            watcher = DeltaWatcher(
                replica, args.pub_dir, freshness=freshness
            ).start(args.pub_poll_interval_s)
            logger.info(
                "Tracking delta chain in %s every %.1fs", args.pub_dir,
                args.pub_poll_interval_s,
            )

        while not stop.wait(0.5):
            pass
    finally:
        stop.set()
        if watcher is not None:
            watcher.stop()
        if frontend is not None:
            frontend.stop()
        batcher.stop()
        if exporter is not None:
            exporter.stop()
        if slo_plane is not None:
            slo_plane.stop()
        if telemetry is not None:
            telemetry.join(timeout=5)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
