"""Serving replica process entrypoint.

One replica = one process = one `ServingReplica` (device runtime) + one
`MicroBatcher` (front door) + one `ServingFrontend` (gRPC edge), run
under the elastic pod manager exactly like a training worker
(`serving/supervisor.py` builds the argv; a SIGKILLed replica is
relaunched with a fresh replica id — ids are never reused).

Discovery rides the shared ``--serve_dir``:

- ``replica-<id>.json`` — this replica's bound predict port, metrics
  port, and pid (atomic tmp+rename write).  `live_replicas()` is the
  reader: it prunes entries whose pid is gone, so loadgen/e2e always
  see the surviving fleet across SIGKILL relaunches without a naming
  service.
- ``events.jsonl`` — every replica journals into the SHARED serve-dir
  journal (append mode), so `model_swap` / `request_shed` /
  ``serving_telemetry`` events from the whole fleet land in one
  timeline; any one exporter's ``/journal`` endpoint (or
  ``obs.top --serving``) then shows fleet-wide serving state.

Per-replica detail (qps/p50/p99/queue-depth/generation) is journaled as
``serving_telemetry`` once per ``--telemetry_interval_s`` — replica id
is unbounded, so it rides the journal, never a metric label
(metric-label-cardinality rule).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import tempfile
import threading
import time
from typing import Dict, List, Optional

from elasticdl_tpu import obs
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving.replica")


# ---------------------------------------------------------------------------
# Serve-dir discovery
# ---------------------------------------------------------------------------


def replica_info_file(serve_dir: str, replica_id: int) -> str:
    return os.path.join(serve_dir, f"replica-{replica_id}.json")


def write_replica_info(serve_dir: str, replica_id: int, info: dict) -> str:
    """Atomic tmp+rename publish (a reader never sees a torn write)."""
    path = replica_info_file(serve_dir, replica_id)
    fd, tmp = tempfile.mkstemp(prefix="replica.", dir=serve_dir)
    with os.fdopen(fd, "w") as f:
        json.dump(info, f)
    os.replace(tmp, path)
    return path


def live_replicas(serve_dir: str) -> List[dict]:
    """Every published replica whose pid is still alive, sorted by
    replica id.  Stale files from SIGKILLed replicas (their relaunch
    gets a FRESH id) are skipped, not deleted — the journal, not the
    serve dir, is the record of what happened."""
    out = []
    try:
        names = os.listdir(serve_dir)
    except OSError:
        return out
    for name in sorted(names):
        if not (name.startswith("replica-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(serve_dir, name)) as f:
                info = json.load(f)
            os.kill(int(info["pid"]), 0)
        except (OSError, ValueError, KeyError):
            continue
        out.append(info)
    return sorted(out, key=lambda i: i.get("replica_id", 0))


# ---------------------------------------------------------------------------
# Entrypoint
# ---------------------------------------------------------------------------


def parse_replica_args(argv=None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(description="elasticdl_tpu serving replica")
    parser.add_argument("--model_dir", required=True,
                        help="export.py artifact to serve")
    parser.add_argument("--serve_dir", required=True,
                        help="shared discovery + journal directory")
    parser.add_argument("--replica_id", type=int, default=0)
    parser.add_argument("--port", type=int, default=0,
                        help="predict port (0 = ephemeral)")
    parser.add_argument("--metrics_port", type=int, default=0)
    parser.add_argument("--model_zoo", default="")
    parser.add_argument("--sparse_kernel", default="auto",
                        choices=("xla", "fused", "auto"))
    parser.add_argument("--max_batch_size", type=int, default=64)
    parser.add_argument("--max_wait_us", type=int, default=2000)
    parser.add_argument("--queue_limit", type=int, default=256)
    parser.add_argument("--telemetry_interval_s", type=float, default=1.0)
    parser.add_argument("--pub_dir", default="",
                        help="delta-chain publish dir (checkpoint/delta.py); "
                             "when set, a DeltaWatcher keeps this replica "
                             "tracking the newest servable generation")
    parser.add_argument("--pub_poll_interval_s", type=float, default=2.0)
    parser.add_argument("--freshness_slo_s", type=float, default=0.0,
                        help="event-time -> servable-model lag SLO; 0 "
                             "disables breach evaluation")
    parser.add_argument("--warmup_features", default="",
                        help="npz file of one example request; every "
                             "padded bucket is pre-traced from it")
    parser.add_argument("--slo_availability_target", type=float, default=0.0,
                        help="serving-availability SLO objective (e.g. "
                             "0.999); 0 registers no availability SLO")
    parser.add_argument("--slo_p99_ms", type=float, default=0.0,
                        help="p99 latency bound for the serving-latency "
                             "SLO; 0 registers no latency SLO")
    parser.add_argument("--slo_compliance_window_s", type=float,
                        default=3600.0,
                        help="rolling error-budget window for this "
                             "replica's SLOs")
    parser.add_argument("--trace_head_every", type=int, default=128,
                        help="deterministic head-sampling period of the "
                             "request-trace exemplar sampler (1-in-N "
                             "traced requests journal; 0 disables head "
                             "samples)")
    parser.add_argument("--trace_exemplar_capacity", type=int, default=64,
                        help="bounded in-memory exemplar ring size")
    parser.add_argument("--trace_tail_threshold_ms", type=float, default=0.0,
                        help="tail-exemplar latency threshold; 0 ties it "
                             "to --slo_p99_ms (the SLO the fleet pages "
                             "on defines 'slow')")
    parser.add_argument("--quality_join_window_s", type=float, default=0.0,
                        help="label-join watermark window of the model-"
                             "quality plane (obs/quality.py): sampled "
                             "predictions wait this long for their "
                             "delayed label; 0 disables the whole plane "
                             "(ledger, drift sketches, canary gate)")
    parser.add_argument("--quality_window_size", type=int, default=2048,
                        help="joined (prediction, label) pairs in the "
                             "online AUC/logloss window")
    parser.add_argument("--quality_gate_max_logloss_regress", type=float,
                        default=0.10,
                        help="candidate-vs-live logloss regression that "
                             "HOLDs a delta swap")
    parser.add_argument("--quality_gate_max_auc_drop", type=float,
                        default=0.05,
                        help="candidate-vs-live AUC drop that HOLDs a "
                             "delta swap")
    parser.add_argument("--quality_gate_min_rows", type=int, default=64,
                        help="labeled replay rows required before the "
                             "gate can score (below = quality unknown)")
    parser.add_argument("--quality_unknown_policy", default="open",
                        choices=("open", "closed"),
                        help="gate verdict when quality is unknown "
                             "(label outage / cold buffer): open passes "
                             "the swap, closed holds it")
    parser.add_argument("--quality_gate_force", action="store_true",
                        help="escape hatch: swap even on a beyond-"
                             "threshold regression (journaled "
                             "outcome=forced)")
    parser.add_argument("--quality_drift_threshold", type=float,
                        default=0.25,
                        help="train-serve sketch divergence (total "
                             "variation) that journals a quality_drift "
                             "breach")
    parser.add_argument("--quality_slo_logloss", type=float, default=0.0,
                        help="online-logloss bound for the model_quality "
                             "SLO; 0 registers no quality SLO")
    args, unknown = parser.parse_known_args(argv)
    if unknown:
        logger.warning("Ignoring unknown replica args: %s", unknown)
    return args


def _build_slo_plane(args):
    """This replica's SLO plane (obs/slo.py) over the process registry.
    The history sampler always runs (it feeds the exporter's /slo
    sparklines); SLO specs register only when their flags opt in.
    Ticked by the telemetry loop — one periodic thread, not two."""
    from elasticdl_tpu.obs.slo import (
        SLOPlane, freshness_slo, quality_slo, serving_availability_slo,
        serving_latency_slo,
    )

    specs = []
    window_s = float(args.slo_compliance_window_s)
    if args.slo_availability_target > 0:
        specs.append(serving_availability_slo(
            args.slo_availability_target, compliance_window_s=window_s
        ))
    if args.slo_p99_ms > 0:
        specs.append(serving_latency_slo(
            args.slo_p99_ms, compliance_window_s=window_s
        ))
    if args.freshness_slo_s > 0 and args.pub_dir:
        specs.append(freshness_slo(
            args.freshness_slo_s, compliance_window_s=window_s
        ))
    if args.quality_slo_logloss > 0 and args.quality_join_window_s > 0:
        specs.append(quality_slo(
            args.quality_slo_logloss, compliance_window_s=window_s
        ))
    return SLOPlane(specs=specs, origin=f"replica_{args.replica_id}")


def _build_quality_plane(args):
    """The model-quality plane (obs/quality.py), all-or-nothing on
    `--quality_join_window_s`: label-join ledger feeding a replay
    buffer, drift monitor, and the canary gate the DeltaWatcher runs
    every delta link through.  Returns (quality, drift, gate) —
    (None, None, None) when disabled, so the rest of main() wires
    nothing and the replica behaves byte-identically to pre-quality."""
    if args.quality_join_window_s <= 0:
        return None, None, None
    from elasticdl_tpu.obs.quality import (
        CanaryGate, DriftMonitor, QualityLedger, ReplayBuffer,
    )

    origin = f"replica_{args.replica_id}"
    replay = ReplayBuffer()
    quality = QualityLedger(
        window_size=args.quality_window_size,
        join_window_s=args.quality_join_window_s,
        origin=origin,
        replay=replay,
    )
    drift = DriftMonitor(
        threshold=args.quality_drift_threshold, origin=origin
    )
    gate = CanaryGate(
        replay,
        max_logloss_regress=args.quality_gate_max_logloss_regress,
        max_auc_drop=args.quality_gate_max_auc_drop,
        min_rows=args.quality_gate_min_rows,
        unknown_policy=args.quality_unknown_policy,
        force=args.quality_gate_force,
    )
    return quality, drift, gate


def _telemetry_loop(stop: threading.Event, interval_s: float, replica,
                    batcher, replica_id: int, slo_plane=None,
                    sampler=None, quality=None, drift=None):
    from elasticdl_tpu.serving.ledger import ledger

    while not stop.wait(interval_s):
        if quality is not None:
            try:
                # Window gauges BEFORE the SLO tick samples the
                # registry, so the quality SLO never scores stale data.
                quality.journal_window(time.monotonic())
            except Exception:
                logger.exception("quality window journal failed")
        if drift is not None:
            try:
                drift.evaluate(time.monotonic())
            except Exception:
                logger.exception("drift evaluation failed")
        if slo_plane is not None:
            try:
                slo_plane.tick()
            except Exception:
                logger.exception("SLO tick failed")
        snap = ledger().snapshot()
        stats = replica.stats()
        phase_p99 = snap.get("phase_p99_ms", {})
        extra = {}
        if sampler is not None:
            slowest = sampler.slowest()
            if slowest is not None:
                # Bounded exemplar pointer (trace id is journal-only per
                # the cardinality rule): what obs.top --serving prints
                # in its footer line.
                extra["exemplar"] = {
                    "trace_id": slowest["trace_id"],
                    "latency_ms": slowest["latency_ms"],
                    "dominant_phase": slowest["dominant_phase"],
                }
        obs.journal().record(
            "serving_telemetry",
            replica_id=replica_id,
            generation=stats["generation"],
            step=stats["step"],
            model_event_time=stats.get("model_event_time", 0.0),
            inflight=stats["inflight"],
            queue_depth=batcher.queue_depth(),
            qps=snap["qps"],
            p50_ms=snap["p50_ms"],
            p99_ms=snap["p99_ms"],
            queue_p99_ms=phase_p99.get("queue", 0.0),
            batch_p99_ms=phase_p99.get("batch", 0.0),
            execute_p99_ms=phase_p99.get("execute", 0.0),
            respond_p99_ms=phase_p99.get("respond", 0.0),
            availability_ratio=snap["availability_ratio"],
            served=snap["counts"]["served"],
            dropped=snap["counts"]["dropped"],
            shed=snap["counts"]["shed"],
            errors=snap["counts"]["error"],
            **extra,
        )


def main(argv=None) -> int:
    args = parse_replica_args(argv)
    os.makedirs(args.serve_dir, exist_ok=True)
    obs.init_journal(args.serve_dir)

    from elasticdl_tpu.common import faults
    from elasticdl_tpu.obs import tracing
    from elasticdl_tpu.obs.exporter import MetricsExporter
    from elasticdl_tpu.serving.batcher import BatcherConfig, MicroBatcher
    from elasticdl_tpu.serving.frontend import ServingFrontend, decode_features
    from elasticdl_tpu.serving.ledger import ExemplarSampler, ledger
    from elasticdl_tpu.serving.runtime import ServingReplica

    if faults.install_from_env():
        logger.warning("Replica %d: fault injection armed from env",
                       args.replica_id)
    # Name this process on the assembled trace: span records carry their
    # own `proc`, so every replica gets its own Perfetto pid row even
    # though the whole fleet appends to ONE serve-dir journal.
    tracing.set_process(f"replica_{args.replica_id}")

    replica = ServingReplica(
        args.model_dir,
        sparse_kernel=args.sparse_kernel,
        model_zoo=args.model_zoo,
    )
    quality, drift, gate = _build_quality_plane(args)
    book = ledger()
    batcher = MicroBatcher(
        replica.execute,
        BatcherConfig(
            max_batch_size=args.max_batch_size,
            max_wait_us=args.max_wait_us,
            queue_limit=args.queue_limit,
        ),
        on_request=book.record_request,
        on_shed=book.record_shed,
        on_batch=(drift.observe_serve if drift is not None else None),
    ).start()
    tail_ms = args.trace_tail_threshold_ms or args.slo_p99_ms
    sampler = ExemplarSampler(
        head_every=args.trace_head_every,
        tail_threshold_ms=tail_ms,
        capacity=args.trace_exemplar_capacity,
        replica_id=args.replica_id,
        quality=quality,
    )
    # Every resource below owns a daemon thread and/or a listening
    # socket; a failure anywhere between start() and the serve loop
    # (warmup decode, bind error, pub_dir scan) must still drain them
    # all, so teardown lives in one finally covering the whole lifetime.
    frontend = None
    exporter = None
    watcher = None
    telemetry = None
    slo_plane = None
    stop = threading.Event()
    try:
        if args.warmup_features:
            with open(args.warmup_features, "rb") as f:
                example = decode_features(f.read())
            replica.warmup(example, batcher.buckets)
            logger.info("Warmed %d bucket shapes", len(batcher.buckets))

        frontend = ServingFrontend(replica, batcher, port=args.port,
                                   sampler=sampler, quality=quality)
        port = frontend.start()
        slo_plane = _build_slo_plane(args)
        # Latency pages carry evidence: the slowest sampled trace ids at
        # fire time, resolvable in the Perfetto trace from this journal.
        slo_plane.slos.set_exemplar_provider(
            lambda _slo: sampler.trace_ids(4))
        exporter = MetricsExporter(
            port=args.metrics_port, slo_plane=slo_plane
        ).start()
        write_replica_info(args.serve_dir, args.replica_id, {
            "replica_id": args.replica_id,
            "pid": os.getpid(),
            "port": port,
            "metrics_port": exporter.port,
            "model_dir": args.model_dir,
        })
        obs.journal().record(
            "serving_replica_start",
            replica_id=args.replica_id,
            port=port,
            model_dir=args.model_dir,
            generation=replica.stats()["generation"],
        )

        def _shutdown(signum, frame):
            logger.info("Replica %d: signal %d, shutting down",
                        args.replica_id, signum)
            stop.set()

        signal.signal(signal.SIGTERM, _shutdown)
        signal.signal(signal.SIGINT, _shutdown)

        telemetry = threading.Thread(
            target=_telemetry_loop,
            args=(stop, args.telemetry_interval_s, replica, batcher,
                  args.replica_id, slo_plane, sampler, quality, drift),
            name="serving-telemetry",
            daemon=True,
        )
        telemetry.start()

        if args.pub_dir:
            from elasticdl_tpu.obs.freshness import FreshnessTracker
            from elasticdl_tpu.serving.continuous import DeltaWatcher

            freshness = (
                FreshnessTracker(args.freshness_slo_s)
                if args.freshness_slo_s > 0
                else None
            )
            watcher = DeltaWatcher(
                replica, args.pub_dir, freshness=freshness,
                gate=gate, buckets=batcher.buckets,
                origin=f"replica_{args.replica_id}",
            ).start(args.pub_poll_interval_s)
            logger.info(
                "Tracking delta chain in %s every %.1fs%s", args.pub_dir,
                args.pub_poll_interval_s,
                " (canary-gated)" if gate is not None else "",
            )

        while not stop.wait(0.5):
            pass
    finally:
        stop.set()
        if watcher is not None:
            watcher.stop()
        if frontend is not None:
            frontend.stop()
        batcher.stop()
        if exporter is not None:
            exporter.stop()
        if slo_plane is not None:
            slo_plane.stop()
        if telemetry is not None:
            telemetry.join(timeout=5)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
