"""Worker pod/process entrypoint.

Parity: elasticdl/python/worker/main.py in the reference.
"""

from __future__ import annotations

import sys

from elasticdl_tpu.common.args import parse_worker_args
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_utils import load_model_spec
from elasticdl_tpu.data.reader import build_data_reader
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker

logger = get_logger("worker.main")


def main(argv=None):
    args = parse_worker_args(argv)
    model_spec = load_model_spec(args)
    data_reader = build_data_reader(args, model_spec, args.training_data)
    client = MasterClient(args.master_addr, worker_id=args.worker_id)
    worker = Worker(
        master_client=client,
        model_spec=model_spec,
        data_reader=data_reader,
        minibatch_size=args.minibatch_size,
    )
    worker.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
