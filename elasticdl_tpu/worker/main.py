"""Worker pod/process entrypoint.

Parity: elasticdl/python/worker/main.py in the reference.
"""

from __future__ import annotations

import sys

from elasticdl_tpu.common.args import parse_worker_args
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_utils import load_model_spec
from elasticdl_tpu.data.reader import build_data_reader
from elasticdl_tpu.worker.master_client import MasterClient
from elasticdl_tpu.worker.worker import Worker

logger = get_logger("worker.main")


def _sigterm_to_systemexit(signum, frame):
    """Convert the pod manager's graceful terminate() (SIGTERM) into a
    normal interpreter exit so `finally` blocks and atexit hooks run —
    most importantly the StepProfiler flush: a preempted worker
    mid-profile-window ships a partial trace instead of losing it.
    The manager escalates to SIGKILL after its grace period, so a hung
    shutdown still dies."""
    raise SystemExit(128 + signum)


def main(argv=None):
    import os
    import signal

    try:
        signal.signal(signal.SIGTERM, _sigterm_to_systemexit)
    except ValueError:
        pass  # not the main thread (in-process test harnesses)

    # The host environment may force-select its accelerator platform at
    # interpreter start (sitecustomize), overriding JAX_PLATFORMS; honor an
    # explicit override before any backend initializes (multi-process CPU
    # worlds in tests/single-host runs depend on it).
    forced = os.environ.get("ELASTICDL_FORCE_PLATFORM")
    if forced:
        import jax

        jax.config.update("jax_platforms", forced)
    from elasticdl_tpu.common import faults

    if faults.install_from_env():
        logger.warning(
            "Fault injection armed from %s=%r",
            faults.ENV_VAR, os.environ.get(faults.ENV_VAR),
        )
    args = parse_worker_args(argv)
    # Tracing plane identity + crash flight recorder: this worker's
    # spans label as `worker_<id>` on the assembled trace
    # (obs/trace.py), and process exit — including SIGTERM via the
    # SystemExit conversion above — flushes open spans + a final
    # registry snapshot, so a preempted worker leaves a complete trace
    # tail instead of a cliff.
    from elasticdl_tpu.obs import tracing

    tracing.set_process(f"worker_{args.worker_id}")
    tracing.install_flight_recorder()
    if getattr(args, "tensorboard_log_dir", ""):
        # Each process owns its journal (obs scoping rule): give worker
        # processes a durable file so worker-side events — profile_window
        # trace pointers, step_anatomy in Local mode, worker spans —
        # survive the process instead of dying with the in-memory tail.
        # Distinct filename per worker: no collision with the master's
        # events.jsonl in the shared log dir.
        from elasticdl_tpu import obs

        obs.init_journal(
            args.tensorboard_log_dir,
            filename=f"events_worker_{args.worker_id}.jsonl",
        )
    if getattr(args, "jax_compilation_cache_dir", ""):
        import jax

        # Persistent compile cache: a re-formed world's jit compiles are
        # disk hits — the dominant recovery cost after process start.
        jax.config.update(
            "jax_compilation_cache_dir", args.jax_compilation_cache_dir
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if getattr(args, "oov_diagnostics", False):
        from elasticdl_tpu.parallel import packed

        packed.set_oov_debug(True)
    if getattr(args, "quality_drift_bins", 0) > 0:
        # Train-side skew sketch (obs/quality.py): every train batch's
        # integer feature ids fold into a process-local DriftMonitor
        # for train-serve divergence (host-side numpy, O(bins) memory).
        from elasticdl_tpu.obs import quality

        quality.enable_train_sketch(quality.DriftMonitor(
            threshold=args.quality_drift_threshold,
            bins=args.quality_drift_bins,
            origin=f"worker_{args.worker_id}",
        ))
    model_spec = load_model_spec(args)
    data_reader = build_data_reader(args, model_spec, args.training_data)
    validation_reader = (
        build_data_reader(args, model_spec, args.validation_data)
        if args.validation_data
        else None
    )
    prediction_reader = (
        build_data_reader(args, model_spec, args.prediction_data)
        if args.prediction_data
        else None
    )
    client = MasterClient(args.master_addr, worker_id=args.worker_id)
    if args.distribution_strategy in (
        "AllreduceStrategy",
        "ParameterServerStrategy",
    ):
        worker = _build_collective_worker(
            args, model_spec, data_reader, client,
            validation_reader, prediction_reader,
        )
    else:
        from elasticdl_tpu.common.profiler import StepProfiler
        from elasticdl_tpu.obs.stepstats import StepAnatomy

        from elasticdl_tpu.data.pipeline import PipelineConfig

        anatomy = StepAnatomy(args.worker_id)
        anatomy.set_model(
            getattr(args, "model_def", "") or getattr(args, "model_zoo", "")
        )
        worker = Worker(
            master_client=client,
            model_spec=model_spec,
            data_reader=data_reader,
            minibatch_size=args.minibatch_size,
            validation_data_reader=validation_reader,
            prediction_data_reader=prediction_reader,
            profiler=StepProfiler(
                args.tensorboard_log_dir, args.profile_steps, args.worker_id
            ),
            anatomy=anatomy,
            pipeline=PipelineConfig.from_args(args),
        )
    worker.run()
    if args.output and "training" in args.job_type:
        # Export the servable artifact at job end (reference: the master's
        # model handler exports after training).  ALL ranks call this in
        # lockstep — materializing process-spanning PS tables is a
        # collective row-gather — and only rank 0 writes; tables stream
        # out in bounded row chunks, so this works at any table size.
        from elasticdl_tpu.client.api import save_model

        save_model(worker.trainer, args.output, args)
    return 0


def _build_collective_worker(
    args, model_spec, data_reader, client,
    validation_reader=None, prediction_reader=None,
):
    """Join the elastic world, build the mesh-wide trainer, restore state."""
    from elasticdl_tpu.checkpoint import CheckpointSaver
    from elasticdl_tpu.obs.telemetry import WorkerTelemetry
    from elasticdl_tpu.parallel import MeshConfig, build_mesh
    from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
    from elasticdl_tpu.parallel.elastic import join_world
    from elasticdl_tpu.worker.collective_worker import CollectiveWorker

    world = join_world(client)
    # Worker telemetry plane: step times / task progress / RPC retries
    # collected here ride the liveness heartbeat to the master's
    # aggregator (docs/observability.md "Worker telemetry plane").
    telemetry = WorkerTelemetry(args.worker_id)
    telemetry.bind_retry_stats(client.retry_stats)
    telemetry.set_rendezvous(world.rendezvous_id)
    # Step-anatomy ledger (docs/observability.md "Step anatomy"): the
    # phase decomposition rides the same heartbeat snapshot; the
    # CollectiveWorker reads it off the telemetry binding and registers
    # the trainer's jitted entrypoints for retrace detection.
    from elasticdl_tpu.obs.stepstats import StepAnatomy

    anatomy = StepAnatomy(args.worker_id)
    anatomy.set_model(
        getattr(args, "model_def", "") or getattr(args, "model_zoo", "")
    )
    telemetry.bind_anatomy(anatomy)
    # All devices of the joined world, shaped (data, model): the model
    # axis carries sharded embedding tables and — for mesh-aware zoo
    # models — ring-attention context parallelism.
    mesh = build_mesh(
        MeshConfig(model=getattr(args, "mesh_model_axis", 1))
    )
    # --sparse_kernel resolution is STRATEGY-INDEPENDENT (the Embedding
    # layers run under every trainer).  Multi-device meshes run the
    # fused kernels through the shard_map dispatch
    # (ops/sparse_embedding.py "Sharded dispatch") — the v1 whole-job
    # downgrade to xla is gone.  Register BOTH process defaults BEFORE
    # the model is built: the kernel default (Embedding layers that did
    # not thread sparse_kernel explicitly resolve it at trace time; zoo
    # models that declare the param get the same value via model_params,
    # common/model_utils.py) and the dispatch mesh (layers that did not
    # thread `mesh` still route per-shard kernel bodies instead of
    # tracing an unpartitionable pallas_call into an SPMD program).
    from elasticdl_tpu.ops import sparse_embedding as ske

    sparse_kernel = getattr(args, "sparse_kernel", "auto") or "auto"
    ske.set_default_kernel(sparse_kernel)
    ske.set_dispatch_mesh(mesh)
    if args.distribution_strategy == "ParameterServerStrategy":
        from elasticdl_tpu.parallel.ps_trainer import ShardedEmbeddingTrainer

        trainer = ShardedEmbeddingTrainer(
            model=model_spec.build_model(mesh=mesh),
            loss_fn=model_spec.loss,
            optimizer=model_spec.optimizer(),
            mesh=mesh,
            embedding_optimizer=(
                model_spec.embedding_optimizer()
                if model_spec.embedding_optimizer is not None
                else None
            ),
            sparse_apply_every=getattr(args, "sparse_apply_every", 1),
            sparse_kernel=sparse_kernel,
        )
    else:
        trainer = DataParallelTrainer(
            model=model_spec.build_model(mesh=mesh),
            loss_fn=model_spec.loss,
            optimizer=model_spec.optimizer(),
            mesh=mesh,
            dense_sharding=args.dense_sharding,
        )
    saver = None
    if args.checkpoint_dir:
        if (
            args.distribution_strategy == "ParameterServerStrategy"
            or args.dense_sharding == "fsdp"
        ):
            # Mesh-sharded state (PS tables / FSDP dense leaves): each
            # process writes its own shard files, so no host ever gathers
            # the full model (checkpoint/sharded.py).
            from elasticdl_tpu.checkpoint import ShardedCheckpointSaver

            saver = ShardedCheckpointSaver(
                args.checkpoint_dir, keep_max=args.keep_checkpoint_max
            )
        else:
            saver = CheckpointSaver(
                args.checkpoint_dir, keep_max=args.keep_checkpoint_max
            )
    from elasticdl_tpu.common.profiler import StepProfiler

    return CollectiveWorker(
        master_client=client,
        model_spec=model_spec,
        data_reader=data_reader,
        minibatch_size=args.minibatch_size,
        world=world,
        trainer=trainer,
        checkpoint_saver=saver,
        checkpoint_steps=args.checkpoint_steps,
        validation_data_reader=validation_reader,
        prediction_data_reader=prediction_reader,
        profiler=StepProfiler(
            args.tensorboard_log_dir, args.profile_steps, args.worker_id
        ),
        train_window_steps=args.train_window_steps,
        telemetry=telemetry,
        pipeline=_pipeline_config(args),
    )


def _pipeline_config(args):
    from elasticdl_tpu.data.pipeline import PipelineConfig

    return PipelineConfig.from_args(args)


if __name__ == "__main__":
    sys.exit(main())
