"""The jit-compiled training/evaluation step.

Parity: the reference's per-minibatch work in
elasticdl/python/worker/worker.py (`training_process_eagerly`,
`forward_process`) — TF eager GradientTape there; here a single XLA-compiled
function: forward + backward + optimizer apply fused into one program, so
elementwise ops fuse into the matmuls and the whole step is one device
launch per minibatch.  Optimizers are optax transforms (the reference's Go
PS applied Eigen kernels server-side; on TPU the update is part of the step).
"""

from __future__ import annotations

import inspect
from functools import partial
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("worker.trainer")


class TrainState(NamedTuple):
    step: jnp.ndarray
    params: Any
    opt_state: Any
    model_state: Any  # non-trainable collections, e.g. batch_stats


def _unbox_partitioned(tree):
    """Strip flax partitioning metadata boxes (sharding hints are consumed
    by the sharded trainers; the dense trainers want plain arrays)."""
    import flax.linen as nn

    return jax.tree.map(
        lambda x: x.unbox() if isinstance(x, nn.Partitioned) else x,
        tree,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )


def _model_apply(model, variables, features, train: bool, mutable):
    """Call a flax module, passing `train` only if the model accepts it."""
    call_params = inspect.signature(model.__call__).parameters
    kwargs = {}
    if "train" in call_params:
        kwargs["train"] = train
    if mutable:
        return model.apply(variables, features, mutable=mutable, **kwargs)
    return model.apply(variables, features, **kwargs), {}


class Trainer:
    """Owns model variables and the jitted train/eval steps for one device.

    The distributed trainers (allreduce / sharded-embedding) wrap the same
    loss/grad core with shard_map over a Mesh; this class is the Local-mode
    and single-chip path.
    """

    def __init__(
        self,
        model,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        seed: int = 0,
    ):
        self._model = model
        self._loss_fn = loss_fn
        self._tx = optimizer
        self._seed = seed
        self._state: Optional[TrainState] = None
        # Host-side mirror of state.step: reading the device scalar every
        # batch would force a device sync and serialize the hot loop.
        self._host_step = 0
        self._train_step = jax.jit(self._train_step_impl, donate_argnums=(0,))
        self._eval_step = jax.jit(self._eval_step_impl)

    def jitted_entrypoints(self) -> dict:
        """Jitted entrypoints by name for the step-anatomy retrace
        watcher (obs/stepstats.py)."""
        return {
            "train_step": self._train_step,
            "eval_step": self._eval_step,
        }

    # ------------------------------------------------------------------

    def _init_state(self, features) -> TrainState:
        from elasticdl_tpu.layers.embedding import (
            export_spec_map,
            strip_capture_collections,
        )

        rng = jax.random.PRNGKey(self._seed)
        variables = dict(self._model.init(rng, jax.tree.map(jnp.asarray, features)))
        self._export_specs = export_spec_map(variables)
        variables = strip_capture_collections(variables)
        params = _unbox_partitioned(variables.pop("params"))
        model_state = _unbox_partitioned(variables)  # batch_stats etc
        opt_state = self._tx.init(params)
        logger.info(
            "Initialized model: %d parameters",
            sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params)),
        )
        return TrainState(jnp.zeros((), jnp.int32), params, opt_state, model_state)

    def ensure_initialized(self, features):
        if self._state is None:
            self._state = self._init_state(features)
        return self._state

    @property
    def state(self) -> Optional[TrainState]:
        return self._state

    @state.setter
    def state(self, value: TrainState):
        self._state = value
        self._host_step = int(value.step)  # one sync on restore, not per batch

    @property
    def step(self) -> int:
        return self._host_step

    # ------------------------------------------------------------------

    def _train_step_impl(self, state: TrainState, features, labels):
        mutable_keys = list(state.model_state.keys())

        def compute_loss(params):
            variables = {"params": params, **state.model_state}
            (outputs, new_model_state) = _model_apply(
                self._model, variables, features, train=True, mutable=mutable_keys
            )
            loss = self._loss_fn(labels, outputs)
            return loss, new_model_state

        (loss, new_model_state), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        updates, new_opt_state = self._tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if not mutable_keys:
            new_model_state = state.model_state
        return (
            TrainState(state.step + 1, new_params, new_opt_state, new_model_state),
            loss,
        )

    def _eval_step_impl(self, state: TrainState, features):
        variables = {"params": state.params, **state.model_state}
        outputs, _ = _model_apply(
            self._model, variables, features, train=False, mutable=False
        )
        return outputs

    # ------------------------------------------------------------------

    def train_step(self, features, labels) -> float:
        state = self.ensure_initialized(features)
        self._state, loss = self._train_step(state, features, labels)
        self._host_step += 1
        return loss

    def eval_step(self, features):
        state = self.ensure_initialized(features)
        return self._eval_step(state, features)

    def get_variables_numpy(self) -> dict:
        """Flat {path: np.ndarray} view of all variables (for export/ckpt).
        Packed embedding tables are unpacked to their logical [vocab, dim]
        export view (same contract as the PS trainer)."""
        from elasticdl_tpu.parallel import packed as pk

        state = self._state
        if state is None:
            return {}
        specs = getattr(self, "_export_specs", {})
        flat = {}
        tree = {"params": state.params, **state.model_state}
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            key = "/".join(str(getattr(p, "key", p)) for p in path)
            if key in specs:
                leaf = pk.unpack(specs[key], leaf)
            flat[key] = np.asarray(leaf)
        return flat
