"""AllReduce-mode worker: lockstep task loop over a multi-process world.

Parity: elasticdl/python/worker/allreduce_trainer.py + worker.py in the
reference — per-step gradient allreduce with elastic re-formation on
failure.  TPU design differences (see parallel/elastic.py):

- Rank 0 pulls tasks from the master and broadcasts them (a task is the
  *global* unit of work; the reference gave each worker its own task, which
  deadlocks lockstep collectives when task sizes diverge).
- Each global minibatch is contiguously partitioned across ranks; ragged
  tails pad + mask, so every rank runs the same number of compiled steps.
- On any worker death the whole world dies and is re-launched by the pod
  manager; this process restores from the latest checkpoint at boot, and
  the master's task queue replays unfinished work (at-least-once).
"""

from __future__ import annotations

import contextlib
import time
import traceback
from typing import List, Optional

import jax
import numpy as np

from elasticdl_tpu import obs
from elasticdl_tpu.common import faults
from elasticdl_tpu.common.constants import Mode, TaskExecCounterKey
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_utils import ModelSpec
from elasticdl_tpu.data.columnar import materialize_columnar_task
from elasticdl_tpu.data.dataset import Dataset, SequentialRecords, _stack
from elasticdl_tpu.data.pipeline import (
    ParsePool,
    PipelineConfig,
    Prefetcher,
    StagingPipeline,
)
from elasticdl_tpu.obs import goodput, tracing
from elasticdl_tpu.parallel import elastic
from elasticdl_tpu.parallel import sharding as shd
from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer
from elasticdl_tpu.parallel.elastic import WorldInfo
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.worker import concat_named, named_arrays

logger = get_logger("worker.collective_worker")


class CollectiveWorker:
    def __init__(
        self,
        master_client,
        model_spec: ModelSpec,
        data_reader,
        minibatch_size: int,
        world: WorldInfo,
        trainer: DataParallelTrainer,
        checkpoint_saver=None,
        checkpoint_steps: int = 0,
        report_version_every_steps: int = 20,
        wait_sleep_s: float = 0.5,
        validation_data_reader=None,
        prediction_data_reader=None,
        profiler=None,
        train_window_steps: int = 0,
        telemetry=None,
        anatomy=None,
        pipeline: Optional[PipelineConfig] = None,
    ):
        self._mc = master_client
        self._spec = model_spec
        self._mb = minibatch_size
        self._world = world
        self._trainer = trainer
        # Worker-side telemetry collector (obs/telemetry.WorkerTelemetry):
        # step times / task progress recorded here ride the heartbeat to
        # the master's aggregator.  None = telemetry plane off (tests).
        self._telemetry = telemetry
        # Step-anatomy ledger (obs/stepstats.StepAnatomy): decomposes
        # each dispatch's wall time into data_wait / stage / compile /
        # execute / bookkeep with host-side clocks.  Defaults to the one
        # bound to the telemetry collector (worker/main wiring), so its
        # windows ride the same heartbeat.  None = anatomy off.
        self._anatomy = anatomy or getattr(telemetry, "anatomy", None)
        if self._anatomy is not None and hasattr(
            trainer, "jitted_entrypoints"
        ):
            self._anatomy.watch_jits(trainer.jitted_entrypoints)
        # Each process supplies `block` rows per collective step (>= mb,
        # rounded up to divide its local device count).
        self._block = trainer.local_block(minibatch_size)
        self._ckpt = checkpoint_saver
        self._ckpt_steps = checkpoint_steps
        self._report_every = report_version_every_steps
        self._wait_sleep_s = wait_sleep_s
        self._last_reported_version = 0
        self._last_ckpt_step = 0
        self._profiler = profiler
        # Batches per device dispatch; 0 = AUTO (sized per job from the
        # measured optimum, the task size, and a staged-bytes cap — see
        # _window_candidate).
        self._window_steps = int(train_window_steps)
        # Async staging engine (data/pipeline.py, --pipeline async):
        # bounded background prefetch + parse pool off the step loop's
        # critical path, staging booked as overlap credit while a
        # dispatch is outstanding.  Sync (the default) is byte-identical
        # to the classic serial loop.  The parse pool is process-long
        # (threads are reused across tasks; per-imap state drains with
        # each task, and churn kills the whole process anyway).
        self._pipeline = pipeline or PipelineConfig()
        self._parse_pool = (
            ParsePool(self._pipeline.parse_workers)
            if self._pipeline.is_async and self._pipeline.parse_workers > 0
            else None
        )
        self._batch_nbytes: Optional[int] = None
        self._apply_short_warned = False
        # The windowed sparse apply (ps_trainer sparse_apply_every) chunks
        # WITHIN one dispatch window — accumulation never spans dispatches,
        # and batches routed through the per-step tail program apply
        # strictly.  A window smaller than the apply interval silently
        # halves (or worse) the promised amortization, so grow an EXPLICIT
        # window to a multiple and say so (auto windows round themselves).
        # `auto` apply mode resolves inside the trainer at init (table
        # rows unknown until then) — reads 1 here and re-syncs via
        # _sync_apply_every() right after ensure_initialized, before
        # anything compiles.
        self._apply_every = int(getattr(trainer, "_sparse_apply_every", 1) or 1)
        self._grow_explicit_window_to_apply_multiple()
        # Pinned from the first task (standard task size) so the job
        # compiles ONE fused-scan executable; smaller (tail) tasks fall
        # back to the already-compiled per-step program instead of
        # compiling a one-off K-step scan per distinct tail size.
        self._effective_window: Optional[int] = None
        self._columnar_logged = False
        # Task-type -> reader: evaluation/prediction shards address their
        # own data sources when configured.
        self._readers = {
            pb.TRAINING: data_reader,
            pb.TRAIN_END_CALLBACK: data_reader,
            pb.EVALUATION: validation_data_reader or data_reader,
            pb.PREDICTION: prediction_data_reader or data_reader,
        }
        # Deterministic shard listing — identical on every rank (same
        # readers over the same data); indexes the task-broadcast encoding.
        # shard_names(), not create_shards(): workers never need the record
        # counts, and counting can be a network round-trip (ODPS).
        names: List[str] = []
        for reader in (data_reader, validation_data_reader, prediction_data_reader):
            if reader is None:
                continue
            for name in reader.shard_names():
                if name not in names:
                    names.append(name)
        self._shard_names = names
        self._metadata = data_reader.metadata

    @property
    def trainer(self) -> DataParallelTrainer:
        return self._trainer

    @property
    def is_leader(self) -> bool:
        return self._world.is_leader

    # ------------------------------------------------------------------

    @property
    def _sharded_ckpt(self) -> bool:
        """Sharded protocol when both sides support it: the trainer keeps
        mesh-sharded state (PS tables) and the saver speaks per-process
        shard files (checkpoint/sharded.py) — every rank reads/writes only
        its own rows instead of rank 0 pickling a full gather."""
        return hasattr(self._trainer, "save_checkpoint") and hasattr(
            self._ckpt, "latest_step"
        )

    def restore_from_checkpoint(self):
        if self._ckpt is None:
            return
        # Goodput: restore time is its own phase (this process's ledger)
        # — after a re-formation it is part of what the rescale costs.
        # The tracing span gives the same window a node on the assembled
        # timeline (rank-scoped; no task trace yet at boot).
        with goodput.ledger().phase("checkpoint_restore", cause="boot"):
            with tracing.span(
                "checkpoint.restore", rank=self._world.rank
            ):
                self._restore_from_checkpoint_inner()

    def _restore_from_checkpoint_inner(self):
        if self._sharded_ckpt:
            step = self._ckpt.latest_step()
            if step is not None:
                self._trainer.set_sharded_restore(self._ckpt, step)
                self._last_ckpt_step = step
                logger.info(
                    "Rank %d will restore sharded checkpoint at step %d",
                    self._world.rank,
                    step,
                )
            return
        state, step = self._ckpt.load_latest()
        if state is not None:
            self._trainer.state = state
            # Seed the delta cadence so a restart doesn't trigger a
            # spurious full-state checkpoint one window after restore.
            self._last_ckpt_step = step
            logger.info(
                "Rank %d restored checkpoint at step %d", self._world.rank, step
            )

    def run(self):
        heartbeat = elastic.HeartbeatReporter(
            self._mc, self._world, telemetry=self._telemetry
        ).start()
        try:
            self._run_task_loop()
        finally:
            heartbeat.stop()
            if self._profiler is not None:
                self._profiler.stop()

    def _verify_restore_consistency(self):
        """Post-restore world-formation check over the control-plane
        collective (parallel/collective.py): every rank must have picked
        the SAME checkpoint step.  A divergent rank (filesystem race, a
        rank whose checkpoint dir mount failed and found nothing) would
        otherwise train from different weights and silently corrupt the
        run — fail the process instead, so the pod manager re-forms the
        world (reference behavior: CollectiveCommunicator membership
        checks around re-formation)."""
        if self._world.world_size <= 1:
            return
        from elasticdl_tpu.parallel.collective import (
            CollectiveCommunicator,
            CollectiveResult,
        )

        comm = CollectiveCommunicator(self._trainer.mesh)
        # Exact-integer comparison against the leader's step (a float MEAN
        # would round in float32 past 2^24 steps and false-abort healthy
        # long-running worlds).
        step = int(self._last_ckpt_step)
        status, leader_step = comm.broadcast(np.int64(step), root=0)
        if status is not CollectiveResult.SUCCEEDED:
            raise RuntimeError(
                "Restore-consistency broadcast failed; re-forming world"
            )
        if int(leader_step) != step:
            raise RuntimeError(
                f"Rank {self._world.rank} restored checkpoint step "
                f"{step} but rank 0 restored {int(leader_step)} — "
                "divergent restores; aborting so the world re-forms "
                "from a consistent snapshot"
            )

    # -- step anatomy (no-op contexts when the plane is off) ------------

    def _anat_phase(self, name: str):
        if self._anatomy is None:
            return contextlib.nullcontext()
        return self._anatomy.phase(name)

    def _anat_dispatch(self, n_steps: int, n_examples: int):
        if self._anatomy is None:
            return contextlib.nullcontext()
        return self._anatomy.dispatch(n_steps, n_examples)

    def _run_task_loop(self):
        self.restore_from_checkpoint()
        self._verify_restore_consistency()
        while True:
            # Queue wait is data_wait — but only for REAL tasks: a WAIT
            # poll is queue idleness (the ledger's `idle` phase below),
            # and booking it would misattribute scheduler gaps as data
            # starvation.  So measure, then book after the type is
            # known.  The leader's interval covers get_task + broadcast;
            # non-leader ranks book their broadcast wait inside
            # broadcast_task under the same rule.
            queue_wait_start = time.monotonic()
            task = self._mc.get_task() if self._world.is_leader else None
            task = elastic.broadcast_task(
                task, self._shard_names, self._world, anatomy=self._anatomy
            )
            if (
                self._anatomy is not None
                and self._world.is_leader
                and task.task_id != -1
                and task.type != pb.WAIT
            ):
                self._anatomy.note_phase_seconds(
                    "data_wait", time.monotonic() - queue_wait_start
                )
            if task.task_id == -1 and task.type != pb.WAIT:
                logger.info(
                    "Job complete; rank %d exiting", self._world.rank
                )
                break
            if task.type == pb.WAIT:
                # Worker-side ledger: queue momentarily empty -> idle
                # until the next real task opens a work phase.
                goodput.ledger().transition("idle", cause="wait_task")
                time.sleep(self._wait_sleep_s)
                continue
            spec = faults.fire("worker.task")
            if spec is not None and spec.kind == "crash":
                faults.crash_now(spec)
            try:
                type_name = pb.TaskType.Name(task.type)
            except ValueError:
                type_name = "UNKNOWN"
            goodput.ledger().transition("training", cause="task_start")
            if self._telemetry is not None:
                self._telemetry.begin_task(
                    task.task_id, type_name, task.end - task.start
                )
            # The span closes the worker half of the trace chain: its
            # journal record carries the dispatch-minted trace id (leader
            # ranks — the fixed-shape broadcast drops strings, so
            # non-leader ranks span without one).  Same name+labelset as
            # the Local-mode worker's span: both paths share one
            # histogram family in-process.
            span_fields = dict(task_id=task.task_id, rank=self._world.rank)
            if task.trace_id:
                span_fields["trace_id"] = task.trace_id
            try:
                with obs.span(
                    "worker.task", labels={"type": type_name}, **span_fields
                ):
                    counters = self._process_task(task)
            except Exception as exc:
                logger.error(
                    "Task %d failed on rank %d:\n%s",
                    task.task_id,
                    self._world.rank,
                    traceback.format_exc(),
                )
                if self._world.is_leader:
                    self._mc.report_task_result_best_effort(
                        task.task_id, str(exc) or repr(exc),
                        trace_id=task.trace_id,
                    )
                # A failed collective step likely poisons the world: die and
                # let the pod manager re-form it (reference: Horovod
                # shutdown/re-init on HorovodInternalError).
                raise
            else:
                # The collective step SUCCEEDED on every rank; a lost
                # success report is only an RPC-plane fault and must not
                # escalate into restart-the-world.  The master requeues
                # the unacked task (at-least-once) and the healthy world
                # retrains it.
                if self._world.is_leader:
                    self._mc.report_task_result_best_effort(
                        task.task_id, "", counters, trace_id=task.trace_id
                    )
        self._report_version(force=True)
        self._maybe_checkpoint(force=True)

    # ------------------------------------------------------------------

    def _process_task(self, task) -> dict:
        if task.type == pb.TRAINING:
            return self._process_train_task(task)
        if task.type == pb.EVALUATION:
            return self._process_eval_task(task)
        if task.type == pb.PREDICTION:
            return self._process_eval_task(task, report=False)
        if task.type == pb.TRAIN_END_CALLBACK:
            return self._process_train_end(task)
        raise ValueError(f"Unknown task type {task.type}")

    def _task_records(self, task, mode: str) -> SequentialRecords:
        """One-pass cursor over the task's parsed records (identically on
        every rank; dataset_fn must be deterministic per (task, mode)).
        Streaming, not a list: only the in-flight batch slice is resident
        (data/dataset.SequentialRecords — the eval-memory bound)."""
        reader = self._readers.get(task.type, self._readers[pb.TRAINING])

        def records():
            return reader.read_records(task)

        dataset = self._spec.dataset_fn(
            Dataset.from_generator(records), mode, self._metadata
        )
        return SequentialRecords(dataset)

    def _local_batches(self, task, mode: str):
        """Yield (features, labels, mask, global_real) lockstep batches.

        Two materializations, one contract: the columnar fast path
        (data/columnar.py — reader.read_columns + the model's
        columnar_dataset_fn, batches are row-range VIEWS with zero
        per-record Python) when both sides support it, else the
        per-record dataset path."""
        reader = self._readers.get(task.type, self._readers[pb.TRAINING])
        columnar = materialize_columnar_task(
            reader,
            task,
            getattr(self._spec, "columnar_dataset_fn", None),
            mode,
            self._metadata,
            parse_pool=self._parse_pool,
        )
        if columnar is not None and not self._columnar_logged:
            # e2e tests grep this to prove the vectorized path engaged.
            self._columnar_logged = True
            logger.info(
                "Columnar task path engaged (%s, %d rows, zero per-record "
                "Python)", mode, columnar.n,
            )
        records = None if columnar is not None else self._task_records(task, mode)

        def slice_batch(lo_off, hi_off):
            """(features, labels, n_real) for task-relative rows
            [lo_off, hi_off); empty slices shape from row 0, all-masked."""
            if columnar is not None:
                n_real = max(0, min(hi_off, columnar.n) - lo_off)
                if n_real:
                    features, labels = columnar.slice(lo_off, hi_off)
                else:
                    features, labels = columnar.slice(0, 1)
                return features, labels, n_real
            slice_records = records.slice(lo_off, hi_off)
            batch = _stack(
                slice_records if slice_records else [records.template()]
            )
            features, labels = (
                batch if isinstance(batch, tuple) else (batch, None)
            )
            return features, labels, len(slice_records)

        for lo, hi, global_real in elastic.iter_local_batch_ranges(
            task.start, task.end, self._mb, self._world
        ):
            features, labels, n_real = slice_batch(
                lo - task.start, hi - task.start
            )
            features, mask = shd.pad_batch(features, self._block)
            mask[:n_real] = 1.0
            mask[n_real:] = 0.0
            if labels is not None:
                labels, _ = shd.pad_batch(labels, self._block)
            yield features, labels, mask, global_real

    # Auto-window bounds (used when --train_window_steps=0).  All of a
    # task's batches share one padded shape, so full windows hit a single
    # compiled scan program; the tail (< window batches) reuses the
    # single-step program — exactly two executables total.  Larger windows
    # amortize the per-dispatch host gap (measured on the PS bench:
    # 8 -> 400 steps/dispatch recovers ~25% throughput, BASELINE.md —
    # round 2 defaulted to 8 and silently left that on the table,
    # VERDICT round-2 weak #7), bounded by the task size and a
    # staged-bytes cap so image-scale batches don't OOM the device.
    AUTO_WINDOW_STEPS = 400
    AUTO_WINDOW_BYTES = 1 << 30

    def _grow_explicit_window_to_apply_multiple(self) -> None:
        """An explicit window that is not a multiple of the apply interval
        silently halves (or worse) the promised amortization — grow it and
        say so (auto windows round themselves in _window_candidate)."""
        if (
            self._window_steps
            and self._apply_every > 1
            and self._window_steps % self._apply_every
        ):
            grown = (
                -(-self._window_steps // self._apply_every)
                * self._apply_every
            )
            logger.warning(
                "Dispatch window %d is not a multiple of "
                "sparse_apply_every=%d; growing the window to %d so every "
                "chunk reaches the configured apply interval",
                self._window_steps, self._apply_every, grown,
            )
            self._window_steps = grown

    def _sync_apply_every(self) -> bool:
        """Re-read the trainer's (possibly auto-resolved) apply interval;
        True if it changed.  Called once right after ensure_initialized —
        nothing has compiled yet, so window sizing may still move."""
        resolved = int(
            getattr(self._trainer, "_sparse_apply_every", 1) or 1
        )
        if resolved == self._apply_every:
            return False
        self._apply_every = resolved
        self._grow_explicit_window_to_apply_multiple()
        return True

    def _window_candidate(self, task_batches: int) -> int:
        explicit = self._window_steps
        cand = min(explicit or self.AUTO_WINDOW_STEPS, task_batches)
        if not explicit and self._batch_nbytes:
            cand = min(
                cand, max(1, self.AUTO_WINDOW_BYTES // self._batch_nbytes)
            )
        if self._apply_every > 1:
            if cand > self._apply_every:
                # Auto windows round DOWN to an apply-interval multiple
                # (memory-safe; explicit windows were grown in __init__).
                cand -= cand % self._apply_every
            elif cand < self._apply_every and not self._apply_short_warned:
                # Byte/task caps forced the window below the apply
                # interval: sparse applies now happen every `cand` steps.
                # Say so — silently shortening the configured interval is
                # exactly what the explicit-window path warns about.
                self._apply_short_warned = True
                logger.warning(
                    "Auto dispatch window %d is below sparse_apply_every="
                    "%d (task size or the %d MB staged-bytes cap): sparse "
                    "applies run every %d steps instead",
                    cand, self._apply_every,
                    self.AUTO_WINDOW_BYTES >> 20, cand,
                )
        return max(1, cand)

    def _process_train_task(self, task) -> dict:
        batch_count = 0
        record_count = 0
        last_loss = None
        pending: list = []
        pending_real = 0
        # Effective dispatch window: a window larger than the task would
        # never fill, silently demoting EVERY batch to the per-step path
        # — the opposite of what a large --train_window_steps asks for.
        # The batch count mirrors iter_local_batch_ranges (per-rank mb x
        # world, NOT the device-padded block).  The window RATCHETS
        # upward: it grows to the largest min(configured, task_batches)
        # seen, so a small first task (ragged shard head) can't pin the
        # whole job to per-step, while tasks smaller than the ratchet use
        # the per-step program instead of compiling one-off scan sizes —
        # executables stay bounded by the few distinct upward steps.
        global_batch = self._mb * self._world.world_size
        task_batches = max(1, -(-(task.end - task.start) // global_batch))
        candidate = self._window_candidate(task_batches)
        if self._effective_window is None or candidate > self._effective_window:
            self._effective_window = candidate
            if self._world.is_leader:
                logger.info(
                    "Dispatch window -> %d steps (%s; task of %d records "
                    "yields %d global batches)",
                    candidate,
                    (
                        f"--train_window_steps={self._window_steps}"
                        if self._window_steps
                        else "auto"
                    ),
                    task.end - task.start,
                    task_batches,
                )
        window_steps = self._effective_window
        # Async mode: staging books as overlap credit while a dispatch
        # is outstanding (double-buffering — window N+1 stages while N
        # executes); sync mode books the classic exclusive phase.
        staging = (
            StagingPipeline(self._anatomy, self._pipeline.dispatch_depth)
            if self._pipeline.is_async
            else None
        )
        # Prefetcher overlap already credited to the anatomy (cumulative
        # marker: overlap_s on the prefetcher only ever grows).
        overlap_booked = [0.0]

        def stage_call(fn, *args):
            if staging is not None:
                return staging.stage(fn, *args)
            with self._anat_phase("stage"):
                return fn(*args)

        def flush():
            nonlocal batch_count, record_count, pending, pending_real, last_loss
            if not pending:
                return
            if self._profiler is not None:
                # Pre-dispatch: a K-step fused window traces whole (it
                # cannot stop mid-device-call); boundaries round outward.
                self._profiler.before_steps(
                    self._trainer.step, len(pending)
                )
            flush_start = time.monotonic()
            if len(pending) == window_steps and hasattr(
                self._trainer, "stage_window"
            ):
                window = stage_call(self._trainer.stage_window, pending)
                with self._anat_dispatch(len(pending), pending_real):
                    losses = self._trainer.train_window(window)
                if staging is not None:
                    staging.note_dispatched()
                last_loss = losses[-1]
            else:
                for i, staged_batch in enumerate(pending):
                    staged = stage_call(
                        self._trainer.stage_batch, *staged_batch
                    )
                    # Real-record count is per-flush, not per-step:
                    # credit it once so the window's examples are exact.
                    with self._anat_dispatch(1, pending_real if i == 0 else 0):
                        last_loss = self._trainer.train_step_staged(staged)
                    if staging is not None:
                        staging.note_dispatched()
            with self._anat_phase("bookkeep"):
                if self._telemetry is not None:
                    # One telemetry sample per dispatch (not per step):
                    # the flush's mean step time + real records, feeding
                    # the heartbeat snapshot's percentiles + examples/s.
                    self._telemetry.record_steps(
                        len(pending),
                        time.monotonic() - flush_start,
                        records=pending_real,
                    )
                batch_count += len(pending)
                record_count += pending_real
                pending, pending_real = [], 0
                if self._profiler is not None:
                    self._profiler.after_steps(self._trainer.step)
                self._report_version_if_due()
                self._maybe_checkpoint()
            if self._anatomy is not None:
                if prefetcher is not None:
                    # Producer time hidden behind this flush's device
                    # work: credit the delta since the last flush so
                    # each anatomy window carries its own overlap.
                    produced = prefetcher.overlap_s
                    if produced > overlap_booked[0]:
                        self._anatomy.note_overlap_seconds(
                            produced - overlap_booked[0]
                        )
                        overlap_booked[0] = produced
                # One anatomy window per dispatch flush: the unit the
                # heartbeat snapshot summarizes — and one aggregate
                # child span per phase under the open worker.task span
                # (docs/observability.md "Distributed tracing").
                window = self._anatomy.close_window()
                if window:
                    tracing.tracer().record_window_spans(window)

        batches = self._local_batches(task, Mode.TRAINING)
        prefetcher = None
        if self._pipeline.is_async:
            # Bounded background read-ahead: parse + batch assembly for
            # item N+1..N+k runs off the critical path while N's window
            # dispatches.  data_wait below then measures only the time
            # the step loop truly BLOCKED; the producer time it hid is
            # credited as overlap at each flush.
            prefetcher = Prefetcher(
                batches, max_inflight=self._pipeline.max_inflight
            )
            batches = prefetcher
        try:
            while True:
                # Host data wait: read + parse + batch assembly (and
                # padding) happen inside the generator (or behind the
                # prefetcher) — the starvation signal the step anatomy
                # exists to expose.
                with self._anat_phase("data_wait"):
                    item = next(batches, None)
                if item is None:
                    break
                features, labels, mask, global_real = item
                if self._trainer.state is None:
                    # First touch: model init + eval_shape + jit build is
                    # compile-plane time, not execute.
                    with self._anat_phase("compile"):
                        self._trainer.ensure_initialized(features)
                else:
                    self._trainer.ensure_initialized(features)
                if self._batch_nbytes is None:
                    # One-time refinement of the window from the real
                    # staged-batch size AND the trainer's now-resolved
                    # apply interval (--sparse_apply_every=auto resolves
                    # at init), before anything has compiled.  Byte
                    # refinement only shrinks; an auto-resolved interval
                    # may also GROW an explicit window to a chunk
                    # multiple.
                    apply_changed = self._sync_apply_every()
                    self._batch_nbytes = sum(
                        np.asarray(leaf).nbytes
                        for leaf in jax.tree.leaves((features, labels, mask))
                    )
                    refined = self._window_candidate(task_batches)
                    if refined < window_steps or (
                        apply_changed and refined != window_steps
                    ):
                        if self._world.is_leader:
                            logger.info(
                                "Dispatch window %d -> %d (staged batch is "
                                "%.1f MB, %d MB auto cap; "
                                "sparse_apply_every=%d)",
                                window_steps, refined,
                                self._batch_nbytes / 2**20,
                                self.AUTO_WINDOW_BYTES >> 20,
                                self._apply_every,
                            )
                        window_steps = refined
                        self._effective_window = refined
                pending.append((features, labels, mask))
                pending_real += global_real
                if len(pending) == window_steps:
                    flush()
            flush()
        finally:
            # Task boundary (normal end, checkpoint cadence handled in
            # flush, or an exception about to re-form the world): drain
            # synchronously so no stale in-flight batch ever crosses a
            # rendezvous generation.
            if prefetcher is not None:
                prefetcher.close()
            if staging is not None:
                staging.drain()
        if last_loss is not None and self._world.is_leader:
            logger.info(
                "task %d done: step=%d loss=%.5f (%d global batches)",
                task.task_id,
                self._trainer.step,
                float(np.asarray(last_loss)),
                batch_count,
            )
        self._report_version()
        counters = {
            TaskExecCounterKey.BATCH_COUNT: batch_count,
            TaskExecCounterKey.RECORD_COUNT: record_count,
        }
        consume_oov = getattr(self._trainer, "consume_oov_count", None)
        if consume_oov is not None:
            # Task boundary — the one place a device sync is already paid
            # (the task-done log above materialized the last loss).
            oov = consume_oov()
            if oov:
                counters[TaskExecCounterKey.OOV_LOOKUP_COUNT] = oov
        return counters

    # Leader-side eval outputs flush cadence: bounds the accumulated
    # (outputs, labels) to EVAL_REPORT_BATCHES x global-batch regardless
    # of task size (the master's evaluation service appends each report
    # to the round and concatenates at finalize, so chunked reports are
    # semantics-identical — metric fns still see the full eval set once,
    # which is the metric contract and the master-side memory floor).
    EVAL_REPORT_BATCHES = 32

    def _process_eval_task(self, task, report: bool = True) -> dict:
        outputs_list = []
        labels_list = []
        batch_count = 0

        def flush():
            if not outputs_list:
                return
            self._mc.report_evaluation_metrics(
                model_version=task.model_version,
                model_outputs=concat_named(outputs_list),
                labels=concat_named(labels_list),
                task_id=task.task_id,
            )
            outputs_list.clear()
            labels_list.clear()

        for features, labels, mask, global_real in self._local_batches(
            task, Mode.EVALUATION
        ):
            # Both gathers are collectives — every rank must execute them.
            outputs = self._trainer.eval_step_local(features)
            global_labels = shd.gather_to_host(
                shd.assemble_global_batch(labels, self._trainer.mesh)
            )
            batch_count += 1
            if not (report and self._world.is_leader):
                continue
            # Strip per-rank padding: rank r's real rows are a prefix of its
            # block-row slice (deterministically reconstructible).
            counts = elastic.per_rank_real_counts(
                global_real, self._mb, self._world.world_size
            )
            keep = np.concatenate(
                [
                    np.arange(r * self._block, r * self._block + count)
                    for r, count in enumerate(counts)
                ]
            ).astype(np.int64)
            outputs_list.append(
                {
                    name: arr[keep]
                    for name, arr in named_arrays(outputs, "output").items()
                }
            )
            labels_list.append(
                {name: arr[keep] for name, arr in named_arrays(global_labels, "").items()}
            )
            if len(outputs_list) >= self.EVAL_REPORT_BATCHES:
                flush()
        flush()
        return {TaskExecCounterKey.BATCH_COUNT: batch_count}

    def _process_train_end(self, task) -> dict:
        self._maybe_checkpoint(force=True)
        if self._world.is_leader and self._spec.callbacks is not None:
            for callback in self._spec.callbacks() or []:
                callback(self)
        return {}

    # ------------------------------------------------------------------

    def _report_version_if_due(self):
        """Window-safe cadence: steps advance in jumps of WINDOW, so the
        trigger is a delta since the last report, not an exact multiple."""
        if self._trainer.step - self._last_reported_version >= self._report_every:
            self._report_version()

    def _report_version(self, force: bool = False):
        if not self._world.is_leader:
            return
        step = self._trainer.step
        if force or step > self._last_reported_version:
            self._mc.report_version(step)
            self._last_reported_version = step

    def _maybe_checkpoint(self, force: bool = False):
        """Every rank computes the save decision identically and joins the
        host-gather (a collective for sharded tables); only rank 0 writes.
        Delta-based cadence (steps can jump by WINDOW at a time)."""
        if self._ckpt is None or self._trainer.state is None:
            return
        step = self._trainer.step
        due = force or (
            self._ckpt_steps and step - self._last_ckpt_step >= self._ckpt_steps
        )
        if due and step > 0 and step != self._last_ckpt_step:
            # Goodput: the save window (including the host gather every
            # rank joins) is checkpoint_save, not training.  The tracing
            # span nests under worker.task when the save fired from a
            # mid-task cadence check (root-less at job end).
            with goodput.ledger().phase("checkpoint_save", cause="cadence"):
                with tracing.span(
                    "checkpoint.save", rank=self._world.rank, step=step
                ):
                    if self._sharded_ckpt:
                        # Collective: every rank writes its own shards.
                        self._trainer.save_checkpoint(self._ckpt, step)
                    else:
                        host_state = self._trainer.state_to_host()
                        if self._world.is_leader:
                            self._ckpt.save(host_state, step)
            self._last_ckpt_step = step
