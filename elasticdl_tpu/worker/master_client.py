"""Worker-side wrapper around the Master gRPC stub.

Parity: elasticdl/python/worker/master_client.py in the reference.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.grpc_utils import build_channel
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.proto.service import MasterStub


class MasterClient:
    def __init__(self, addr: str, worker_id: int):
        self._channel = build_channel(addr)
        self._stub = MasterStub(self._channel)
        self._worker_id = worker_id

    @property
    def worker_id(self) -> int:
        return self._worker_id

    def get_task(self, task_type: int = pb.TRAINING) -> pb.Task:
        request = pb.GetTaskRequest(worker_id=self._worker_id, task_type=task_type)
        return self._stub.get_task(request).task

    def report_task_result(
        self, task_id: int, err_message: str = "", exec_counters: Optional[Dict[str, int]] = None
    ):
        request = pb.ReportTaskResultRequest(
            task_id=task_id, err_message=err_message, worker_id=self._worker_id
        )
        if exec_counters:
            for key, value in exec_counters.items():
                request.exec_counters[key] = int(value)
        self._stub.report_task_result(request)

    def report_evaluation_metrics(self, model_version: int, model_outputs,
                                  labels, task_id: int = 0):
        """`model_outputs` is {name: array}; `labels` is an array or a
        {name: array} dict (multi-label models).  `task_id` scopes the
        chunked reports to their EVALUATION task (see the proto note)."""
        request = pb.ReportEvaluationMetricsRequest(
            worker_id=self._worker_id, model_version=model_version,
            task_id=task_id,
        )
        for name, array in model_outputs.items():
            request.model_outputs.append(tensor_utils.ndarray_to_pb(array, name=name))
        if not isinstance(labels, dict):
            labels = {"": np.asarray(labels)}
        for name, array in labels.items():
            request.labels.append(
                tensor_utils.ndarray_to_pb(np.asarray(array), name=name)
            )
        self._stub.report_evaluation_metrics(request)

    def report_version(self, model_version: int):
        self._stub.report_version(
            pb.ReportVersionRequest(
                model_version=model_version, worker_id=self._worker_id
            )
        )

    def get_comm_rank(self, host: str = "") -> pb.GetCommRankResponse:
        return self._stub.get_comm_rank(
            pb.GetCommRankRequest(worker_id=self._worker_id, host=host)
        )

    def report_worker_liveness(self, host: str, rendezvous_id: int) -> bool:
        response = self._stub.report_worker_liveness(
            pb.ReportWorkerLivenessRequest(
                worker_id=self._worker_id, host=host, rendezvous_id=rendezvous_id
            )
        )
        return response.should_reset

    def get_shard_checkpoint(self) -> str:
        return self._stub.get_shard_checkpoint(pb.ShardCheckpointRequest()).content

    def close(self):
        self._channel.close()
