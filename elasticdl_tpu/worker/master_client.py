"""Worker-side wrapper around the Master gRPC stub.

Parity: elasticdl/python/worker/master_client.py in the reference, plus the
transient-failure plane: every RPC carries an explicit deadline, and
idempotent RPCs (reads and naturally-deduplicated reports) retry transient
failures with backoff so workers ride through a master restart instead of
dying and triggering a slice-wide world re-formation.

Idempotency per RPC (the retry wrapper never guesses — see
common/grpc_utils.py):

- `get_task`           retried: a popped-but-unacked task is recovered by
                       the master's timeout/churn paths (at-least-once).
- `get_comm_rank`, `report_worker_liveness`, `get_shard_checkpoint`
                       retried: pure reads / latest-wins liveness.
- `report_version`     retried: the master folds it with max().
- `report_task_result` NOT retried: a duplicate success report for a
                       task id the master already closed logs as
                       unknown-task; a duplicate *failure* report would
                       double-charge the task's retry budget.
- `report_evaluation_metrics`
                       NOT retried: reports append to the round's staged
                       chunks — a duplicate would double-count rows.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Optional

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.constants import RPC
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.grpc_utils import (
    IDEMPOTENT_POLICY,
    NON_IDEMPOTENT_POLICY,
    RetryPolicy,
    RetryStats,
    build_channel,
    call_with_retry,
    trace_metadata,
)
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.proto.service import MasterStub

logger = get_logger("worker.master_client")


class MasterClient:
    def __init__(
        self,
        addr: str,
        worker_id: int,
        retry_policy: Optional[RetryPolicy] = None,
        no_retry_policy: Optional[RetryPolicy] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        self._channel = build_channel(addr)
        self._stub = MasterStub(self._channel)
        self._worker_id = worker_id
        self._retry_policy = retry_policy or IDEMPOTENT_POLICY
        self._no_retry_policy = no_retry_policy or NON_IDEMPOTENT_POLICY
        self._sleep = sleep
        #: Transient-failure observability: how often this worker had to
        #: retry (chaos tests assert workers actually rode through the
        #: outage instead of never noticing it).
        self.retry_stats = RetryStats()

    @property
    def worker_id(self) -> int:
        return self._worker_id

    # ------------------------------------------------------------------

    def _call(self, method: str, request, policy: RetryPolicy, metadata=None):
        return call_with_retry(
            getattr(self._stub, method),
            request,
            method=method,
            policy=policy,
            stats=self.retry_stats,
            sleep=self._sleep,
            # Per-worker jitter salt: deterministic per worker, but the
            # fleet's backoff schedules are decorrelated.
            seed=str(self._worker_id),
            metadata=metadata,
        )

    def _call_idempotent(self, method: str, request):
        return self._call(method, request, self._retry_policy)

    def _call_once(self, method: str, request, timeout_s: Optional[float] = None,
                   metadata=None):
        policy = self._no_retry_policy
        if timeout_s is not None and timeout_s != policy.timeout_s:
            # Override only the deadline; an injected no_retry_policy
            # keeps its other fields.
            policy = dataclasses.replace(policy, timeout_s=timeout_s)
        return self._call(method, request, policy, metadata=metadata)

    # ------------------------------------------------------------------

    def get_task(self, task_type: int = pb.TRAINING) -> pb.Task:
        """The client half of dispatch is a trace span: the span id is
        minted BEFORE the call and rides gRPC metadata (the servicer's
        `rpc.get_task` span parents under it), and the span journals
        after the fact once the response reveals the trace id — WAIT
        polls and job-complete answers carry no trace and journal no
        span (a poll loop must not flood the journal)."""
        from elasticdl_tpu.obs import tracing

        request = pb.GetTaskRequest(worker_id=self._worker_id, task_type=task_type)
        span_id = tracing.tracer().mint_span_id()
        start_ts = time.time()
        start = time.monotonic()
        task = self._call(
            "get_task",
            request,
            self._retry_policy,
            metadata=trace_metadata("", span_id=span_id),
        ).task
        if task.trace_id:
            tracing.tracer().record_span(
                "worker.get_task",
                start_ts=start_ts,
                duration_s=time.monotonic() - start,
                trace_id=task.trace_id,
                # Root convention: the task root's span id IS the trace
                # id, so the client can parent under it without ever
                # having seen the root span.
                parent_id=task.trace_id,
                span_id=span_id,
                worker_id=self._worker_id,
            )
        return task

    def report_task_result(
        self, task_id: int, err_message: str = "",
        exec_counters: Optional[Dict[str, int]] = None, trace_id: str = "",
    ):
        """`trace_id` (the dispatch-minted id from Task.trace_id) rides
        gRPC metadata back to the master, closing the cross-process
        journal chain (grpc_utils.TRACE_METADATA_KEY)."""
        from elasticdl_tpu.obs import tracing

        request = pb.ReportTaskResultRequest(
            task_id=task_id, err_message=err_message, worker_id=self._worker_id
        )
        if exec_counters:
            for key, value in exec_counters.items():
                request.exec_counters[key] = int(value)
        if not trace_id:
            self._call_once("report_task_result", request)
            return
        # Traced report: the client span parents under the task root
        # (the worker.task span has already closed by report time), and
        # its span id rides the metadata so the master's
        # rpc.report_task_result handler span nests under it.
        with tracing.tracer().span(
            "worker.report_task",
            trace_id=trace_id,
            parent_id=trace_id,
            worker_id=self._worker_id,
            task_id=task_id,
        ) as report_span:
            self._call_once(
                "report_task_result",
                request,
                metadata=trace_metadata(trace_id, span_id=report_span.span_id),
            )

    def report_task_result_best_effort(
        self, task_id: int, err_message: str = "",
        exec_counters: Optional[Dict[str, int]] = None, trace_id: str = "",
    ) -> bool:
        """Result report where delivery failure is data, not an error:
        result reports are non-idempotent and never retried, and an
        unreported task is recovered by the master's timeout/churn paths
        (at-least-once) — so a report lost to a master outage must not
        crash the worker or poison the world.  True when delivered."""
        try:
            self.report_task_result(
                task_id, err_message, exec_counters, trace_id=trace_id
            )
            return True
        except Exception:
            logger.warning(
                "Could not report task %d %s (master unreachable?); the "
                "master will requeue the task (at-least-once)",
                task_id, "failure" if err_message else "success",
            )
            return False

    def report_evaluation_metrics(self, model_version: int, model_outputs,
                                  labels, task_id: int = 0):
        """`model_outputs` is {name: array}; `labels` is an array or a
        {name: array} dict (multi-label models).  `task_id` scopes the
        chunked reports to their EVALUATION task (see the proto note)."""
        request = pb.ReportEvaluationMetricsRequest(
            worker_id=self._worker_id, model_version=model_version,
            task_id=task_id,
        )
        for name, array in model_outputs.items():
            request.model_outputs.append(tensor_utils.ndarray_to_pb(array, name=name))
        if not isinstance(labels, dict):
            labels = {"": np.asarray(labels)}
        for name, array in labels.items():
            request.labels.append(
                tensor_utils.ndarray_to_pb(np.asarray(array), name=name)
            )
        self._call_once(
            "report_evaluation_metrics",
            request,
            timeout_s=RPC.EVAL_REPORT_DEADLINE_S,
        )

    def report_version(self, model_version: int):
        self._call_idempotent(
            "report_version",
            pb.ReportVersionRequest(
                model_version=model_version, worker_id=self._worker_id
            ),
        )

    def get_comm_rank(self, host: str = "") -> pb.GetCommRankResponse:
        return self._call_idempotent(
            "get_comm_rank",
            pb.GetCommRankRequest(worker_id=self._worker_id, host=host),
        )

    def report_worker_liveness(
        self, host: str, rendezvous_id: int, telemetry_json: str = ""
    ) -> bool:
        """`telemetry_json` is the worker's bounded telemetry snapshot
        (obs/telemetry.py) — the heartbeat doubles as the telemetry
        carrier, so per-worker observability costs zero new RPCs."""
        response = self._call_idempotent(
            "report_worker_liveness",
            pb.ReportWorkerLivenessRequest(
                worker_id=self._worker_id, host=host,
                rendezvous_id=rendezvous_id, telemetry_json=telemetry_json,
            ),
        )
        return response.should_reset

    def get_shard_checkpoint(self) -> str:
        return self._call_idempotent(
            "get_shard_checkpoint", pb.ShardCheckpointRequest()
        ).content

    def close(self):
        self._channel.close()
