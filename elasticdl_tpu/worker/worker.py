"""The worker runtime: task loop around the jitted step.

Parity: elasticdl/python/worker/worker.py in the reference — `Worker.run()`
pulls tasks from the master, builds the per-task dataset, runs the
minibatch loop, and reports results; evaluation tasks run forward-only and
ship outputs/labels to the master for aggregation.
"""

from __future__ import annotations

import contextlib
import time
import traceback
from typing import Optional

import numpy as np

from elasticdl_tpu import obs
from elasticdl_tpu.common import faults
from elasticdl_tpu.common.constants import Mode, TaskExecCounterKey
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.model_utils import ModelSpec
from elasticdl_tpu.data.pipeline import PipelineConfig, Prefetcher
from elasticdl_tpu.data.task_data_service import TaskDataService
from elasticdl_tpu.obs import goodput, quality
from elasticdl_tpu.proto import elasticdl_pb2 as pb
from elasticdl_tpu.worker.trainer import Trainer

logger = get_logger("worker.worker")


class Worker:
    def __init__(
        self,
        master_client,
        model_spec: ModelSpec,
        data_reader,
        minibatch_size: int,
        trainer: Optional[Trainer] = None,
        report_version_every_steps: int = 20,
        wait_sleep_s: float = 0.5,
        max_consecutive_task_failures: int = 10,
        validation_data_reader=None,
        prediction_data_reader=None,
        profiler=None,
        anatomy=None,
        pipeline: Optional[PipelineConfig] = None,
    ):
        self._mc = master_client
        self._spec = model_spec
        self._minibatch_size = minibatch_size
        self._task_data_service = TaskDataService(
            data_reader, model_spec.dataset_fn
        )
        # Evaluation/prediction tasks read from their own data source when
        # one is configured (shard names address a different dataset).
        self._eval_data_service = (
            TaskDataService(validation_data_reader, model_spec.dataset_fn)
            if validation_data_reader is not None
            else self._task_data_service
        )
        self._predict_data_service = (
            TaskDataService(prediction_data_reader, model_spec.dataset_fn)
            if prediction_data_reader is not None
            else self._task_data_service
        )
        self._trainer = trainer or Trainer(
            model=model_spec.build_model(),
            loss_fn=model_spec.loss,
            optimizer=model_spec.optimizer(),
        )
        self._report_every = report_version_every_steps
        self._wait_sleep_s = wait_sleep_s
        self._max_consecutive_failures = max_consecutive_task_failures
        self._last_reported_version = 0
        self._profiler = profiler
        # Step-anatomy ledger (obs/stepstats.StepAnatomy, optional):
        # host-clock decomposition of the train loop into data_wait /
        # compile / execute / bookkeep sub-phases.
        self._anatomy = anatomy
        if anatomy is not None and hasattr(
            self._trainer, "jitted_entrypoints"
        ):
            anatomy.watch_jits(self._trainer.jitted_entrypoints)
        # Async staging engine (data/pipeline.py): Local mode fuses
        # staging into train_step, so async here means bounded
        # background prefetch — parse/batching for item N+1 runs while
        # step N dispatches, with the hidden producer time credited as
        # anatomy overlap.  Sync (default) is the classic serial loop.
        self._pipeline = pipeline or PipelineConfig()

    def _anat_phase(self, name: str):
        if self._anatomy is None:
            return contextlib.nullcontext()
        return self._anatomy.phase(name)

    @property
    def trainer(self) -> Trainer:
        return self._trainer

    # ------------------------------------------------------------------

    def run(self):
        """Main loop: pull tasks until the master says the job is done."""
        try:
            self._run_inner()
        finally:
            # In finally: an aborting worker must still flush an in-flight
            # profiler trace — it's most needed exactly then.
            if self._profiler is not None:
                self._profiler.stop()

    def _run_inner(self):
        consecutive_failures = 0
        while True:
            task = self._mc.get_task()
            if task.task_id == -1 and task.type != pb.WAIT:
                logger.info("Job complete; worker %d exiting", self._mc.worker_id)
                break
            if task.type == pb.WAIT:
                # Ledger: nothing to do right now — idle, not training
                # (in Local mode this is the same process-wide ledger the
                # master hooks feed; the phases agree by construction).
                goodput.ledger().transition("idle", cause="wait_task")
                time.sleep(self._wait_sleep_s)
                continue
            spec = faults.fire("worker.task")
            if spec is not None and spec.kind == "crash":
                faults.crash_now(spec)
            try:
                counters = self._process_task(task)
            except Exception as exc:
                logger.error("Task %d failed:\n%s", task.task_id, traceback.format_exc())
                self._mc.report_task_result_best_effort(
                    task.task_id, str(exc) or repr(exc),
                    trace_id=task.trace_id,
                )
                consecutive_failures += 1
                if consecutive_failures >= self._max_consecutive_failures:
                    raise RuntimeError(
                        f"{consecutive_failures} consecutive task failures; "
                        "worker aborting"
                    ) from exc
            else:
                # The task itself succeeded — a lost SUCCESS report must
                # not morph into a failure report (it would requeue
                # already-trained records AND double-charge the task's
                # retry budget).
                self._mc.report_task_result_best_effort(
                    task.task_id, "", counters, trace_id=task.trace_id
                )
                consecutive_failures = 0
        # Final version report so master-side services see the last step.
        self._report_version(force=True)

    # ------------------------------------------------------------------

    def _process_task(self, task) -> dict:
        try:
            type_name = pb.TaskType.Name(task.type)
        except ValueError:
            type_name = "UNKNOWN"
        # Span: per-task worker-side latency histogram (bounded `type`
        # label) + a journal record carrying the unbounded task id and the
        # dispatch-minted trace id (the worker half of the trace chain).
        span_fields = dict(task_id=task.task_id)
        if task.trace_id:
            span_fields["trace_id"] = task.trace_id
        with obs.span(
            "worker.task", labels={"type": type_name}, **span_fields
        ):
            if task.type == pb.TRAINING:
                return self._process_train_task(task)
            if task.type == pb.EVALUATION:
                return self._process_eval_task(task)
            if task.type == pb.PREDICTION:
                return self._process_predict_task(task)
            if task.type == pb.TRAIN_END_CALLBACK:
                return self._process_train_end(task)
            raise ValueError(f"Unknown task type {task.type}")

    def _get_batches(self, task, mode: str):
        # The user's dataset_fn parses/shuffles records; the worker applies
        # the job-level minibatch batching (reference worker behavior).
        service = {
            Mode.TRAINING: self._task_data_service,
            Mode.EVALUATION: self._eval_data_service,
            Mode.PREDICTION: self._predict_data_service,
        }[mode]
        dataset = service.get_dataset(task, mode)
        return dataset.batch(self._minibatch_size)

    def _process_train_task(self, task) -> dict:
        batch_count = 0
        record_count = 0
        last_loss = None
        prefetcher = None
        if self._pipeline.is_async:
            batches = self._task_data_service.get_batches(
                task, Mode.TRAINING, self._minibatch_size,
                lookahead=self._pipeline.max_inflight,
            )
            if isinstance(batches, Prefetcher):
                prefetcher = batches
        else:
            batches = iter(self._get_batches(task, Mode.TRAINING))
        try:
            while True:
                # Host data wait: record parse + batching live in the
                # iterator (step anatomy's starvation signal); behind a
                # prefetcher this measures only true blocked time.
                with self._anat_phase("data_wait"):
                    batch = next(batches, None)
                if batch is None:
                    break
                features, labels = batch
                spec = faults.fire("worker.step")
                if spec is not None and spec.kind == "crash":
                    faults.crash_now(spec)
                # Train-side skew sketch (host-side, pre-staging host
                # arrays — never a device read): no-op until
                # --quality_drift_bins enables a monitor.
                quality.note_train_batch(features)
                if self._profiler is not None:
                    self._profiler.before_steps(self._trainer.step)
                n = _batch_size_of(features)
                if self._anatomy is not None:
                    # One dispatch per batch in Local mode (staging is
                    # fused into train_step; compile-vs-execute split
                    # comes from the trainer's watched jit cache).
                    with self._anatomy.dispatch(1, n):
                        last_loss = self._trainer.train_step(features, labels)
                else:
                    last_loss = self._trainer.train_step(features, labels)
                batch_count += 1
                record_count += n
                with self._anat_phase("bookkeep"):
                    if self._profiler is not None:
                        self._profiler.after_steps(self._trainer.step)
                    if self._trainer.step % self._report_every == 0:
                        self._report_version()
        finally:
            # Task boundary (or an exception): drain the read-ahead so
            # no stale in-flight batch survives into the next task.
            if prefetcher is not None:
                if self._anatomy is not None:
                    self._anatomy.note_overlap_seconds(prefetcher.overlap_s)
                prefetcher.close()
        if self._anatomy is not None:
            # One anatomy window per task in Local mode — and since this
            # path has no telemetry heartbeat to carry it, journal the
            # cumulative anatomy here (the process journal: shared with
            # the master in-process in Local mode, the worker's own
            # events_worker_N.jsonl in subprocess runs).  The window's
            # phases also become aggregate child spans of the open
            # worker.task span (obs/tracing.py).
            from elasticdl_tpu.obs import stepstats, tracing

            window = self._anatomy.close_window()
            if window:
                tracing.tracer().record_window_spans(window)
            stepstats.journal_anatomy(
                self._anatomy.worker_id, self._anatomy.snapshot()
            )
        if last_loss is not None:
            logger.info(
                "task %d done: step=%d loss=%.5f (%d batches)",
                task.task_id,
                self._trainer.step,
                float(last_loss),
                batch_count,
            )
        self._report_version()
        return {
            TaskExecCounterKey.BATCH_COUNT: batch_count,
            TaskExecCounterKey.RECORD_COUNT: record_count,
        }

    def _process_eval_task(self, task) -> dict:
        dataset = self._get_batches(task, Mode.EVALUATION)
        outputs_list = []
        labels_list = []
        batch_count = 0
        for features, labels in dataset:
            outputs = self._trainer.eval_step(features)
            outputs_list.append(named_arrays(outputs, "output"))
            labels_list.append(named_arrays(labels, ""))
            batch_count += 1
        if outputs_list:
            # Report under the round's version so the master aggregates all
            # of a round's tasks together regardless of worker step skew.
            self._mc.report_evaluation_metrics(
                model_version=task.model_version,
                model_outputs=concat_named(outputs_list),
                labels=concat_named(labels_list),
                # Reports stage per task on the master and promote when
                # the task completes (retry-safe chunked-report protocol).
                task_id=task.task_id,
            )
        return {TaskExecCounterKey.BATCH_COUNT: batch_count}

    def _process_predict_task(self, task) -> dict:
        dataset = self._get_batches(task, Mode.PREDICTION)
        batch_count = 0
        for batch in dataset:
            features = batch[0] if isinstance(batch, tuple) else batch
            self._trainer.eval_step(features)
            batch_count += 1
        return {TaskExecCounterKey.BATCH_COUNT: batch_count}

    def _process_train_end(self, task) -> dict:
        if self._spec.callbacks is not None:
            for callback in self._spec.callbacks() or []:
                callback(self)
        return {}

    def _report_version(self, force: bool = False):
        step = self._trainer.step
        if force or step > self._last_reported_version:
            self._mc.report_version(step)
            self._last_reported_version = step


def named_arrays(tree, default_name: str = "output") -> dict:
    """Flatten a model-output/label pytree into {name: np.ndarray}.

    Dicts (the multi-output contract) keep their keys, nesting joined with
    '/'; a bare tensor maps to `default_name`.  The reference aggregates
    arbitrary named outputs/labels through Keras metrics (SURVEY.md §3.5).
    """
    if isinstance(tree, dict):
        flat = {}
        for key, value in tree.items():
            if isinstance(value, dict):
                for sub, arr in named_arrays(value, default_name).items():
                    flat[f"{key}/{sub}"] = arr
            else:
                flat[str(key)] = np.asarray(value)
        return flat
    return {default_name: np.asarray(tree)}


def concat_named(batches: list) -> dict:
    """Concatenate a list of {name: array} dicts along axis 0."""
    names = batches[0].keys()
    return {name: np.concatenate([b[name] for b in batches]) for name in names}


def _batch_size_of(features) -> int:
    if isinstance(features, dict):
        features = next(iter(features.values()))
    if isinstance(features, (tuple, list)):
        features = features[0]
    return int(np.asarray(features).shape[0])
