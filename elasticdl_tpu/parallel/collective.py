"""Fault-tolerant collective ops with status results.

Parity: the reference's CollectiveCommunicator (FTlib wrapper,
collective_ops/communicator.py — SURVEY.md §2.1): `allreduce/broadcast/
barrier` return SUCCEEDED/FAILED instead of raising, so the training loop
can react (retry, trigger communicator re-formation) rather than crash.

TPU-native: the data-plane collective is a jitted XLA op over the current
mesh; what can *fail* is the distributed runtime when a peer process dies
mid-collective.  We catch that and surface FAILED — the elastic layer
(parallel/elastic.py) then re-forms the mesh over survivors, exactly where
the reference re-forms its NCCL ring.
"""

from __future__ import annotations

import enum
from typing import Any, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.parallel import sharding as shd

logger = get_logger("parallel.collective")


class CollectiveResult(enum.Enum):
    SUCCEEDED = 0
    FAILED = 1


class CollectiveCommunicator:
    """Mesh-wide allreduce/broadcast/barrier that reports failure as status.

    `mesh` may span multiple processes (jax.distributed world); single
    process with N local devices behaves identically (the test harness).
    """

    def __init__(self, mesh):
        self._mesh = mesh
        self._jit_cache: dict = {}

    @property
    def mesh(self):
        return self._mesh

    def _jitted(self, name, fn, in_shardings, out_shardings):
        import jax

        key = name
        if key not in self._jit_cache:
            self._jit_cache[key] = jax.jit(
                fn, in_shardings=in_shardings, out_shardings=out_shardings
            )
        return self._jit_cache[key]

    # ------------------------------------------------------------------

    def allreduce(self, data: Any, op: str = "MEAN"):
        """Mean/sum of a host array over the mesh's device set.

        Returns (CollectiveResult, result_or_None).  Data is replicated in;
        with every participant contributing via their sharded batch the
        reduction happens inside the train step — this entry point is the
        *control-plane* collective (metric sync, param averaging on
        re-formation), mirroring the reference's usage.
        """
        import jax
        import jax.numpy as jnp

        try:
            repl = shd.replicated(self._mesh)
            batch = shd.batch_sharded(self._mesh)
            n = shd.data_axis_size(self._mesh)

            def reduce_fn(x):  # x: (n, ...) sharded over data
                s = jnp.sum(x, axis=0)
                return s / n if op == "MEAN" else s

            fn = self._jitted(f"allreduce_{op}", reduce_fn, (batch,), repl)
            # Each process contributes copies for its local devices only
            # (a host-global device_put cannot target non-addressable
            # devices in a multi-process mesh).
            local_rows = max(1, n // jax.process_count())
            local = np.broadcast_to(
                np.asarray(data)[None], (local_rows,) + np.asarray(data).shape
            )
            tiled = shd.assemble_global_batch(np.ascontiguousarray(local), self._mesh)
            return CollectiveResult.SUCCEEDED, np.asarray(fn(tiled))
        except Exception as exc:  # runtime/peer failure → status, not crash
            logger.error("allreduce failed: %s", exc)
            return CollectiveResult.FAILED, None

    def broadcast(self, data: Optional[Any], root: int = 0):
        """Replicate `data` from the root process to all processes."""
        import jax

        try:
            from jax.experimental import multihost_utils

            if jax.process_count() == 1:
                return CollectiveResult.SUCCEEDED, data
            result = multihost_utils.broadcast_one_to_all(
                data, is_source=jax.process_index() == root
            )
            return CollectiveResult.SUCCEEDED, jax.tree.map(np.asarray, result)
        except Exception as exc:
            logger.error("broadcast failed: %s", exc)
            return CollectiveResult.FAILED, None

    def barrier(self, name: str = "barrier"):
        import jax

        try:
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(name)
            return CollectiveResult.SUCCEEDED
        except Exception as exc:
            logger.error("barrier failed: %s", exc)
            return CollectiveResult.FAILED
