"""Fault-tolerant collective ops with status results.

Parity: the reference's CollectiveCommunicator (FTlib wrapper,
collective_ops/communicator.py — SURVEY.md §2.1): `allreduce/broadcast/
barrier` return SUCCEEDED/FAILED instead of raising, so the training loop
can react (retry, trigger communicator re-formation) rather than crash.

TPU-native: per-step gradient reduction is a compiled psum inside the
train step, NOT this class.  This is the *control-plane* collective —
host-side reductions over the process set (metric sync, param averaging on
re-formation) via jax.distributed/multihost_utils.  What can *fail* is the
distributed runtime when a peer process dies mid-collective; we catch that
and surface FAILED — the elastic layer (parallel/elastic.py) then re-forms
the mesh over survivors, exactly where the reference re-forms its NCCL ring.
"""

from __future__ import annotations

import enum
from typing import Any, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("parallel.collective")


class CollectiveResult(enum.Enum):
    SUCCEEDED = 0
    FAILED = 1


class CollectiveCommunicator:
    """Mesh-wide allreduce/broadcast/barrier that reports failure as status.

    `mesh` may span multiple processes (jax.distributed world); single
    process with N local devices behaves identically (the test harness).
    """

    def __init__(self, mesh):
        self._mesh = mesh  # kept for re-formation wiring (elastic layer)

    # ------------------------------------------------------------------

    def allreduce(self, data: Any, op: str = "MEAN"):
        """Mean/sum of a host array contributed ONCE per process.

        Returns (CollectiveResult, result_or_None).  Matches the reference's
        CollectiveCommunicator semantics: each worker process contributes a
        single value, regardless of how many local devices it drives — this
        is the *control-plane* collective (metric sync, param averaging on
        re-formation), not the per-step gradient psum (which lives inside
        the compiled train step).
        """
        import jax

        if op not in ("MEAN", "SUM"):
            # Programming error, not a peer failure: raise, don't FAIL.
            raise ValueError(f"Unknown allreduce op {op!r}")
        try:
            arr = np.asarray(data)
            if jax.process_count() == 1:
                stacked = arr[None]
            else:
                from jax.experimental import multihost_utils

                stacked = np.asarray(multihost_utils.process_allgather(arr))
                stacked = stacked.reshape((jax.process_count(),) + arr.shape)
            total = stacked.sum(axis=0)
            if op == "MEAN":
                total = total / stacked.shape[0]
            return CollectiveResult.SUCCEEDED, total
        except Exception as exc:  # runtime/peer failure → status, not crash
            logger.error("allreduce failed: %s", exc)
            return CollectiveResult.FAILED, None

    def broadcast(self, data: Optional[Any], root: int = 0):
        """Replicate `data` from the root process to all processes."""
        import jax

        try:
            from jax.experimental import multihost_utils

            if jax.process_count() == 1:
                return CollectiveResult.SUCCEEDED, data
            result = multihost_utils.broadcast_one_to_all(
                data, is_source=jax.process_index() == root
            )
            return CollectiveResult.SUCCEEDED, jax.tree.map(np.asarray, result)
        except Exception as exc:
            logger.error("broadcast failed: %s", exc)
            return CollectiveResult.FAILED, None

    def barrier(self, name: str = "barrier"):
        import jax

        try:
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices(name)
            return CollectiveResult.SUCCEEDED
        except Exception as exc:
            logger.error("barrier failed: %s", exc)
            return CollectiveResult.FAILED
