# sharding-compile-layer — the one sanctioned mesh context (see
# analysis/jax_rules.py sharding-coverage): every jit/shard_map this
# module builds applies placements from a rule table or explicit specs,
# and tests/test_compile.py gates each (trainer, rule-table) config with
# HLO-structure parity, so per-call-site sharding checks are owned here.
"""Declarative sharding compile layer: one place that turns (step fn,
param pytree, partition-rule table, mesh) into the compiled step.

ROADMAP item 3 named the problem: `dp_trainer.py`, `ps_trainer.py`, and
`ring_attention.py` each hand-rolled their mesh/sharding decisions —
three private copies of "which leaf lives where", each with its own
`jax.jit(in_shardings=...)` plumbing, donation flags, and (for the
ring) `shard_map` fallback shims.  New parallelism forms meant new
trainers.  This module centralizes the decision the way Titanax's
compile module and fmengine's `match_partition_rules` do (SNIPPETS
[2]/[3]):

- **Rule tables** (`Rule`, `RuleTable.shardings`): an ordered list of
  (regex over the '/'-joined leaf path, PartitionSpec-or-callable)
  entries matched over a param/state pytree, first match wins.  Scalars
  replicate without consulting the table (partitioning a 0-d leaf is
  meaningless); a non-scalar leaf no rule matches is an ERROR — silent
  XLA layout guessing is exactly what the table exists to prevent.
  Size-aware placements (FSDP's min-leaf/divisibility tests, the PS
  table's block-divisibility test) are callable rules: they receive
  (path, shape) and return the spec, so the *policy* still reads as one
  table entry.
- **Strategy selection** (`select_strategy`, `CompilePlan.compile`):
  jit-with-shardings ("pjit") when explicit per-leaf shardings cover
  the argument pytrees; `shard_map` for map-style bodies that need
  per-device rank-local views (ring attention's ppermute ring, the
  fused Pallas sparse kernels — `pallas_call` has no SPMD partitioning
  rule, so manual sharding is the only way a kernel body runs on a
  multi-device mesh).
- **One plumbing point**: donation (`donate_argnums`) and
  `in/out_shardings` are applied here, and every compile journals a
  `compile_plan` event (trainer, strategy, rule hits/misses, donated
  argnums — scripts/validate_journal.py) so a postmortem can always
  answer "what placement did this job actually compile?".

The trainers now build every compiled entry point through this module
(gated by a grep test in tests/test_compile.py), so a new parallelism
form is a rule-table entry, not a new trainer.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("parallel.compile")

#: Sentinel distinguishing "not passed" from an explicit None (jax gives
#: None meaning in sharding kwargs).
_UNSET = object()


# ---------------------------------------------------------------------------
# Partition-rule tables
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Rule:
    """One partition rule: `pattern` is a regex searched against the
    '/'-joined leaf path (dict keys, attr names, sequence indices);
    `spec` is a `PartitionSpec`, or a callable `(path, shape) ->
    PartitionSpec` for size/shape-aware placements (FSDP min-leaf,
    table block divisibility)."""

    pattern: str
    spec: Any


def _key_str(key) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def tree_paths(tree) -> List[Tuple[str, Any]]:
    """[(path string, leaf)] over a pytree, '/'-joined keys."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [
        ("/".join(_key_str(k) for k in path), leaf) for path, leaf in flat
    ]


def _leaf_shape(leaf) -> tuple:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        shape = np.shape(leaf)
    return tuple(shape)


class RuleTable:
    """Ordered partition rules over a pytree (fmengine's
    `match_partition_rules`, shape-aware).  First match wins; scalar
    leaves (ndim 0 or one element) replicate without consulting the
    table; an unmatched non-scalar leaf raises."""

    def __init__(self, rules: Sequence[Rule], name: str = ""):
        self.name = name
        self.rules = tuple(rules)
        self._compiled = [re.compile(rule.pattern) for rule in self.rules]

    def match(self, tree):
        """(specs pytree, stats) — stats carries per-rule hit counts and
        the total leaves that fell to the scalar default."""
        import jax
        from jax.sharding import PartitionSpec as P

        hits = [0] * len(self.rules)
        scalars = 0
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = []
        for path, leaf in flat:
            path_str = "/".join(_key_str(k) for k in path)
            shape = _leaf_shape(leaf)
            if len(shape) == 0 or int(np.prod(shape)) == 1:
                scalars += 1
                specs.append(P())
                continue
            for i, regex in enumerate(self._compiled):
                if regex.search(path_str) is not None:
                    hits[i] += 1
                    spec = self.rules[i].spec
                    if callable(spec):
                        spec = spec(path_str, shape)
                    specs.append(spec)
                    break
            else:
                raise ValueError(
                    f"partition rule table {self.name!r} has no rule for "
                    f"leaf {path_str!r} (shape {shape}) — every non-scalar "
                    "leaf must be covered; add a rule (or a catch-all "
                    "'.*' replicate entry) so the placement is declared, "
                    "not guessed"
                )
        stats = {
            "rule_hits": int(sum(hits)),
            # Unmatched non-scalar leaves raise above, so a SUCCESSFUL
            # match always reports 0 — the journaled invariant witness
            # that nothing fell through to a guessed layout.
            "rule_misses": 0,
            # Rules that matched nothing (e.g. a catch-all behind a
            # fully-covering specific rule) — dead-table-entry hygiene,
            # NOT a coverage hole.
            "unused_rules": int(sum(1 for h in hits if h == 0)),
            "scalars": scalars,
            "per_rule": {
                rule.pattern: hit for rule, hit in zip(self.rules, hits)
            },
        }
        return jax.tree_util.tree_unflatten(treedef, specs), stats

    def shardings(self, mesh, tree):
        """(NamedSharding pytree, stats) for `tree` on `mesh`."""
        import jax
        from jax.sharding import NamedSharding

        specs, stats = self.match(tree)
        return (
            jax.tree.map(lambda s: NamedSharding(mesh, s), specs),
            stats,
        )


def match_partition_rules(rules: Sequence[Rule], tree):
    """Functional form (SNIPPETS [3] parity): specs pytree only."""
    return RuleTable(rules).match(tree)[0]


# ---------------------------------------------------------------------------
# Strategy selection + the raw shard_map shim
# ---------------------------------------------------------------------------


def select_strategy(
    *, in_shardings=_UNSET, out_shardings=_UNSET, in_specs=None,
    out_specs=None,
) -> str:
    """'shard_map' for map-style bodies (per-shard specs given), 'pjit'
    when explicit shardings cover the pytree (or the body is a plain
    whole-array program the partitioner owns)."""
    if in_specs is not None or out_specs is not None:
        if (in_specs is None) != (out_specs is None):
            raise ValueError(
                "shard_map strategy needs BOTH in_specs and out_specs "
                "(a map-style body's input and output rank-local views "
                "must both be declared)"
            )
        return "shard_map"
    return "pjit"


def shard_map_call(
    fn: Callable,
    mesh,
    *,
    in_specs,
    out_specs,
    check_vma: Optional[bool] = None,
):
    """`jax.shard_map` with the jax.experimental fallback and the
    check_vma/check_rep kwarg rename handled in one place.  Trace-safe
    (no journaling): model bodies build shard_mapped callables under
    trace (ring attention inside a zoo model's `__call__`).

    `check_vma=False` is the documented escape hatch for Pallas bodies
    in interpret mode (CPU tests/dryruns trip a jax limitation inside
    the kernel interpreter: "Primitive dynamic_slice requires varying
    manual axes to match"); collective placement for those paths is
    pinned by HLO-structure tests instead.
    """
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm

    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    if check_vma is None:
        return sm(fn, **kwargs)
    try:
        return sm(fn, check_vma=check_vma, **kwargs)
    except TypeError:  # older jax: the flag was called check_rep
        return sm(fn, check_rep=check_vma, **kwargs)


def jit_utility(fn: Callable, **jit_kwargs):
    """Sanctioned passthrough for NON-step compiles whose outputs are
    layout-irrelevant (e.g. a specs-only init jit whose dead param
    computations XLA eliminates).  Step functions go through
    `CompilePlan.compile` so their placement is declared and journaled.
    """
    import jax

    return jax.jit(fn, **jit_kwargs)


# ---------------------------------------------------------------------------
# The compile plan
# ---------------------------------------------------------------------------


def _journal_plan(record: Dict[str, Any]) -> None:
    # Host-side only (trainer init / _compile_steps time); the obs
    # plane never rides a traced step (trace-purity rule).
    from elasticdl_tpu import obs

    obs.journal().record("compile_plan", **record)


class CompilePlan:
    """The declarative compile context for one trainer: a mesh, an
    optional partition-rule table, and the journaling identity.

    `state_shardings(tree)` resolves the rule table over a state pytree
    (recording hits/misses for the next `compile_plan` event);
    `compile(fn, ...)` produces the compiled step — jit-with-shardings
    or shard_map per `select_strategy` — applying donation and
    in/out_shardings in this one place.
    """

    def __init__(self, mesh, rules: Optional[RuleTable] = None,
                 trainer: str = ""):
        self.mesh = mesh
        self.rules = rules
        self.trainer = trainer
        self._last_stats: Dict[str, int] = {}

    # -- rule resolution -------------------------------------------------

    def state_shardings(self, tree):
        """NamedSharding pytree for `tree` from this plan's rule table."""
        if self.rules is None:
            raise ValueError(
                f"CompilePlan for {self.trainer!r} has no rule table; "
                "pass explicit shardings to compile() instead"
            )
        shardings, stats = self.rules.shardings(self.mesh, tree)
        self._last_stats = stats
        return shardings

    def replicated(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P())

    # -- the compile entry ----------------------------------------------

    def compile(
        self,
        fn: Callable,
        *,
        name: str,
        in_shardings=_UNSET,
        out_shardings=_UNSET,
        in_specs=None,
        out_specs=None,
        donate_argnums: Tuple[int, ...] = (),
        static_argnums=None,
        check_vma: Optional[bool] = None,
        journal: bool = True,
    ):
        """The compiled callable for `fn` under this plan.

        pjit strategy: `jax.jit` with the given shardings + donation.
        shard_map strategy: the shard_mapped body wrapped in `jax.jit`
        (out_shardings derived from out_specs; donation still applies),
        so callers get one compiled program either way.
        """
        import jax

        strategy = select_strategy(
            in_shardings=in_shardings, out_shardings=out_shardings,
            in_specs=in_specs, out_specs=out_specs,
        )
        if strategy == "shard_map":
            body = shard_map_call(
                fn, self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=check_vma,
            )
            # The shard_map's own specs pin every operand's rank-local
            # layout; the jit wrapper only owns donation + caching.
            compiled = jax.jit(
                body,
                donate_argnums=donate_argnums,
                static_argnums=static_argnums,
            )
        else:
            kwargs: Dict[str, Any] = {}
            if in_shardings is not _UNSET:
                kwargs["in_shardings"] = in_shardings
            if out_shardings is not _UNSET:
                kwargs["out_shardings"] = out_shardings
            if static_argnums is not None:
                kwargs["static_argnums"] = static_argnums
            compiled = jax.jit(
                fn, donate_argnums=donate_argnums, **kwargs
            )
        if journal:
            stats = self._last_stats
            _journal_plan({
                "trainer": self.trainer,
                "name": name,
                "strategy": strategy,
                "rule_table": self.rules.name if self.rules else "",
                "rule_hits": stats.get("rule_hits", 0),
                "rule_misses": stats.get("rule_misses", 0),
                "unused_rules": stats.get("unused_rules", 0),
                "donated_argnums": list(donate_argnums),
                "devices": int(self.mesh.devices.size),
            })
        return compiled
