"""Sharded-embedding trainer: ParameterServerStrategy, compiled.

Parity: the reference's PS-mode training stack (SURVEY.md §3.3) — worker
pulls dense params + embedding rows from Go PS pods, computes grads, and
pushes dense grads + IndexedSlices back for the PS's Eigen sparse kernels.
TPU-native: the PS dissolves into the step function.

- Dense params: replicated over the mesh, optax-updated (the PS's dense
  optimizer path).
- Embedding tables: ONE array per table, vocab-sharded across ALL mesh
  devices' HBM (the PS-pod partitioning, minus the gRPC hop).  Lookups are
  gathers on the sharded operand; XLA lowers them to local gathers + ICI
  collectives inside the same program as the matmuls.
- Sparse gradients: captured at each Embedding layer's perturbation point
  (layers/embedding.py) — never a dense [vocab, dim] cotangent — and
  scatter-applied by the sparse row-wise optimizers (parallel/sparse_optim).

Same public surface as DataParallelTrainer, so the worker runtimes drive
either interchangeably.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.layers.embedding import (
    IDS_COLLECTION,
    OOV_COLLECTION,
    PERTURBATIONS,
    SPECS_COLLECTION,
    VOCAB_AXIS,
)
from elasticdl_tpu.parallel import compile as pc
from elasticdl_tpu.parallel import packed as pk
from elasticdl_tpu.parallel.packed import PackedSpec
from elasticdl_tpu.parallel import sharding as shd
from elasticdl_tpu.parallel.dp_trainer import per_example_loss_fn
from elasticdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from elasticdl_tpu.parallel.sparse_optim import SparseOptimizer, sgd
from elasticdl_tpu.worker.trainer import _model_apply

logger = get_logger("parallel.ps_trainer")

# --sparse_apply_every=auto resolution (round-5 VERDICT #5): strict
# per-step apply up to this many resident embedding rows, the windowed
# W below above it.  Threshold = where strict mode's per-step
# table-streaming pass starts dominating (the BASELINE.md table-scale
# probe: ~3.5x at 26M rows) — deliberately the same number as
# model_zoo/deepfm's SPLIT_TABLE_ROWS so a layout-aware model and the
# trainer resolve `auto` consistently from the same row count.  W=32 is
# the round-4 "largest safe W" (convergence within noise of strict at
# both tested scales, BASELINE.md "Windowed-apply convergence").
AUTO_APPLY_TABLE_ROWS = 10_000_000
AUTO_APPLY_W = 32


class PSTrainState(NamedTuple):
    step: jnp.ndarray
    params: Any           # dense params; table leaves hold scalar placeholders
    opt_state: Any
    model_state: Any      # batch_stats etc.
    tables: Dict[str, jnp.ndarray]          # path-key -> [vocab, dim]
    slots: Dict[str, Dict[str, jnp.ndarray]]  # path-key -> optimizer slots


def _path_key(path) -> str:
    return "/".join(str(getattr(p, "key", p)) for p in path)


def _unbox(tree):
    return jax.tree.map(
        lambda x: x.unbox() if isinstance(x, nn.Partitioned) else x,
        tree,
        is_leaf=lambda x: isinstance(x, nn.Partitioned),
    )


class ShardedEmbeddingTrainer:
    """PS-mode trainer over an N-device (data, model) mesh."""

    def __init__(
        self,
        model,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        mesh,
        embedding_optimizer: Optional[SparseOptimizer] = None,
        seed: int = 0,
        sparse_apply_every=1,
        sparse_kernel: Optional[str] = None,
    ):
        self._model = model
        self._loss_fn = loss_fn
        self._per_example_loss = per_example_loss_fn(loss_fn)
        self._tx = optimizer
        if embedding_optimizer is None:
            logger.warning(
                "No embedding_optimizer in the model spec; defaulting to "
                "sparse SGD(0.01) for embedding tables"
            )
            embedding_optimizer = sgd(0.01)
        self._emb_tx = embedding_optimizer
        # --sparse_kernel: 'fused' swaps the optimizer's apply onto the
        # Pallas dedup+apply kernel (ops/sparse_embedding.py); the
        # LOOKUP side rides the model's own Embedding layers (the zoo
        # threads the flag via model_params; worker main also sets the
        # process default so un-threaded models follow).  None = the
        # process default; 'auto' resolves there (xla until the fused
        # chip numbers land — BASELINE.md queued chip work).
        from elasticdl_tpu.ops import sparse_embedding as ske

        self._sparse_kernel_requested = sparse_kernel or ske.default_kernel()
        resolved = ske.resolve_kernel(sparse_kernel)
        # Fused dispatch route: single_device keeps the plain pallas_call
        # path; a multi-device mesh routes every fused kernel through
        # shard_map (ops/sparse_embedding.py "Sharded dispatch") —
        # tables shard over the `model` axis, ids route to their owning
        # shard, and the combine is a psum.  The v1 multi-device config
        # ERROR (pallas_call has no SPMD partitioning rule) is gone:
        # shard_map IS the partitioning rule.
        self._sparse_route = ske.dispatch_route(mesh)
        if resolved == "fused":
            if self._emb_tx.remake is None:
                logger.warning(
                    "sparse_kernel=fused but embedding optimizer %r has "
                    "no remake hook; its apply keeps its constructed "
                    "mode (lookups still run fused)",
                    self._emb_tx.name,
                )
            else:
                self._emb_tx = self._remake_fused(self._emb_tx, mesh)
            if (
                self._sparse_route == "shard_map"
                and ske.dispatch_mesh() is not mesh
            ):
                # The trainer cannot introspect the MODEL's Embedding
                # layers (created inside @nn.compact), so it cannot
                # verify they carry this mesh.  A layer left at
                # mesh=None in a multi-device job would trace an
                # unpartitionable pallas_call into the SPMD step — the
                # failure the old config error guarded.  worker/main
                # registers the process default; direct constructions
                # must thread mesh= into the model.  Leave the
                # breadcrumb the eventual compile error won't.
                logger.warning(
                    "sparse_kernel=fused on a %d-device mesh: the fused "
                    "kernels dispatch through shard_map ONLY where the "
                    "model's Embedding layers were built with this mesh "
                    "(mesh= field, or ske.set_dispatch_mesh as "
                    "worker/main does).  If a layer was built without "
                    "it, the step will fail to compile — docs/design.md "
                    "'Declarative sharding'.",
                    int(mesh.devices.size),
                )
        self._sparse_kernel = resolved
        if sparse_apply_every == "auto":
            # Resolved at ensure_initialized, the first point the
            # resident table row count is known (AUTO_APPLY_TABLE_ROWS
            # below).  None means "unresolved"; consumers that peek
            # before init (collective_worker window sizing) treat it as
            # strict and re-sync after the trainer initializes.
            self._sparse_apply_every = None
        else:
            self._sparse_apply_every = max(1, int(sparse_apply_every))
        self._mesh = mesh
        self._seed = seed
        self._dp = shd.data_axis_size(mesh)
        self._state: Optional[PSTrainState] = None
        self._host_step = 0
        # Device-side OOV scalars, one per dispatched step/window; summed
        # and drained host-side by consume_oov_count().
        self._pending_oov: list = []
        self._perturb_shapes: Dict[str, Any] = {}
        self._pending_restore: Optional[PSTrainState] = None
        self._pending_sharded_restore: Optional[Tuple[Any, int]] = None
        self._train_step = None  # jitted lazily once shardings are known
        self._eval_step = None

    def _remake_fused(self, emb_tx: SparseOptimizer, mesh):
        """Rebuild the optimizer in fused mode, threading the dispatch
        mesh when its remake hook accepts one (signature-inspected — no
        exception swallowing).  A pre-mesh hook is fine on a single
        device but a hard ERROR on a multi-device mesh: a mesh-less
        fused apply over model-sharded tables would trace an
        unpartitionable pallas_call into the SPMD step while the
        journal reports route=shard_map — the misattribution the
        journal event exists to prevent."""
        import inspect

        try:
            params = inspect.signature(emb_tx.remake).parameters
            accepts_mesh = "mesh" in params or any(
                p.kind == p.VAR_KEYWORD for p in params.values()
            )
        except (TypeError, ValueError):
            accepts_mesh = False
        if accepts_mesh:
            return emb_tx.remake("fused", mesh=mesh)
        if self._sparse_route == "shard_map":
            raise ValueError(
                f"sparse_kernel=fused on a {int(mesh.devices.size)}-"
                f"device mesh needs an embedding optimizer whose remake "
                f"hook accepts mesh= (got {emb_tx.name!r} with a "
                "mode-only hook) — the fused apply must dispatch "
                "through shard_map to run over model-sharded tables "
                "(docs/design.md 'Declarative sharding')"
            )
        return emb_tx.remake("fused")

    # -- public surface (mirrors DataParallelTrainer) -------------------

    @property
    def mesh(self):
        return self._mesh

    def jitted_entrypoints(self) -> dict:
        """Current jitted entrypoints by name for the step-anatomy
        retrace watcher (obs/stepstats.py; see DataParallelTrainer)."""
        return {
            "ps_train_step": self._train_step,
            "ps_train_window": getattr(self, "_train_window", None),
            "ps_eval_step": self._eval_step,
        }

    def local_block(self, per_rank_batch: int) -> int:
        local_devices = max(1, self._dp // jax.process_count())
        return -(-per_rank_batch // local_devices) * local_devices

    @property
    def state(self) -> Optional[PSTrainState]:
        return self._state

    @state.setter
    def state(self, value: PSTrainState):
        value = PSTrainState(*value)
        if self._state is None:
            # Restore before the first batch (checkpoint restore at worker
            # boot): applied inside ensure_initialized once the model's
            # structure/shardings exist.
            self._pending_restore = value
            self._host_step = int(np.asarray(jax.device_get(value.step)))
            return
        self._state = self._place_state(jax.device_get(value))
        self._host_step = int(np.asarray(jax.device_get(value.step)))

    @property
    def step(self) -> int:
        return self._host_step

    # -- sharding layout (declarative rule table, parallel/compile.py) --

    def _partition_rules(self) -> pc.RuleTable:
        """PS-mode placement policy as a rule table: dense state (step,
        params, opt_state, model_state) replicates; embedding tables
        and their table-shaped optimizer slots shard on dim0 (storage
        blocks).  The block placement is the ONE shape-aware entry:

        - xla engine: blocks across the WHOLE mesh (`data` x `model`) —
          maximum HBM capacity, the analogue of partitioning one table
          over every PS pod; tables too small to split evenly replicate
          (they are by definition tiny).
        - fused engine: blocks over the `model` axis only (replicated
          across `data`) — the layout the shard_map'd kernel dispatch
          declares (ops/sparse_embedding.table_partition_axis), so the
          per-shard pallas bodies see exactly their resident blocks
          with no per-step resharding.

        Scalar slots (adam's global-bias counter) replicate via the
        table's scalar default."""
        from jax.sharding import PartitionSpec as P

        from elasticdl_tpu.ops import sparse_embedding as ske

        fused = self._sparse_kernel == "fused"
        mesh = self._mesh
        total = int(mesh.devices.size)

        def table_blocks(path, shape):
            if fused:
                axis = ske.table_partition_axis(shape[0], mesh)
                if axis is None:
                    return P()
                return P(axis, *([None] * (len(shape) - 1)))
            if shape[0] % total != 0:
                return P()
            return P((DATA_AXIS, MODEL_AXIS), *([None] * (len(shape) - 1)))

        return pc.RuleTable(
            [
                pc.Rule(r"^(tables|slots)(/|$)", table_blocks),
                pc.Rule(".*", P()),
            ],
            name="ps-fused" if fused else "ps-xla",
        )

    def _plan(self) -> pc.CompilePlan:
        return pc.CompilePlan(
            self._mesh, self._partition_rules(), trainer="ps_trainer"
        )

    def _state_shardings(self, state: PSTrainState, plan=None):
        plan = plan or self._plan()
        tree = plan.state_shardings({
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "model_state": state.model_state,
            "tables": state.tables,
            "slots": state.slots,
        })
        return PSTrainState(
            step=tree["step"],
            params=tree["params"],
            opt_state=tree["opt_state"],
            model_state=tree["model_state"],
            tables=tree["tables"],
            slots=tree["slots"],
        )

    @staticmethod
    def _place_leaf(x, s):
        return shd.put(x, s)

    def _place_state(self, state: PSTrainState) -> PSTrainState:
        return shd.put(state, self._state_shardings(state))

    # -- initialization -------------------------------------------------

    def ensure_initialized(self, features) -> PSTrainState:
        if self._state is not None:
            return self._state
        rng = jax.random.PRNGKey(self._seed)
        # Init with the GLOBAL batch shape (local rows x process count):
        # perturbation variables take their shape from init, and apply runs
        # on the assembled global batch.  Zeros keep init identical on
        # every rank (param init only consumes shapes + rng).
        procs = jax.process_count()
        features = jax.tree.map(
            lambda x: jnp.zeros(
                (np.shape(x)[0] * procs,) + tuple(np.shape(x)[1:]),
                np.asarray(x).dtype,
            ),
            features,
        )
        variables = dict(self._model.init(rng, features))
        params_boxed = variables.pop("params")
        variables.pop(IDS_COLLECTION, None)
        variables.pop(OOV_COLLECTION, None)
        perturbs = variables.pop(PERTURBATIONS, {})
        specs_tree = variables.pop(SPECS_COLLECTION, {})
        model_state = variables

        # Split tables (VOCAB_AXIS-marked Partitioned leaves) from dense.
        tables: Dict[str, jnp.ndarray] = {}
        self._table_paths = {}
        self._table_specs: Dict[str, PackedSpec] = {}

        def split(path, leaf):
            if (
                isinstance(leaf, nn.Partitioned)
                and leaf.names
                and leaf.names[0] == VOCAB_AXIS
            ):
                key = _path_key(path)
                tables[key] = leaf.unbox()
                self._table_paths[key] = tuple(
                    getattr(p, "key", p) for p in path
                )
                return jnp.zeros((), jnp.float32)  # structure placeholder
            return leaf.unbox() if isinstance(leaf, nn.Partitioned) else leaf

        flat = jax.tree_util.tree_flatten_with_path(
            params_boxed,
            is_leaf=lambda x: isinstance(x, nn.Partitioned),
        )
        params = jax.tree_util.tree_unflatten(
            flat[1], [split(p, v) for p, v in flat[0]]
        )
        for key, module_path in self._table_paths.items():
            spec_arr = np.asarray(
                _collection_get(specs_tree, module_path[:-1], "spec")
            )
            self._table_specs[key] = PackedSpec(int(spec_arr[0]), int(spec_arr[1]))
            assert tables[key].shape == self._table_specs[key].packed_shape, (
                key, tables[key].shape, self._table_specs[key],
            )
        slots = {
            key: self._emb_tx.init_slots(self._table_specs[key], table)
            for key, table in tables.items()
        }
        self._perturb_shapes = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), _unbox(perturbs)
        )
        state = PSTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=self._tx.init(params),
            model_state=_unbox(model_state),
            tables=tables,
            slots=slots,
        )
        if self._pending_sharded_restore is not None:
            self._state = self._restore_sharded(state)
        else:
            if self._pending_restore is not None:
                state = self._pending_restore
                self._pending_restore = None
            self._state = self._place_state(jax.device_get(state))
        n_dense = sum(
            int(np.prod(np.shape(p))) for p in jax.tree.leaves(params)
        )
        n_table = sum(int(np.prod(t.shape)) for t in tables.values())
        total_rows = sum(
            spec.vocab_size for spec in self._table_specs.values()
        )
        if self._sparse_apply_every is None:
            self._sparse_apply_every = (
                1 if total_rows <= AUTO_APPLY_TABLE_ROWS else AUTO_APPLY_W
            )
            logger.info(
                "sparse_apply_every=auto -> %d (%.1fM resident embedding "
                "rows %s the %dM strict/windowed threshold)",
                self._sparse_apply_every,
                total_rows / 1e6,
                "<=" if total_rows <= AUTO_APPLY_TABLE_ROWS else ">",
                AUTO_APPLY_TABLE_ROWS // 1_000_000,
            )
        if self._sparse_apply_every == 1 and total_rows > AUTO_APPLY_TABLE_ROWS:
            # Same honesty contract as the attention VMEM advice: strict
            # per-step apply at this scale pays table-sized streaming
            # passes every step — measured ~3x slower than the windowed
            # config at the 26M-row probe, and the windowed semantics
            # are convergence-validated (BASELINE.md "Windowed-apply
            # convergence": peak held-out AUC at W=16 within 0.003 of
            # strict).  Say so instead of silently running slow.
            logger.warning(
                "Strict per-step sparse apply with %.1fM embedding rows "
                "resident: --sparse_apply_every=16 runs ~3x faster at "
                "this scale with convergence measured equal at peak "
                "(docs/tutorial.md 'Large embedding tables'); strict "
                "mode stays exact-per-step if that is what you need",
                total_rows / 1e6,
            )
        logger.info(
            "Initialized PS-mode model: %d dense params (replicated), "
            "%d embedding-table params in %d table(s) sharded over %d "
            "device(s) [%s, sparse_kernel=%s]",
            n_dense,
            n_table,
            len(tables),
            self._mesh.devices.size,
            self._emb_tx.name,
            self._sparse_kernel,
        )
        # Journal the kernel decision (host-side, init-time — the obs
        # plane never rides the traced step): postmortems and the
        # bench-regress audit trail need to know WHICH engine a number
        # was measured on (schema: scripts/validate_journal.py).
        from elasticdl_tpu import obs

        # `route` replaces the removed multi-device downgrade warning:
        # for the fused engine it names the dispatch the kernels take
        # (single_device pallas_call vs shard_map over the mesh); the
        # xla engine always runs the SPMD partitioner ('xla').
        obs.journal().record(
            "sparse_kernel_selected",
            kernel=self._sparse_kernel,
            requested=self._sparse_kernel_requested,
            route=(
                self._sparse_route if self._sparse_kernel == "fused"
                else "xla"
            ),
            optimizer=self._emb_tx.name,
            tables=len(tables),
            table_rows=total_rows,
        )
        self._compile_steps()
        return self._state

    def _compile_steps(self):
        plan = self._plan()
        repl = plan.replicated()
        batch = shd.batch_sharded(self._mesh)
        window = shd.window_sharded(self._mesh)
        state_shardings = self._state_shardings(self._state, plan)
        self._train_step = plan.compile(
            self._train_step_impl,
            name="ps_train_step",
            in_shardings=(state_shardings, batch, batch, batch),
            out_shardings=(state_shardings, (repl, repl)),
            donate_argnums=(0,),
        )
        self._train_window = plan.compile(
            self._train_window_impl,
            name="ps_train_window",
            in_shardings=(state_shardings, window, window, window),
            out_shardings=(state_shardings, (repl, repl)),
            donate_argnums=(0,),
        )
        self._eval_step = plan.compile(
            self._eval_step_impl,
            name="ps_eval_step",
            in_shardings=(state_shardings, batch),
            out_shardings=batch,
        )

    # -- compiled steps -------------------------------------------------

    def _merge_params(self, params, tables):
        flat = jax.tree_util.tree_flatten_with_path(params)
        merged = [
            tables.get(_path_key(path), leaf) for path, leaf in flat[0]
        ]
        return jax.tree_util.tree_unflatten(flat[1], merged)

    def _zero_perturbations(self):
        return jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), self._perturb_shapes
        )

    def _forward_backward(self, state: PSTrainState, features, labels, mask):
        """One fwd/bwd: loss, mutated collections, dense + perturbation
        (sparse embedding) gradients."""
        mutable_keys = list(state.model_state.keys()) + [
            IDS_COLLECTION, OOV_COLLECTION,
        ]

        def compute_loss(params, perturbs):
            full_params = self._merge_params(params, state.tables)
            variables = {
                "params": full_params,
                PERTURBATIONS: perturbs,
                **state.model_state,
            }
            outputs, muts = _model_apply(
                self._model, variables, features, train=True,
                mutable=mutable_keys,
            )
            losses = self._per_example_loss(labels, outputs)
            loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            return loss, muts

        (loss, muts), (dense_grads, perturb_grads) = jax.value_and_grad(
            compute_loss, argnums=(0, 1), has_aux=True
        )(state.params, self._zero_perturbations())
        return loss, muts, dense_grads, perturb_grads

    @staticmethod
    def _oov_total(muts) -> jnp.ndarray:
        """Sum of the per-Embedding OOV counts sown this apply (scalar
        int32; zero when the model has no Embedding layers)."""
        total = jnp.zeros((), jnp.int32)
        for leaf in jax.tree.leaves(muts.get(OOV_COLLECTION, {})):
            total = total + jnp.sum(jnp.asarray(leaf))
        return total

    def _sparse_batches(self, muts, perturb_grads, tables):
        """Per table: (spec, flat ids, flat grads) from the sown id
        collection + perturbation cotangents."""
        ids_tree = muts.get(IDS_COLLECTION, {})
        for key, module_path in self._table_paths.items():
            prefix = module_path[:-1]  # drop the 'embedding' param name
            spec = self._table_specs[key]
            ids = _collection_get(ids_tree, prefix, "ids")
            grad = _collection_get(perturb_grads, prefix, "bet")
            flat_ids = ids.reshape((-1,))
            flat_grads = grad.reshape((-1, spec.dim)).astype(tables[key].dtype)
            yield key, spec, flat_ids, flat_grads

    def _dense_and_state(self, state, muts, dense_grads):
        updates, new_opt_state = self._tx.update(
            dense_grads, state.opt_state, state.params
        )
        new_params = optax.apply_updates(state.params, updates)
        new_model_state = (
            {k: muts[k] for k in state.model_state.keys() if k in muts}
            or state.model_state
        )
        return new_params, new_opt_state, new_model_state

    def _train_step_impl(self, state: PSTrainState, features, labels, mask):
        loss, muts, dense_grads, perturb_grads = self._forward_backward(
            state, features, labels, mask
        )
        new_params, new_opt_state, new_model_state = self._dense_and_state(
            state, muts, dense_grads
        )
        # Sparse apply per table: pair sown ids with perturbation grads.
        new_tables = dict(state.tables)
        new_slots = dict(state.slots)
        for key, spec, flat_ids, flat_grads in self._sparse_batches(
            muts, perturb_grads, new_tables
        ):
            new_tables[key], new_slots[key] = self._emb_tx.apply(
                spec, new_tables[key], new_slots[key], flat_ids, flat_grads
            )
        return (
            PSTrainState(
                state.step + 1,
                new_params,
                new_opt_state,
                new_model_state,
                new_tables,
                new_slots,
            ),
            (loss, self._oov_total(muts)),
        )

    def _train_chunk_impl(self, state: PSTrainState, feats, labels, masks):
        """W steps with per-step dense updates and ONE deferred sparse
        apply (sparse_apply_every > 1).

        The windowed relaxation: embedding grads accumulate into a packed
        acc table across the chunk (duplicates sum, exactly the per-step
        dedup contract) and the sparse optimizer applies ONCE per chunk
        from the sum — so forwards within a chunk read the tables as of
        the chunk start.  This is the reference's ASYNC-PS staleness
        (workers there train on pulled snapshots while pushed grads land;
        SURVEY §3.3), traded deliberately: the full-table streaming
        moment update amortizes W-fold, which at the 26M-row north-star
        probe is the difference between 184k and >500k samples/s/chip.
        Dense params, batch stats, and the step counter still update
        every step; strict per-step semantics remain the default (W=1).

        Mechanically the chunk's (ids, grads) stream OUT of the scan and
        feed one optimizer `apply` on the concatenated W-step batch —
        NOT an accumulator table carried through the scan: XLA never
        scatters into a loop carry in place, so a carried acc paid a
        full table copy every step (measured 15.9 ms/step at the 26M
        probe, worse than what the window was saving).  The scan outputs
        cost W x batch-sized buffers instead (a few hundred MB at W=64).
        """

        def body(st, xs):
            features, labels_, mask = xs
            loss, muts, dense_grads, perturb_grads = self._forward_backward(
                st, features, labels_, mask
            )
            new_params, new_opt_state, new_model_state = self._dense_and_state(
                st, muts, dense_grads
            )
            sparse = {
                key: (flat_ids, flat_grads)
                for key, _, flat_ids, flat_grads in self._sparse_batches(
                    muts, perturb_grads, st.tables
                )
            }
            new_st = PSTrainState(
                st.step + 1, new_params, new_opt_state, new_model_state,
                st.tables, st.slots,
            )
            return new_st, (loss, self._oov_total(muts), sparse)

        state, (losses, oovs, sparse) = jax.lax.scan(
            body, state, (feats, labels, masks)
        )
        new_tables = dict(state.tables)
        new_slots = dict(state.slots)
        for key in self._table_paths:
            spec = self._table_specs[key]
            ids_w, grads_w = sparse[key]  # [W, n], [W, n, dim]
            new_tables[key], new_slots[key] = self._emb_tx.apply(
                spec, new_tables[key], new_slots[key],
                ids_w.reshape((-1,)),
                grads_w.reshape((-1, spec.dim)),
            )
        return (
            state._replace(tables=new_tables, slots=new_slots),
            (losses, jnp.sum(oovs)),
        )

    def _train_window_impl(self, state, feat_win, label_win, mask_win):
        """K train steps in ONE device program (lax.scan over the stacked
        window).  One dispatch + one transfer amortize per-call overheads
        K-fold — the TPU-idiomatic device-side training loop.  With
        sparse_apply_every=W > 1 the window runs as ceil(K/W) chunks (see
        _train_chunk_impl)."""
        W = self._sparse_apply_every or 1  # auto resolves at init

        if W <= 1:
            def body(st, xs):
                features, labels, mask = xs
                new_state, (loss, oov) = self._train_step_impl(
                    st, features, labels, mask
                )
                return new_state, (loss, oov)

            state, (losses, oovs) = jax.lax.scan(
                body, state, (feat_win, label_win, mask_win)
            )
            return state, (losses, jnp.sum(oovs))

        K = jax.tree.leaves(feat_win)[0].shape[0]
        n_full, rem = divmod(K, W)
        losses_parts = []
        oov_parts = []
        if n_full:
            chunked = jax.tree.map(
                lambda x: x[: n_full * W].reshape(
                    (n_full, W) + x.shape[1:]
                ),
                (feat_win, label_win, mask_win),
            )

            def chunk_body(st, xs):
                return self._train_chunk_impl(st, *xs)

            state, (losses_full, oov_full) = jax.lax.scan(
                chunk_body, state, chunked
            )
            losses_parts.append(losses_full.reshape((-1,)))
            oov_parts.append(jnp.sum(oov_full))
        if rem:
            tail = jax.tree.map(
                lambda x: x[n_full * W:], (feat_win, label_win, mask_win)
            )
            state, (losses_tail, oov_tail) = self._train_chunk_impl(
                state, *tail
            )
            losses_parts.append(losses_tail)
            oov_parts.append(oov_tail)
        losses = (
            jnp.concatenate(losses_parts)
            if len(losses_parts) > 1
            else losses_parts[0]
        )
        return state, (losses, sum(oov_parts))

    def _eval_step_impl(self, state: PSTrainState, features):
        variables = {
            "params": self._merge_params(state.params, state.tables),
            PERTURBATIONS: self._zero_perturbations(),
            **state.model_state,
        }
        outputs, _ = _model_apply(
            self._model, variables, features, train=False,
            mutable=[IDS_COLLECTION],
        )
        return outputs

    # -- host-side entry points (same shapes contract as DP trainer) ----

    def train_step(self, features, labels):
        block = self.local_block(
            jax.tree.leaves(features)[0].shape[0]
        )
        features, mask = shd.pad_batch(features, block)
        labels, _ = shd.pad_batch(labels, block)
        return self.train_step_local(features, labels, mask)

    def train_step_local(self, features, labels, mask):
        self.ensure_initialized(features)
        return self.train_step_staged(self.stage_batch(features, labels, mask))

    def stage_batch(self, features, labels, mask):
        """Asynchronously place one lockstep batch on the mesh.  Staging
        returns immediately (device transfers are async), so staging batch
        k+1 BEFORE stepping batch k overlaps host->device traffic with
        compute — on hosts where the transfer is the bottleneck this is
        the difference between step-time and transfer-time throughput."""
        return (
            shd.assemble_global_batch(features, self._mesh),
            shd.assemble_global_batch(labels, self._mesh),
            shd.assemble_global_batch(np.asarray(mask, np.float32), self._mesh),
        )

    def train_step_staged(self, staged):
        if self._state is None:
            # Init derives perturbation shapes from LOCAL batch shapes;
            # staged batches are already global, so init must happen first
            # (train_step_local does this; direct stagers call
            # ensure_initialized themselves).
            raise RuntimeError(
                "train_step_staged requires ensure_initialized(features) first"
            )
        self._state, (loss, oov) = self._train_step(self._state, *staged)
        self._host_step += 1
        self._pending_oov.append(oov)
        return loss

    def stage_window(self, batches):
        """Stage K lockstep (features, labels, mask) batches in ONE
        host->device transfer: [K, batch, ...] stacks, batch dim sharded.
        Per-transfer overhead (dominant on thin hosts) amortizes K-fold;
        `train_window(window)` then runs all K steps in one device
        program.  All K batches must share shapes (callers route ragged
        tails through `train_step_staged`)."""
        stacked_f, stacked_l, stacked_m = shd.stack_window(batches)
        return (
            shd.assemble_window(stacked_f, self._mesh),
            shd.assemble_window(stacked_l, self._mesh),
            shd.assemble_window(stacked_m, self._mesh),
        )

    def train_window(self, window):
        """Run every batch of a staged window; returns the [K] losses
        (device array — don't block on it in the hot loop)."""
        if self._state is None:
            raise RuntimeError(
                "train_window requires ensure_initialized(features) first"
            )
        k = jax.tree.leaves(window[1])[0].shape[0]
        self._state, (losses, oov) = self._train_window(self._state, *window)
        self._host_step += k
        self._pending_oov.append(oov)
        return losses

    def consume_oov_count(self) -> int:
        """Total out-of-vocabulary ids seen by train steps since the last
        call.  BLOCKS on the pending device scalars — call at task
        boundaries (the worker does, folding the count into the task's
        exec counters), not in the dispatch hot loop."""
        if not self._pending_oov:
            return 0
        total = sum(int(np.asarray(x)) for x in self._pending_oov)
        self._pending_oov = []
        return total

    def eval_step(self, features):
        n = jax.tree.leaves(features)[0].shape[0]
        block = self.local_block(n)
        features, _ = shd.pad_batch(features, block)
        outputs = self.eval_step_local(features)
        return jax.tree.map(lambda x: np.asarray(x)[:n], outputs)

    def eval_step_local(self, features):
        # The gather is ONE GLOBAL BATCH of outputs to every host (a
        # collective, so all ranks call it) — memory is batch-bounded;
        # task/dataset-scale bounding lives in the worker's streaming
        # eval loop (collective_worker EVAL_REPORT_BATCHES +
        # data/dataset.SequentialRecords).
        state = self.ensure_initialized(features)
        features = shd.assemble_global_batch(features, self._mesh)
        outputs = self._eval_step(state, features)
        return shd.gather_to_host(outputs)

    # -- sharded checkpointing -------------------------------------------

    def _sharded_arrays(self, state: PSTrainState) -> Dict[str, jax.Array]:
        """The mesh-sharded leaves, under stable checkpoint names.  '|' is
        the name separator (path keys use '/'); row intervals append two
        more '|' fields in the shard files (checkpoint/sharded.py)."""
        out = {f"table|{k}": v for k, v in state.tables.items()}
        for key, group in state.slots.items():
            for name, v in group.items():
                if np.ndim(v):  # scalar slots ride the dense pickle instead
                    out[f"slot|{key}|{name}"] = v
        return out

    def _scalar_slots(self, state: PSTrainState) -> dict:
        """Replicated 0-d slots (e.g. adam's global-bias counter): row-
        interval sharding is meaningless for them, so they checkpoint with
        the dense state."""
        return {
            key: {
                name: jax.device_get(v)
                for name, v in group.items()
                if not np.ndim(v)
            }
            for key, group in state.slots.items()
        }

    def save_checkpoint(self, saver, step: int) -> None:
        """COLLECTIVE sharded checkpoint (checkpoint/sharded.py): every
        process calls this and writes only its local table/slot rows — no
        host ever materializes a full table, unlike `state_to_host` (whose
        full gather OOMs by construction at Criteo scale)."""
        if self._state is None:
            return
        state = self._state
        # Dense state is replicated and only rank 0 writes it — don't pay
        # the device->host transfer on the other N-1 ranks' hot path.
        dense = None
        if jax.process_index() == 0:
            dense = {
                "step": jax.device_get(state.step),
                "params": jax.device_get(state.params),
                "opt_state": jax.device_get(state.opt_state),
                "model_state": jax.device_get(state.model_state),
                "scalar_slots": self._scalar_slots(state),
            }
        saver.save(step, dense, self._sharded_arrays(state))

    def set_sharded_restore(self, saver, step: int) -> None:
        """Defer restore until ensure_initialized has built the model's
        structure and shardings (worker-boot restore, same contract as the
        `state` setter's pending path)."""
        self._pending_sharded_restore = (saver, step)
        self._host_step = step

    def _restore_sharded(self, template: PSTrainState) -> PSTrainState:
        """Materialize the checkpoint under the CURRENT world's shardings:
        dense state replicates from rank 0's pickle; each table/slot row
        interval is read by whichever process now owns it — world-size
        agnostic, which is what restart-the-world shrink/grow needs."""
        saver, step = self._pending_sharded_restore
        self._pending_sharded_restore = None
        shardings = self._state_shardings(template)
        dense = saver.load_dense(step)
        if hasattr(saver, "manifest"):
            # Fail with the CAUSE when the checkpoint's table set differs
            # from this build's (a bare KeyError on 'table|...' is
            # undiagnosable).  The usual way to get here: a per-mode
            # table layout changed between runs — e.g. DeepFM merges its
            # linear+fm tables under windowed sparse apply but splits
            # them under strict mode at >10M rows, so changing
            # --sparse_apply_every across a restart changes the model's
            # table structure.
            have = {
                name[len("table|"):]
                for name in saver.manifest(step).get("arrays", {})
                if name.startswith("table|")
            }
            want = set(template.tables)
            if have != want:
                raise ValueError(
                    f"Checkpoint at step {step} holds embedding tables "
                    f"{sorted(have)} but this build expects "
                    f"{sorted(want)} — the model's table layout changed "
                    "between save and restore (e.g. DeepFM's per-mode "
                    "layout splits/merges tables when "
                    "--sparse_apply_every crosses the strict/windowed "
                    "boundary at >10M rows). Restore with the same "
                    "sparse_apply_every, or pin the layout with "
                    "--model_params split_tables=true|false"
                )
        tables = {
            k: saver.load_array(step, f"table|{k}", shardings.tables[k])
            for k in template.tables
        }
        scalar_slots = dense.get("scalar_slots", {})

        def load_scalar_slot(k, n, tmpl):
            # Fail LOUDLY if the checkpoint predates this slot (e.g. a
            # per_row-bias adam checkpoint restored into a global-bias
            # build): silently defaulting the counter to 0 would reset
            # bias correction on a converged model.
            if n not in scalar_slots.get(k, {}):
                raise ValueError(
                    f"Checkpoint at step {step} has no scalar slot "
                    f"{k}/{n} — it was written by a build with a "
                    "different optimizer configuration (e.g. adam "
                    "bias_correction='per_row' vs 'global'); restore "
                    "with the matching configuration"
                )
            return self._place_leaf(
                np.asarray(scalar_slots[k][n], dtype=np.asarray(tmpl).dtype),
                shardings.slots[k][n],
            )

        slots = {
            k: {
                n: (
                    load_scalar_slot(k, n, group[n])
                    if not np.ndim(group[n])
                    else saver.load_array(
                        step, f"slot|{k}|{n}", shardings.slots[k][n]
                    )
                )
                for n in group
            }
            for k, group in template.slots.items()
        }
        for k, v in tables.items():
            assert v.shape == template.tables[k].shape, (
                f"Checkpoint table {k} shape {v.shape} != model "
                f"{template.tables[k].shape} (vocab/dim changed?)"
            )
        for k, group in slots.items():
            for n, v in group.items():
                tmpl = template.slots[k][n]
                # .shape/.dtype only — never np.asarray a sharded slot
                # (that would gather the full table to host).
                got = (tuple(np.shape(v)), np.dtype(v.dtype))
                want = (tuple(np.shape(tmpl)), np.dtype(tmpl.dtype))
                assert got == want, (
                    f"Checkpoint slot {k}/{n} is {got} but this build "
                    f"expects {want} — slot layouts changed (e.g. adam "
                    "'t' moved from flat i32 to packed lane f32 in round "
                    "3); re-train or migrate the checkpoint"
                )
        if hasattr(saver, "release"):
            saver.release(step)  # close shard-file handles; restore done
        self._host_step = int(np.asarray(dense["step"]))
        logger.info(
            "Restored sharded checkpoint at step %d (%d tables)",
            self._host_step,
            len(tables),
        )
        return PSTrainState(
            step=self._place_leaf(np.asarray(dense["step"]), shardings.step),
            params=jax.tree.map(
                self._place_leaf, dense["params"], shardings.params
            ),
            opt_state=jax.tree.map(
                self._place_leaf, dense["opt_state"], shardings.opt_state
            ),
            model_state=jax.tree.map(
                self._place_leaf, dense["model_state"], shardings.model_state
            ),
            tables=tables,
            slots=slots,
        )

    def state_to_host(self) -> Optional[PSTrainState]:
        """Host-complete snapshot for checkpointing.  Tables/slots are
        sharded across processes, so this is a COLLECTIVE (allgather) —
        every process must call it, even though only rank 0 writes."""
        if self._state is None:
            return None
        state = self._state
        return PSTrainState(
            step=jax.device_get(state.step),
            params=jax.device_get(state.params),
            opt_state=jax.device_get(state.opt_state),
            model_state=jax.device_get(state.model_state),
            tables={k: shd.gather_to_host(v) for k, v in state.tables.items()},
            slots={
                k: {n: shd.gather_to_host(v) for n, v in group.items()}
                for k, group in state.slots.items()
            },
        )

    def get_variables_numpy(self) -> dict:
        """Flat {path: logical np.ndarray} — packed tables are unpacked to
        their [vocab, dim] shape (the export/serving view).  COLLECTIVE in
        a multi-process world: tables span processes, so materializing
        them is an allgather every rank must join (device_get alone raises
        on non-addressable shards)."""
        if self._state is None:
            return {}
        state = self._state
        flat = {}
        merged = self._merge_params(
            jax.device_get(state.params),
            {
                k: np.asarray(
                    pk.unpack(self._table_specs[k], shd.gather_to_host(v))
                )
                for k, v in state.tables.items()
            },
        )
        tree = {"params": merged, **jax.device_get(state.model_state)}
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            flat[_path_key(path)] = np.asarray(leaf)
        return flat


def _collection_get(tree, module_path: Tuple, name: str):
    """Fetch collection value at tree[module_path...][name], unwrapping
    flax's sow tuple."""
    node = tree
    for part in module_path:
        node = node[part]
    value = node[name]
    if isinstance(value, tuple):  # sow appends into a tuple
        value = value[0]
    return value
