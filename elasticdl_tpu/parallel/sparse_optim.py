"""Sparse row-wise optimizers for sharded embedding tables.

Parity: the reference's native optimizer kernels
(elasticdl/pkg/kernel/capi/kernel_api.cc via elasticdl/pkg/optimizer — the
Eigen-backed SGD/Adam/Momentum/AdaGrad `*SparseApply` paths the Go PS runs
on pushed IndexedSlices).  Here the same math is a few scatter/gather ops
inside the jit-compiled train step: the update touches only the looked-up
rows, slot variables (accumulators/moments) are tables of the same sharded
shape, and XLA routes the scattered rows over ICI to whichever chip owns
them.  elasticdl_tpu/native/kernel_api.cc mirrors these kernels in C++ for
host-side parity testing (golden values shared by both suites).

Semantics notes (same trade-offs as TF's sparse optimizer application):
- SGD / AdaGrad apply duplicate ids additively (scatter-add), which equals
  the exact segment-summed gradient update.
- Momentum/Adam use gather-update-scatter on the touched rows; duplicate
  ids within one minibatch collapse to a single slot update computed from
  their summed gradient (lazy semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SparseOptimizer:
    """A row-wise optimizer: init_slots(table) -> slots dict;
    apply(table, slots, ids, grads) -> (new_table, new_slots).

    ids: int32 [n]; grads: [n, dim] (already flattened by the trainer).
    """

    name: str
    init_slots: Callable[[jnp.ndarray], Dict[str, jnp.ndarray]]
    apply: Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]
    hyperparams: dict = field(default_factory=dict)


def _dedup(ids, grads):
    """Collapse duplicate ids to segment-summed grads with static shapes
    (sort + segment_sum, O(n log n)): returns (sorted_ids, summed_grads,
    is_segment_start).  Each duplicate group's grads are summed at its
    first sorted position; the rest carry zero gradient, so
    gather-update-scatter is well-defined under duplicates."""
    n = ids.shape[0]
    order = jnp.argsort(ids)
    s_ids = ids[order]
    s_grads = grads[order]
    starts = jnp.concatenate(
        [jnp.ones((1,), bool), s_ids[1:] != s_ids[:-1]]
    )
    segments = jnp.cumsum(starts) - 1                       # [n]
    per_segment = jax.ops.segment_sum(s_grads, segments, num_segments=n)
    summed = per_segment[segments] * starts[:, None].astype(grads.dtype)
    return s_ids, summed, starts


def sgd(learning_rate: float = 0.01) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(table):
        return {}

    def apply(table, slots, ids, grads):
        return table.at[ids].add(-lr * grads), slots

    return SparseOptimizer("sgd", init_slots, apply, {"learning_rate": lr})


def momentum(
    learning_rate: float = 0.01, mu: float = 0.9, nesterov: bool = False
) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(table):
        return {"momentum": jnp.zeros_like(table)}

    def apply(table, slots, ids, grads):
        ids, grads, is_first = _dedup(ids, grads)
        # All-zero gradient rows (padding positions, fully-masked batches)
        # must not decay momentum or move the row.
        is_first = is_first & jnp.any(grads != 0, axis=-1)
        v_rows = slots["momentum"][ids]
        v_new = mu * v_rows + grads
        # Slot writes must be scatter-ADDs of deltas: scatter-set with
        # duplicate ids is order-undefined and can let a stale row win.
        delta_v = jnp.where(is_first[:, None], v_new - v_rows, 0.0)
        new_momentum = slots["momentum"].at[ids].add(delta_v)
        step = (mu * v_new + grads) if nesterov else v_new
        new_table = table.at[ids].add(
            jnp.where(is_first[:, None], -lr * step, 0.0)
        )
        return new_table, {"momentum": new_momentum}

    return SparseOptimizer(
        "momentum", init_slots, apply,
        {"learning_rate": lr, "momentum": mu, "nesterov": nesterov},
    )


def adagrad(learning_rate: float = 0.01, epsilon: float = 1e-7) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(table):
        return {"accumulator": jnp.zeros_like(table)}

    def apply(table, slots, ids, grads):
        ids, grads, is_first = _dedup(ids, grads)
        acc = slots["accumulator"].at[ids].add(grads * grads)
        rows = acc[ids]
        update = -lr * grads / (jnp.sqrt(rows) + epsilon)
        new_table = table.at[ids].add(jnp.where(is_first[:, None], update, 0.0))
        return new_table, {"accumulator": acc}

    return SparseOptimizer(
        "adagrad", init_slots, apply,
        {"learning_rate": lr, "epsilon": epsilon},
    )


def adam(
    learning_rate: float = 0.001,
    beta_1: float = 0.9,
    beta_2: float = 0.999,
    epsilon: float = 1e-8,
) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(table):
        return {
            "m": jnp.zeros_like(table),
            "v": jnp.zeros_like(table),
            # Per-row step count for bias correction (the reference's Go
            # Adam keeps a global step; per-row matches lazy semantics).
            "t": jnp.zeros((table.shape[0],), jnp.int32),
        }

    def apply(table, slots, ids, grads):
        ids, grads, is_first = _dedup(ids, grads)
        # Zero-grad rows (padding / masked batches) must not decay moments
        # or advance the per-row step count.
        is_first = is_first & jnp.any(grads != 0, axis=-1)
        t = slots["t"].at[ids].add(is_first.astype(jnp.int32))
        t_rows = jnp.maximum(t[ids], 1).astype(table.dtype)
        m_rows = slots["m"][ids]
        v_rows = slots["v"][ids]
        m_new = beta_1 * m_rows + (1 - beta_1) * grads
        v_new = beta_2 * v_rows + (1 - beta_2) * grads * grads
        # Scatter-ADD deltas (duplicate-safe), zero for non-first rows.
        new_m = slots["m"].at[ids].add(
            jnp.where(is_first[:, None], m_new - m_rows, 0.0)
        )
        new_v = slots["v"].at[ids].add(
            jnp.where(is_first[:, None], v_new - v_rows, 0.0)
        )
        m_hat = m_new / (1 - beta_1 ** t_rows[:, None])
        v_hat = v_new / (1 - beta_2 ** t_rows[:, None])
        update = -lr * m_hat / (jnp.sqrt(v_hat) + epsilon)
        new_table = table.at[ids].add(jnp.where(is_first[:, None], update, 0.0))
        return new_table, {"m": new_m, "v": new_v, "t": t}

    return SparseOptimizer(
        "adam", init_slots, apply,
        {"learning_rate": lr, "beta_1": beta_1, "beta_2": beta_2,
         "epsilon": epsilon},
    )


_BY_NAME = {"sgd": sgd, "momentum": momentum, "adagrad": adagrad, "adam": adam}


def by_name(name: str, **hyperparams) -> SparseOptimizer:
    if name not in _BY_NAME:
        raise ValueError(f"Unknown sparse optimizer {name!r}; have {sorted(_BY_NAME)}")
    return _BY_NAME[name](**hyperparams)
