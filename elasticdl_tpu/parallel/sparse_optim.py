"""Sparse row-wise optimizers for sharded, packed embedding tables.

Parity: the reference's native optimizer kernels
(elasticdl/pkg/kernel/capi/kernel_api.cc via elasticdl/pkg/optimizer — the
Eigen-backed SGD/Adam/Momentum/AdaGrad `*SparseApply` paths the Go PS runs
on pushed IndexedSlices).  elasticdl_tpu/native/kernel_api.cc mirrors the
same math in C++ for host-side parity testing (golden values shared by
both suites).

TPU design (round 2 rewrite — the round-1 version cost 2.9x):

- Tables and slot variables live in PACKED layout (parallel/packed.py):
  [vocab/R, 128] so every memory op is full-lane.  The round-1 layout let
  XLA choose column-major [vocab, dim], making each of sparse-Adam's
  three table-sized scatters ~6.3 ms on the DeepFM step.
- Duplicate-id handling is a packed scatter-add segment-sum
  (`grad_accumulate`) — no argsort, no per-row gather/update/scatter.
- Moment/accumulator updates STREAM over the whole table with a
  touched-row mask (elementwise, perfectly tiled, sharded with the table
  — zero communication) instead of gathering the touched rows.  Per-step
  cost is O(table_size / n_devices) sequential HBM traffic, which for
  lane-packed tables beats the random-access row updates by >10x; the
  measured DeepFM-Adam step went 30 ms -> 2 ms on one chip.

Semantics (identical to round 1 and to the TF sparse-apply contract):
- Duplicate ids within a step contribute their SUMMED gradient and cause
  exactly one slot/row update (the reference dedups IndexedSlices the
  same way).
- Rows whose summed gradient is exactly zero (padding ids, fully-masked
  batches, cancellation) are untouched: no moment decay, no step count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Tuple

import jax.numpy as jnp

from elasticdl_tpu.parallel import packed as pk
from elasticdl_tpu.parallel.packed import PackedSpec


@dataclass(frozen=True)
class SparseOptimizer:
    """A row-wise optimizer over packed tables.

    init_slots(spec, packed_table) -> slots dict (packed layouts);
    apply(spec, packed_table, slots, ids, grads)
        -> (new_packed_table, new_slots).

    ids: int32 [n] LOGICAL row ids; grads: [n, dim] (flattened by the
    trainer).  Helpers `init_slots_logical`/`apply_logical` operate on
    [vocab, dim] arrays for tests and host-side use.
    """

    name: str
    init_slots: Callable[..., Dict[str, jnp.ndarray]]
    apply: Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]
    hyperparams: dict = field(default_factory=dict)

    # -- logical-shape conveniences (tests, host tools) -----------------

    def init_slots_logical(self, table):
        spec = PackedSpec(table.shape[0], table.shape[1])
        return self.init_slots(spec, pk.pack(spec, table))

    def apply_logical(self, table, slots, ids, grads):
        """table [vocab, dim] in/out; slots must come from
        init_slots_logical (packed layouts)."""
        spec = PackedSpec(table.shape[0], table.shape[1])
        new_packed, new_slots = self.apply(
            spec, pk.pack(spec, table), slots, ids, grads
        )
        return pk.unpack(spec, new_packed), new_slots


def _t_slot_shape(spec: PackedSpec) -> tuple:
    # Per-row step counts as a FLAT [vocab_padded] i32 (1-D arrays tile
    # T(1024) with no lane padding; a [blocks, R] i32 would pad R -> 128
    # lanes and waste 128/R x HBM).
    return (spec.vocab_padded,)


def sgd(learning_rate: float = 0.01) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(spec, packed_table):
        return {}

    def apply(spec, packed_table, slots, ids, grads):
        return pk.scatter_add(spec, packed_table, ids, -lr * grads), slots

    return SparseOptimizer("sgd", init_slots, apply, {"learning_rate": lr})


def momentum(
    learning_rate: float = 0.01, mu: float = 0.9, nesterov: bool = False
) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(spec, packed_table):
        return {"momentum": jnp.zeros_like(packed_table)}

    def apply(spec, packed_table, slots, ids, grads):
        acc = pk.grad_accumulate(spec, packed_table, ids, grads)
        touched = pk.broadcast_rows(spec, pk.touched_mask(spec, acc)).astype(
            packed_table.dtype
        )
        v_new = touched * (mu * slots["momentum"] + acc) + (1 - touched) * slots[
            "momentum"
        ]
        step = (mu * v_new + acc) if nesterov else v_new
        new_table = packed_table - lr * touched * step
        return new_table, {"momentum": v_new}

    return SparseOptimizer(
        "momentum", init_slots, apply,
        {"learning_rate": lr, "momentum": mu, "nesterov": nesterov},
    )


def adagrad(learning_rate: float = 0.01, epsilon: float = 1e-7) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(spec, packed_table):
        return {"accumulator": jnp.zeros_like(packed_table)}

    def apply(spec, packed_table, slots, ids, grads):
        acc = pk.grad_accumulate(spec, packed_table, ids, grads)
        new_acc = slots["accumulator"] + acc * acc
        update = -lr * acc / (jnp.sqrt(new_acc) + epsilon)
        return packed_table + update, {"accumulator": new_acc}

    return SparseOptimizer(
        "adagrad", init_slots, apply,
        {"learning_rate": lr, "epsilon": epsilon},
    )


def adam(
    learning_rate: float = 0.001,
    beta_1: float = 0.9,
    beta_2: float = 0.999,
    epsilon: float = 1e-8,
) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(spec, packed_table):
        return {
            "m": jnp.zeros_like(packed_table),
            "v": jnp.zeros_like(packed_table),
            # Per-row step count for bias correction (the reference's Go
            # Adam keeps a global step; per-row matches lazy semantics).
            "t": jnp.zeros(_t_slot_shape(spec), jnp.int32),
        }

    def apply(spec, packed_table, slots, ids, grads):
        acc = pk.grad_accumulate(spec, packed_table, ids, grads)
        touched_rows = pk.touched_mask(spec, acc)  # [blocks, R] bool
        t_new = slots["t"] + touched_rows.reshape((-1,)).astype(jnp.int32)
        touched = pk.broadcast_rows(spec, touched_rows).astype(packed_table.dtype)
        t_rows = pk.broadcast_rows(
            spec,
            jnp.maximum(t_new, 1)
            .reshape((spec.num_blocks, spec.rows_per_block))
            .astype(packed_table.dtype),
        )
        m_new = touched * (beta_1 * slots["m"] + (1 - beta_1) * acc) + (
            1 - touched
        ) * slots["m"]
        v_new = touched * (beta_2 * slots["v"] + (1 - beta_2) * acc * acc) + (
            1 - touched
        ) * slots["v"]
        m_hat = m_new / (1 - beta_1 ** t_rows)
        v_hat = v_new / (1 - beta_2 ** t_rows)
        update = -lr * touched * m_hat / (jnp.sqrt(v_hat) + epsilon)
        return packed_table + update, {"m": m_new, "v": v_new, "t": t_new}

    return SparseOptimizer(
        "adam", init_slots, apply,
        {"learning_rate": lr, "beta_1": beta_1, "beta_2": beta_2,
         "epsilon": epsilon},
    )


_BY_NAME = {"sgd": sgd, "momentum": momentum, "adagrad": adagrad, "adam": adam}


def by_name(name: str, **hyperparams) -> SparseOptimizer:
    if name not in _BY_NAME:
        raise ValueError(f"Unknown sparse optimizer {name!r}; have {sorted(_BY_NAME)}")
    return _BY_NAME[name](**hyperparams)
