"""Sparse row-wise optimizers for sharded, packed embedding tables.

Parity: the reference's native optimizer kernels
(elasticdl/pkg/kernel/capi/kernel_api.cc via elasticdl/pkg/optimizer — the
Eigen-backed SGD/Adam/Momentum/AdaGrad `*SparseApply` paths the Go PS runs
on pushed IndexedSlices).  elasticdl_tpu/native/kernel_api.cc mirrors the
same math in C++ for host-side parity testing (golden values shared by
both suites).

TPU design (round 2 rewrite — the round-1 version cost 2.9x):

- Tables and slot variables live in PACKED layout (parallel/packed.py):
  [vocab/R, 128] so every memory op is full-lane.  The round-1 layout let
  XLA choose column-major [vocab, dim], making each of sparse-Adam's
  three table-sized scatters ~6.3 ms on the DeepFM step.
- Duplicate-id handling is a packed scatter-add segment-sum
  (`grad_accumulate`) — no argsort, no per-row gather/update/scatter.
- Moment/accumulator updates have TWO paths, selected per table at trace
  time (mode="auto"):
  * STREAM: one elementwise pass over the whole table with a touched-row
    mask (perfectly tiled, sharded with the table — zero communication).
    Per-step cost is O(table_size / n_devices) sequential HBM traffic,
    which for lane-packed tables beats random-access row updates by >10x
    at small table sizes; the measured DeepFM-Adam step went 30 ms ->
    2 ms on one chip (2.6M rows).
  * SCATTER (lazy, round 3): sort-free dedup of the batch ids
    (packed.dedup_representatives — two O(n) scatters plus one O(vocab)
    i32 buffer), then gather/update/scatter ONLY the touched rows.
    O(batch) instead of O(table): at the north-star 26M resident rows the
    streaming pass had collapsed DeepFM from 839k to 192k samples/s; this
    path removes the table-size term entirely.
  * FUSED (round 6, opt-in via --sparse_kernel): the scatter path's
    gather/update/scatter trips collapsed into one Pallas kernel
    (ops/sparse_embedding.fused_dedup_apply) that keeps each touched
    row in VMEM between the dedup, the slot math, and the write-back —
    none of the [n, 128] HBM intermediates the XLA formulation
    materializes.  Bit-exact vs the scatter path for adagrad/adam
    (1-ulp documented tolerance on sgd/momentum table writes — see the
    kernel docstring).
  The auto crossover (streaming below ~8 batch-sized table passes,
  scatter above) is set from measurements on the v5e chip; see
  _use_scatter below.  `auto` never selects FUSED on its own until its
  chip numbers land (BASELINE.md queued chip work).

Semantics (identical to round 1 and to the TF sparse-apply contract):
- Duplicate ids within a step contribute their SUMMED gradient and cause
  exactly one slot/row update (the reference dedups IndexedSlices the
  same way).
- Rows whose summed gradient is exactly zero (padding ids, fully-masked
  batches, cancellation) are untouched: no moment decay, no step count.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp

from elasticdl_tpu.parallel import packed as pk
from elasticdl_tpu.parallel.packed import PackedSpec


@dataclass(frozen=True)
class SparseOptimizer:
    """A row-wise optimizer over packed tables.

    init_slots(spec, packed_table) -> slots dict (packed layouts);
    apply(spec, packed_table, slots, ids, grads)
        -> (new_packed_table, new_slots).

    ids: int32 [n] LOGICAL row ids; grads: [n, dim] (flattened by the
    trainer).  Helpers `init_slots_logical`/`apply_logical` operate on
    [vocab, dim] arrays for tests and host-side use.
    """

    name: str
    init_slots: Callable[..., Dict[str, jnp.ndarray]]
    apply: Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]
    hyperparams: dict = field(default_factory=dict)
    # apply_acc(spec, packed_table, slots, acc) -> (table, slots): one
    # optimizer step from an ALREADY-ACCUMULATED packed gradient table
    # (grad_accumulate output).  Semantically identical to apply() on the
    # batch that produced the acc — the dedup contract makes the two
    # interchangeable (pinned by test_apply_acc_matches_apply).  NOTE the
    # trainer's windowed path (ps_trainer sparse_apply_every > 1) calls
    # apply() on the chunk's CONCATENATED (ids, grads), not this — an acc
    # table carried through the step scan costs a full table copy per
    # step (BASELINE.md).  apply_acc serves host-side/offline applies and
    # callers that already hold an accumulated gradient table.
    apply_acc: Optional[Callable] = None
    # remake(mode, mesh=None) -> SparseOptimizer: this optimizer rebuilt
    # with a different apply-mode but identical hyperparameters.  The
    # trainer uses it to honor --sparse_kernel=fused on an optimizer the
    # model spec constructed with the default mode (ps_trainer can't
    # mutate a frozen dataclass whose apply closures captured the mode).
    # `mesh` selects the fused kernels' dispatch route: a multi-device
    # mesh routes fused_dedup_apply through shard_map
    # (ops/sparse_embedding.py "Sharded dispatch").
    remake: Optional[Callable[..., "SparseOptimizer"]] = None

    # -- logical-shape conveniences (tests, host tools) -----------------

    def init_slots_logical(self, table):
        spec = PackedSpec(table.shape[0], table.shape[1])
        return self.init_slots(spec, pk.pack(spec, table))

    def apply_logical(self, table, slots, ids, grads):
        """table [vocab, dim] in/out; slots must come from
        init_slots_logical (packed layouts)."""
        spec = PackedSpec(table.shape[0], table.shape[1])
        new_packed, new_slots = self.apply(
            spec, pk.pack(spec, table), slots, ids, grads
        )
        return pk.unpack(spec, new_packed), new_slots


def _t_slot_shape(spec: PackedSpec) -> tuple:
    # Per-row step counts stored as f32 BROADCAST LANES: same packed shape
    # as the table, each row's count repeated across its dim lanes.  The
    # round-2 flat [vocab_padded] i32 layout was 8x smaller but cost two
    # physical reshape copies per step (measured 3.1 ms/step/table at the
    # 26M-row probe: XLA materializes [vocab] <-> [blocks, R] relayouts)
    # and kept the t update out of the fused m/v/table pass.  Lane-shaped
    # t joins that multi-output fusion and needs no reshapes; f32 counts
    # are exact to 2^24 steps.
    return (spec.num_blocks, spec.block_width)


# Auto mode: measured on the v5e chip at the 26M-row probe (BASELINE.md):
# the streaming pass costs ~27 ns per storage block per step; the scatter
# path is count-bound at ~0.4 us per batch id (dedup buffer RMW + row
# gathers/scatters) — at num_blocks = 15 x n_ids it still measured 2x
# SLOWER than streaming (91 ms vs ~45 ms per step).  Require a wide
# margin before switching: scatter only pays for huge-vocab/small-batch
# regimes (e.g. online-style batches against Criteo-scale tables).
_SCATTER_CROSSOVER = 64


def _use_scatter(spec: PackedSpec, n_ids: int, mode: str) -> bool:
    if mode == "scatter":
        return True
    if mode == "stream":
        return False
    if mode != "auto":
        raise ValueError(f"mode must be auto|stream|scatter, got {mode!r}")
    return spec.num_blocks > _SCATTER_CROSSOVER * n_ids


def select_mode(spec: PackedSpec, n_ids: int, mode: str) -> str:
    """'stream' | 'scatter' | 'fused' for one apply.  `fused` routes the
    whole update through the Pallas dedup+apply kernel
    (ops/sparse_embedding.py); `auto` keeps the measured stream/scatter
    crossover and never picks fused on its own — the fused kernels'
    chip numbers are queued driver work (BASELINE.md), so fused stays
    opt-in (--sparse_kernel) until the evidence lands."""
    if mode == "fused":
        return "fused"
    return (
        "scatter" if _use_scatter(spec, n_ids, mode) else "stream"
    )


def _fused_apply(kind: str, hyper: dict, mesh=None):
    """apply() via the fused Pallas dedup+apply kernel.  Import at
    construction time (host), not trace time.  `mesh` routes the
    kernel's dispatch (single-device pallas_call vs shard_map over a
    multi-device mesh)."""
    from elasticdl_tpu.ops import sparse_embedding as ske

    def apply(spec, packed_table, slots, ids, grads):
        return ske.fused_dedup_apply(
            spec, kind, hyper, packed_table, slots, ids, grads, mesh=mesh
        )

    return apply


def _dual_apply(mode: str, stream_apply_acc, scatter_apply,
                fused_apply=None):
    """The apply dispatcher shared by every slotted optimizer: streaming
    (grad_accumulate + the acc-consuming core), touched-rows scatter, or
    the fused Pallas kernel — chosen per select_mode."""

    def stream_apply(spec, packed_table, slots, ids, grads):
        acc = pk.grad_accumulate(spec, packed_table, ids, grads)
        return stream_apply_acc(spec, packed_table, slots, acc)

    impls = {
        "stream": stream_apply,
        "scatter": scatter_apply,
        "fused": fused_apply,
    }

    def apply(spec, packed_table, slots, ids, grads):
        impl = impls[select_mode(spec, ids.shape[0], mode)]
        if impl is None:
            raise ValueError("this optimizer has no fused kernel path")
        return impl(spec, packed_table, slots, ids, grads)

    return apply


def sgd(learning_rate: float = 0.01, mode: str = "auto",
        mesh=None) -> SparseOptimizer:
    lr = learning_rate
    hyper = {"learning_rate": lr}

    def init_slots(spec, packed_table):
        return {}

    def scatter_or_stream_apply(spec, packed_table, slots, ids, grads):
        # SGD is linear in the gradient, so one scatter-add IS both the
        # stream and the scatter path — no dedup needed.
        return pk.scatter_add(spec, packed_table, ids, -lr * grads), slots

    fused = _fused_apply("sgd", hyper, mesh)

    def apply(spec, packed_table, slots, ids, grads):
        if select_mode(spec, ids.shape[0], mode) == "fused":
            return fused(spec, packed_table, slots, ids, grads)
        return scatter_or_stream_apply(spec, packed_table, slots, ids, grads)

    def apply_acc(spec, packed_table, slots, acc):
        # SGD is linear in the gradient, so the windowed apply is EXACTLY
        # the sum of the per-step applies.
        return packed_table - lr * acc, slots

    return SparseOptimizer(
        "sgd", init_slots, apply, hyper, apply_acc,
        remake=lambda m, mesh=None: sgd(learning_rate, mode=m, mesh=mesh),
    )


def momentum(
    learning_rate: float = 0.01,
    mu: float = 0.9,
    nesterov: bool = False,
    mode: str = "auto",
    mesh=None,
) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(spec, packed_table):
        return {"momentum": jnp.zeros_like(packed_table)}

    def stream_apply_acc(spec, packed_table, slots, acc):
        touched = pk.broadcast_rows(spec, pk.touched_mask(spec, acc)).astype(
            packed_table.dtype
        )
        v_new = touched * (mu * slots["momentum"] + acc) + (1 - touched) * slots[
            "momentum"
        ]
        step = (mu * v_new + acc) if nesterov else v_new
        new_table = packed_table - lr * touched * step
        return new_table, {"momentum": v_new}

    def scatter_apply(spec, packed_table, slots, ids, grads):
        uids, gsum, touched = pk.dedup_representatives(spec, ids, grads)
        tch = touched.astype(packed_table.dtype)[:, None]  # [n, 1]
        gsum = gsum * tch
        v_rows = pk.lookup(spec, slots["momentum"], uids)
        v_new_rows = mu * v_rows + gsum
        step = (mu * v_new_rows + gsum) if nesterov else v_new_rows
        new_v = pk.scatter_add(spec, slots["momentum"], uids,
                               (v_new_rows - v_rows) * tch)
        new_table = pk.scatter_add(spec, packed_table, uids, -lr * tch * step)
        return new_table, {"momentum": new_v}

    hyper = {"learning_rate": lr, "momentum": mu, "nesterov": nesterov}
    return SparseOptimizer(
        "momentum", init_slots,
        _dual_apply(mode, stream_apply_acc, scatter_apply,
                    _fused_apply("momentum", hyper, mesh)),
        hyper,
        stream_apply_acc,
        remake=lambda m, mesh=None: momentum(
            learning_rate, mu, nesterov, mode=m, mesh=mesh
        ),
    )


def adagrad(
    learning_rate: float = 0.01, epsilon: float = 1e-7, mode: str = "auto",
    mesh=None,
) -> SparseOptimizer:
    lr = learning_rate

    def init_slots(spec, packed_table):
        return {"accumulator": jnp.zeros_like(packed_table)}

    def stream_apply_acc(spec, packed_table, slots, acc):
        new_acc = slots["accumulator"] + acc * acc
        update = -lr * acc / (jnp.sqrt(new_acc) + epsilon)
        return packed_table + update, {"accumulator": new_acc}

    def scatter_apply(spec, packed_table, slots, ids, grads):
        uids, gsum, touched = pk.dedup_representatives(spec, ids, grads)
        tch = touched.astype(packed_table.dtype)[:, None]
        gsum = gsum * tch
        acc_rows = pk.lookup(spec, slots["accumulator"], uids)
        new_acc_rows = acc_rows + gsum * gsum
        update = -lr * gsum / (jnp.sqrt(new_acc_rows) + epsilon)
        new_acc = pk.scatter_add(spec, slots["accumulator"], uids, gsum * gsum)
        new_table = pk.scatter_add(spec, packed_table, uids, update)
        return new_table, {"accumulator": new_acc}

    hyper = {"learning_rate": lr, "epsilon": epsilon}
    return SparseOptimizer(
        "adagrad", init_slots,
        _dual_apply(mode, stream_apply_acc, scatter_apply,
                    _fused_apply("adagrad", hyper, mesh)),
        hyper,
        stream_apply_acc,
        remake=lambda m, mesh=None: adagrad(
            learning_rate, epsilon, mode=m, mesh=mesh
        ),
    )


def adam(
    learning_rate: float = 0.001,
    beta_1: float = 0.9,
    beta_2: float = 0.999,
    epsilon: float = 1e-8,
    mode: str = "auto",
    bias_correction: str = "per_row",
    mesh=None,
) -> SparseOptimizer:
    """Sparse Adam.

    bias_correction:
    - "per_row" (default): each row's correction uses ITS OWN touch count
      (lazy semantics; matches the golden native-kernel contract).  Costs
      a table-sized `t` slot plus its share of the streaming pass.
    - "global": correction uses one shared apply counter — what the
      reference's Go Adam actually does (†pkg/optimizer adam with a global
      step; TF's Adam on sparse grads behaves the same).  Rows first
      touched late are slightly over-corrected, and the table-sized `t`
      slot disappears — at the 26M-row probe that is 1.66 GB of HBM and
      ~3 ms/step of streaming traffic.
    """
    lr = learning_rate
    if bias_correction not in ("per_row", "global"):
        raise ValueError(
            f"bias_correction must be per_row|global, got {bias_correction!r}"
        )
    per_row = bias_correction == "per_row"

    def init_slots(spec, packed_table):
        slots = {
            "m": jnp.zeros_like(packed_table),
            "v": jnp.zeros_like(packed_table),
        }
        if per_row:
            # Lane-broadcast f32 layout — see _t_slot_shape.
            slots["t"] = jnp.zeros(_t_slot_shape(spec), jnp.float32)
        else:
            slots["t_global"] = jnp.zeros((), jnp.float32)
        return slots

    def stream_apply_acc(spec, packed_table, slots, acc):
        touched = pk.broadcast_rows(spec, pk.touched_mask(spec, acc)).astype(
            packed_table.dtype
        )
        new_slots = {}
        if per_row:
            # Pad lanes stay zero (scatter mode's expand_updates zero-pads).
            t_new = slots["t"] + touched * pk.real_lane_mask(
                spec, packed_table.dtype
            )
            t_rows = jnp.maximum(t_new, 1.0)
            new_slots["t"] = t_new
        else:
            t_rows = slots["t_global"] + 1.0
            new_slots["t_global"] = t_rows
        m_new = touched * (beta_1 * slots["m"] + (1 - beta_1) * acc) + (
            1 - touched
        ) * slots["m"]
        v_new = touched * (beta_2 * slots["v"] + (1 - beta_2) * acc * acc) + (
            1 - touched
        ) * slots["v"]
        m_hat = m_new / (1 - beta_1 ** t_rows)
        v_hat = v_new / (1 - beta_2 ** t_rows)
        update = -lr * touched * m_hat / (jnp.sqrt(v_hat) + epsilon)
        new_slots["m"] = m_new
        new_slots["v"] = v_new
        return packed_table + update, new_slots

    def scatter_apply(spec, packed_table, slots, ids, grads):
        uids, gsum, touched = pk.dedup_representatives(spec, ids, grads)
        tch = touched.astype(packed_table.dtype)[:, None]
        gsum = gsum * tch
        m_rows = pk.lookup(spec, slots["m"], uids)
        v_rows = pk.lookup(spec, slots["v"], uids)
        new_slots = {}
        if per_row:
            t_rows = pk.lookup(spec, slots["t"], uids)[:, :1]  # [n, 1]
            tr = jnp.maximum(t_rows + tch, 1.0)
            new_slots["t"] = pk.scatter_add(
                spec, slots["t"], uids,
                jnp.broadcast_to(tch, (tch.shape[0], spec.dim)),
            )
        else:
            t_global = slots["t_global"] + 1.0
            tr = t_global
            new_slots["t_global"] = t_global
        m_new_rows = beta_1 * m_rows + (1 - beta_1) * gsum
        v_new_rows = beta_2 * v_rows + (1 - beta_2) * gsum * gsum
        m_hat = m_new_rows / (1 - beta_1 ** tr)
        v_hat = v_new_rows / (1 - beta_2 ** tr)
        update = -lr * tch * m_hat / (jnp.sqrt(v_hat) + epsilon)
        new_slots["m"] = pk.scatter_add(spec, slots["m"], uids,
                                        (m_new_rows - m_rows) * tch)
        new_slots["v"] = pk.scatter_add(spec, slots["v"], uids,
                                        (v_new_rows - v_rows) * tch)
        new_table = pk.scatter_add(spec, packed_table, uids, update)
        return new_table, new_slots

    hyper = {"learning_rate": lr, "beta_1": beta_1, "beta_2": beta_2,
             "epsilon": epsilon, "bias_correction": bias_correction}
    return SparseOptimizer(
        "adam", init_slots,
        _dual_apply(mode, stream_apply_acc, scatter_apply,
                    _fused_apply("adam", hyper, mesh)),
        hyper,
        stream_apply_acc,
        remake=lambda m, mesh=None: adam(
            learning_rate, beta_1, beta_2, epsilon, mode=m,
            bias_correction=bias_correction, mesh=mesh,
        ),
    )


_BY_NAME = {"sgd": sgd, "momentum": momentum, "adagrad": adagrad, "adam": adam}


def by_name(name: str, **hyperparams) -> SparseOptimizer:
    if name not in _BY_NAME:
        raise ValueError(f"Unknown sparse optimizer {name!r}; have {sorted(_BY_NAME)}")
    return _BY_NAME[name](**hyperparams)
