"""Device mesh construction and (re-)formation.

The reference scales data-parallel training over NCCL/Gloo rings whose
membership is managed by FTlib gossip or Horovod's Gloo rendezvous
(SURVEY.md §2.1).  On TPU the communicator *is* the compiled program: we
build a `jax.sharding.Mesh` over the visible devices and let XLA lower
`psum`/`all_gather`/`all_to_all` onto ICI.  Elasticity then means
re-building the mesh over the surviving process set (see
elasticdl_tpu.parallel.elastic), not re-building a ring library.

Axis conventions (used across the framework):

- ``data``  — data parallel (batch dim).  Always present.
- ``model`` — tensor/model parallel (embedding-table shards, matmul
  sharding).  Size 1 unless requested.

A mesh of shape (data, model) covers every parallelism the reference has
(data parallel + PS-partitioned embedding tables, SURVEY.md §2.6) and is
the substrate the sharded embedding engine rides on.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("parallel.mesh")

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. -1 for `data` means "all remaining devices"."""

    data: int = -1
    model: int = 1

    def resolve(self, n_devices: int) -> tuple[int, int]:
        model = max(1, self.model)
        if n_devices % model != 0:
            raise ValueError(
                f"model axis {model} does not divide device count {n_devices}"
            )
        data = self.data if self.data != -1 else n_devices // model
        if data * model != n_devices:
            raise ValueError(
                f"mesh {data}x{model} != device count {n_devices}"
            )
        return data, model


def build_mesh(
    config: MeshConfig = MeshConfig(),
    devices: Optional[Sequence] = None,
):
    """Build a 2-D (data, model) Mesh over `devices` (default: all)."""
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    data, model = config.resolve(len(devices))
    mesh = Mesh(
        np.asarray(devices).reshape(data, model), (DATA_AXIS, MODEL_AXIS)
    )
    logger.info(
        "Built mesh %dx%d (%s x %s) over %d %s device(s)",
        data,
        model,
        DATA_AXIS,
        MODEL_AXIS,
        len(devices),
        devices[0].platform,
    )
    return mesh


def force_virtual_cpu_devices(n: int) -> None:
    """Emulate an n-chip slice on CPU (must run before jax backend init).

    This is the test-harness fake-device layer (SURVEY.md §4): pjit/psum/
    mesh-reformation logic runs identically on n virtual CPU devices and on
    a real TPU slice.
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
