"""Worker-side elastic world membership and collective task flow.

Parity: the reference's elastic-Horovod worker path
(worker/allreduce_trainer.py + master rendezvous, SURVEY.md §3.4): workers
ask the master `get_comm_rank`, join the communicator, and re-join when
membership changes.  TPU design: "the communicator" is a jax.distributed
world + Mesh; joining = `jax.distributed.initialize` with the assigned
(rank, world, coordinator).  A member death fatally kills the whole world
(see master/pod_manager.py), so re-join happens in a fresh process after
the pod manager re-forms the world — this module is what that fresh
process runs.

Task flow in a multi-process world: rank 0 pulls tasks from the master and
broadcasts them to all ranks as a tiny fixed-shape collective; every rank
processes its contiguous slice of each *global* minibatch, so all ranks
execute the same number of (collective) train steps per task — the lockstep
invariant jit-compiled SPMD requires.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np

from elasticdl_tpu import obs
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.obs import goodput
from elasticdl_tpu.proto import elasticdl_pb2 as pb

logger = get_logger("parallel.elastic")


@dataclass
class WorldInfo:
    rank: int
    world_size: int
    rendezvous_id: int
    coordinator_addr: str

    @property
    def is_leader(self) -> bool:
        return self.rank == 0


def advertised_host() -> str:
    """The address this worker tells the rendezvous to reach it at.
    On Kubernetes the pod IP is injected as MY_POD_IP (k8s_client pod
    rendering); ELASTICDL_WORKER_HOST overrides for bespoke networks;
    single-host worlds fall back to loopback."""
    import os

    return (
        os.environ.get("ELASTICDL_WORKER_HOST", "")
        or os.environ.get("MY_POD_IP", "")
        or "127.0.0.1"
    )


def join_world(
    master_client,
    poll_interval_s: float = 0.5,
    timeout_s: float = 300.0,
    initialization_timeout_s: int = 120,
) -> WorldInfo:
    """Poll the master rendezvous until this worker has a rank AND the
    coordinator is resolved, then join the jax.distributed world (no-op
    for world_size == 1).

    Each poll carries this worker's advertised host: in deferred-host
    worlds (Kubernetes) the coordinator address can only resolve after
    rank 0 has advertised, and advertising must repeat because a world
    re-declaration discards previously reported hosts.  Advertising rides
    the rank poll, never the liveness channel — a heartbeat during world
    formation would collapse the rendezvous startup grace to the (much
    shorter) steady-state liveness timeout and get healthy workers killed
    while peers are still pulling images.
    """
    deadline = time.time() + timeout_s
    host = advertised_host()
    # Worker-side goodput accounting: everything from the first rank poll
    # to the coordination barrier completing is rendezvous time (this
    # process's ledger — the master accounts its own half).
    with goodput.ledger().phase("rendezvous", cause="join_world"):
        return _join_world_inner(
            master_client, poll_interval_s, deadline, host,
            initialization_timeout_s,
        )


def _join_world_inner(
    master_client, poll_interval_s, deadline, host,
    initialization_timeout_s,
) -> WorldInfo:
    while True:
        resp = master_client.get_comm_rank(host)
        if (
            resp.rank_id >= 0
            and resp.world_size > 0
            and (resp.world_size == 1 or resp.coordinator_addr)
        ):
            break
        if time.time() > deadline:
            raise TimeoutError(
                f"Worker {master_client.worker_id} never received a rank "
                f"(last world_size={resp.world_size}, "
                f"coordinator={resp.coordinator_addr!r})"
            )
        time.sleep(poll_interval_s)
    info = WorldInfo(
        rank=resp.rank_id,
        world_size=resp.world_size,
        rendezvous_id=resp.rendezvous_id,
        coordinator_addr=resp.coordinator_addr,
    )
    if info.world_size > 1:
        import jax

        logger.info(
            "Joining world %d: rank %d/%d via %s",
            info.rendezvous_id,
            info.rank,
            info.world_size,
            info.coordinator_addr,
        )
        # Span: the worker-side half of world-formation cost (the
        # distributed-init barrier) — the master-side half is
        # elasticdl_rendezvous_formation_duration_seconds.
        with obs.span(
            "worker.join_world",
            rendezvous_id=info.rendezvous_id,
            rank=info.rank,
            world_size=info.world_size,
        ):
            jax.distributed.initialize(
                coordinator_address=info.coordinator_addr,
                num_processes=info.world_size,
                process_id=info.rank,
                initialization_timeout=initialization_timeout_s,
            )
    return info


class HeartbeatReporter:
    """Background liveness heartbeats to the master (failure-detection
    plane: the pod manager kills workers whose heartbeats go silent, which
    converts hangs into the process-exit signal churn handling reacts to).

    The heartbeat is also the TELEMETRY CARRIER: when a WorkerTelemetry
    collector (obs/telemetry.py) is attached, each beat ships its bounded
    snapshot in `ReportWorkerLivenessRequest.telemetry_json` — per-worker
    observability with zero new RPCs.  Intervals carry ±`JITTER` of
    deterministic per-worker jitter so a fleet that just re-formed (every
    worker's clock started at the same rendezvous barrier) doesn't
    heartbeat the master in lockstep."""

    WARN_INTERVAL_S = 60.0
    #: Fractional interval jitter (0.2 = ±20%).
    JITTER = 0.2

    def __init__(
        self,
        master_client,
        world: WorldInfo,
        host: str = "",
        interval_s: float = 5.0,
        telemetry=None,
        jitter: float = JITTER,
    ):
        import threading

        self._mc = master_client
        self._world = world
        self._host = host or advertised_host()
        self._interval_s = interval_s
        self._telemetry = telemetry
        self._jitter = float(jitter)
        self._stop = threading.Event()
        #: Consecutive/total failed heartbeats (tests and ops read these —
        #: a silently-dead liveness plane looks exactly like a healthy one
        #: from the worker side otherwise).
        self.error_count = 0
        self._last_warn_monotonic: Optional[float] = None
        self._thread = threading.Thread(
            target=self._loop, name="worker-heartbeat", daemon=True
        )

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def jittered_interval_s(self, tick: int) -> float:
        """Interval for beat `tick`: uniform in [1-J, 1+J] x interval,
        seeded from (worker, tick) — deterministic per worker (replayable
        schedules, same rule as the RPC backoff jitter) yet decorrelated
        across the fleet."""
        if not self._jitter:
            return self._interval_s
        import random

        u = random.Random(f"hb:{self._mc.worker_id}:{tick}").random()
        return self._interval_s * (1.0 - self._jitter + 2.0 * self._jitter * u)

    def _loop(self):
        tick = 0
        while not self._stop.wait(self.jittered_interval_s(tick)):
            tick += 1
            payload = ""
            if self._telemetry is not None:
                try:
                    payload = self._telemetry.snapshot_json()
                except Exception:
                    payload = ""  # telemetry must never kill the liveness plane
            try:
                if payload:
                    # Clock probe around the carrying RPC: the send/recv
                    # wall stamps journal (this process's journal) as a
                    # `clock_probe`, paired by the trace assembler with
                    # the master's worker_telemetry event (same
                    # worker_ts) to estimate this worker's clock offset
                    # by the midpoint method — the heartbeat doubles as
                    # the time-sync plane with zero new RPCs.
                    t_send = time.time()
                    self._mc.report_worker_liveness(
                        self._host, self._world.rendezvous_id,
                        telemetry_json=payload,
                    )
                    t_recv = time.time()
                    probe_ts = getattr(
                        self._telemetry, "last_snapshot_ts", 0.0
                    )
                    if probe_ts:
                        obs.journal().record(
                            "clock_probe",
                            worker_id=self._mc.worker_id,
                            probe_ts=probe_ts,
                            t_send=round(t_send, 6),
                            t_recv=round(t_recv, 6),
                            rtt_s=round(t_recv - t_send, 6),
                        )
                else:
                    self._mc.report_worker_liveness(
                        self._host, self._world.rendezvous_id
                    )
            except Exception as exc:
                # Master unreachable: the process-manager side owns the
                # failure, but say so (rate-limited) — a heartbeat plane
                # that swallows every error is indistinguishable from one
                # that works, until the pod manager kills this "hung"
                # worker for silence.
                self.error_count += 1
                now = time.monotonic()
                if (
                    self._last_warn_monotonic is None
                    or now - self._last_warn_monotonic >= self.WARN_INTERVAL_S
                ):
                    self._last_warn_monotonic = now
                    logger.warning(
                        "Liveness heartbeat to master failed (%s: %s); "
                        "%d failure(s) so far — the pod manager may kill "
                        "this worker if heartbeats stay silent",
                        type(exc).__name__, exc, self.error_count,
                    )


# ---------------------------------------------------------------------------
# Task broadcast: rank 0 is the only master-facing rank for task dispatch.
# ---------------------------------------------------------------------------

_TASK_ENC_LEN = 7  # task_id, shard_idx, start, end, type, model_version, epoch


def _encode_task(task: Optional[pb.Task], shard_names: List[str]) -> np.ndarray:
    if task is None:
        return np.full((_TASK_ENC_LEN,), -1, np.int64)
    shard_idx = shard_names.index(task.shard_name) if task.shard_name else -1
    return np.asarray(
        [task.task_id, shard_idx, task.start, task.end, task.type,
         task.model_version, task.epoch],
        np.int64,
    )


def _decode_task(arr: np.ndarray, shard_names: List[str]) -> pb.Task:
    task_id, shard_idx, start, end, type_, version, epoch = (int(v) for v in arr)
    return pb.Task(
        task_id=task_id,
        shard_name=shard_names[shard_idx] if shard_idx >= 0 else "",
        start=start,
        end=end,
        type=type_,
        model_version=version,
        epoch=epoch,
    )


def broadcast_task(
    task: Optional[pb.Task], shard_names: List[str], world: WorldInfo,
    anatomy=None,
) -> pb.Task:
    """All ranks call this; rank 0 supplies the task, everyone returns it.

    `shard_names` must be identical (same order) on every rank — it comes
    from the deterministic data reader shard listing each rank builds.

    `anatomy` (obs/stepstats.StepAnatomy, optional) books the broadcast
    wall under `data_wait` on NON-leader ranks: for them this collective
    IS the task-queue wait (they block here while rank 0 talks to the
    master), and the step-anatomy ledger would otherwise blame the gap
    on whatever phase ran last.  Booked after the fact and only for real
    tasks — a WAIT poll is queue idleness (the goodput ledger's `idle`),
    not data starvation, and must not corrupt the anatomy.  The leader's
    wait (get_task + this broadcast) is booked by its own task loop.
    """
    if world.world_size == 1:
        assert task is not None
        return task
    from jax.experimental import multihost_utils

    start = time.monotonic()
    encoded = multihost_utils.broadcast_one_to_all(
        _encode_task(task, shard_names), is_source=world.is_leader
    )
    if world.is_leader and task is not None:
        # The leader keeps its ORIGINAL task object: the fixed-shape
        # encoding drops string fields (trace_id), and the leader is the
        # only rank that reports results — its trace id must survive the
        # broadcast round-trip.
        return task
    decoded = _decode_task(np.asarray(encoded), shard_names)
    if (
        anatomy is not None
        and not world.is_leader
        and decoded.task_id != -1
        and decoded.type != pb.WAIT
    ):
        anatomy.note_phase_seconds("data_wait", time.monotonic() - start)
    return decoded


# ---------------------------------------------------------------------------
# Lockstep global batching.
# ---------------------------------------------------------------------------

def iter_local_batch_ranges(
    task_start: int,
    task_end: int,
    per_rank_batch: int,
    world: WorldInfo,
) -> Iterator[Tuple[int, int, int]]:
    """Yield (lo, hi, global_real) for this rank, one tuple per global step.

    Global batch b covers records [task_start + b*W*B, ...); rank r's slice
    is the r-th contiguous B-record chunk of it.  Every rank yields the same
    number of tuples (possibly with empty [lo, lo) slices at the ragged
    tail), preserving the lockstep-collective invariant; `global_real` is
    the batch's real record count across all ranks (for masking/metrics).
    """
    total = task_end - task_start
    global_batch = per_rank_batch * world.world_size
    n_steps = max(1, -(-total // global_batch)) if total > 0 else 0
    for b in range(n_steps):
        g_lo = task_start + b * global_batch
        g_hi = min(g_lo + global_batch, task_end)
        lo = min(g_lo + world.rank * per_rank_batch, g_hi)
        hi = min(lo + per_rank_batch, g_hi)
        yield lo, hi, g_hi - g_lo


def per_rank_real_counts(
    global_real: int, per_rank_batch: int, world_size: int
) -> List[int]:
    """How many real (non-pad) rows each rank contributed to a global batch
    (deterministically reconstructible by any rank — used to strip padding
    from gathered eval outputs)."""
    counts = []
    remaining = global_real
    for _ in range(world_size):
        take = min(per_rank_batch, max(0, remaining))
        counts.append(take)
        remaining -= take
    return counts
