"""Packed (lane-tiled) storage for narrow embedding tables.

Why this exists — the TPU memory-layout problem for embedding tables:
XLA tiles 2-D f32 arrays as T(8,128) (8 sublanes x 128 lanes).  A logical
[vocab, dim] table with small dim (CTR models use 1..32) is hostile to
that tiling either way:

- row-major {1,0}: the minor (lane) dimension `dim` pads to 128 ->
  128/dim x HBM blow-up (16x for dim=8).  XLA refuses.
- column-major {0,1} (what XLA picks): one embedding row's `dim` floats
  sit `vocab` elements apart, so every row gather/scatter touches `dim`
  far-apart tiles.  Measured on the DeepFM step (SURVEY §2.5 config 4):
  the three [2.6M, 8] scatter-adds of the sparse-Adam update ran ~6.3 ms
  EACH — 19 ms of a 30 ms step.

The fix is to make the physical shape lane-shaped: store the table as
[vocab/R, 128] where R = 128/dim_padded rows pack into one 128-lane
storage row.  Then:

- lookup  = gather of full 512-byte storage rows (fast path) + a tiny
  one-hot einsum to select the packed slot (MXU work, no per-element
  gather — `take_along_axis` on lanes lowers to a serialized gather and
  measured 250 ms for a batch; the einsum is ~0).
- scatter = tile the update to 128 lanes, mask to the right slot, and
  scatter-add full storage rows.
- optimizer slot updates stream over the whole (sharded) table with a
  touched-row mask instead of gather/update/scatter of individual rows
  (see parallel/sparse_optim.py).

Parity note: this module replaces the row-partitioned embedding storage
of the reference's Go parameter server (elasticdl/pkg/ps/parameters.go,
embedding.go — a hash map of vocab-row slices per PS pod).  The sharding
story is unchanged (dim 0, now storage blocks, spreads over the mesh);
only the per-device physical layout is TPU-shaped.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

LANES = 128

# Opt-in out-of-vocabulary diagnostics (--oov_diagnostics / env
# ELASTICDL_OOV_DEBUG).  The fixed-vocab contract (docs/design.md
# "Fixed-vocabulary embedding tables"): ids outside [0, vocab) contribute
# zeros and receive no update — the reference's Go PS instead lazily
# GREW a row on first lookup, so a ported open-vocabulary model loses
# updates silently here.  With diagnostics on, the Embedding layer
# reports per-step OOV counts (jax.debug.print host callback) so that
# migration gap is visible instead of silent.
import os as _os

_OOV_DEBUG = _os.environ.get("ELASTICDL_OOV_DEBUG", "").strip().lower() in (
    "1", "true", "yes", "on",
)


def set_oov_debug(enabled: bool) -> None:
    global _OOV_DEBUG
    _OOV_DEBUG = bool(enabled)


def oov_debug_enabled() -> bool:
    return _OOV_DEBUG


def _pad_dim(dim: int) -> int:
    """Smallest power-of-two >= dim that divides 128, or a multiple of 128
    for wide rows (which need no packing).

    Power-of-two is a measured requirement, not cosmetics: a round-3
    experiment packed dim 9 at its own stride (block_width 126) to save
    the 78% pad HBM, and the per-step grad scatter went 4.1 ms -> 15.8 ms
    at the 26M-row probe — non-tile-aligned storage rows make every
    scatter straddle 128-lane tiles.  Pad waste is the cheaper poison."""
    if dim >= LANES:
        return -(-dim // LANES) * LANES
    p = 1
    while p < dim:
        p *= 2
    return p


@dataclass(frozen=True)
class PackedSpec:
    """Static description of one packed table."""

    vocab_size: int
    dim: int

    @property
    def dim_padded(self) -> int:
        return _pad_dim(self.dim)

    @property
    def rows_per_block(self) -> int:
        return max(1, LANES // self.dim_padded)

    @property
    def vocab_padded(self) -> int:
        r = self.rows_per_block
        return -(-self.vocab_size // r) * r

    @property
    def num_blocks(self) -> int:
        return self.vocab_padded // self.rows_per_block

    @property
    def block_width(self) -> int:
        return self.rows_per_block * self.dim_padded  # == LANES for dim<128

    @property
    def packed_shape(self) -> tuple:
        return (self.num_blocks, self.block_width)


def pack(spec: PackedSpec, table):
    """[vocab, dim] -> packed [num_blocks, block_width]."""
    table = jnp.asarray(table)
    v_pad = spec.vocab_padded - table.shape[0]
    d_pad = spec.dim_padded - table.shape[1]
    if v_pad or d_pad:
        table = jnp.pad(table, ((0, v_pad), (0, d_pad)))
    return table.reshape(spec.packed_shape)


def unpack(spec: PackedSpec, packed):
    """packed [num_blocks, block_width] -> logical [vocab, dim]."""
    logical = jnp.asarray(packed).reshape(spec.vocab_padded, spec.dim_padded)
    return logical[: spec.vocab_size, : spec.dim]


def mark_iid(initializer):
    """Tag an initializer as elementwise-i.i.d. (its distribution does not
    depend on the shape argument — uniform/normal with fixed scale), which
    lets packed_init generate DIRECTLY in packed storage shape.  That
    matters at scale: the logical->packed relayout of a [26M, 9] init
    crashes the TPU compiler outright (tpu_compile_helper exit 1,
    reproducible round 3)."""
    initializer.packed_iid_safe = True
    return initializer


def packed_init(spec: PackedSpec, initializer):
    """Wrap an initializer so it produces the packed storage shape (flax
    param init shim).

    Initializers tagged with `mark_iid` generate directly in the packed
    shape (distribution-identical for i.i.d. draws) with pad cells zeroed.
    Untagged initializers may be shape-DEPENDENT (fan-scaled variance,
    row-indexed conventions), so they are invoked with the logical
    (vocab, dim) shape and repacked — correct for any initializer, but the
    relayout does not compile on TPU past ~10M-row tables (see mark_iid);
    tag large-table initializers i.i.d. or initialize on host.
    """

    def init(key, shape, dtype=jnp.float32):
        assert tuple(shape) == spec.packed_shape, (shape, spec)
        if not getattr(initializer, "packed_iid_safe", False):
            return pack(spec, initializer(key, (spec.vocab_size, spec.dim), dtype))
        packed = initializer(key, spec.packed_shape, dtype)
        r = spec.rows_per_block
        d = spec.dim_padded
        # Zero pad rows/lanes so the packed invariant (pad cells == 0)
        # holds from the start.
        row = (
            jnp.arange(spec.num_blocks, dtype=jnp.int32)[:, None] * r
            + jnp.arange(spec.block_width, dtype=jnp.int32)[None, :] // d
        )
        mask = row < spec.vocab_size
        if spec.dim != d:
            mask = mask & (
                jnp.arange(spec.block_width, dtype=jnp.int32)[None, :] % d
                < spec.dim
            )
        return jnp.where(mask, packed, jnp.zeros((), dtype))

    return init


def lookup(spec: PackedSpec, packed, ids):
    """Gather logical rows: ids [n] int32 -> [n, dim].

    Storage-row gather (contiguous 512B rows) + one-hot einsum slot
    select.  NEVER use take_along_axis here: lane-indexed gathers
    serialize on TPU (measured 250 ms vs ~0 for the einsum).
    """
    r = spec.rows_per_block
    d = spec.dim_padded
    rows = jnp.take(packed, ids // r, axis=0)  # [n, block_width]
    if r == 1:
        return rows[:, : spec.dim]
    rows = rows.reshape((-1, r, d))
    sel = jax.nn.one_hot(ids % r, r, dtype=packed.dtype)  # [n, r]
    # precision=HIGHEST: at default MXU precision this matmul would round
    # the f32 table values to bf16 on every lookup (and its gradient).
    # The selector contraction is tiny, so exactness costs nothing.
    out = jnp.einsum(
        "nrd,nr->nd", rows, sel, precision=jax.lax.Precision.HIGHEST
    )
    return out[:, : spec.dim]


def expand_updates(spec: PackedSpec, ids, updates):
    """(ids [n], updates [n, dim]) -> (block_ids [n], rows [n, block_width])
    where each output row holds the update in its packed slot and zeros
    elsewhere.  `scatter-add(packed, block_ids, rows)` then applies the
    update with full-storage-row writes (duplicates sum, as scatter-add
    must).

    Negative ids (padding) are routed to an out-of-bounds-HIGH block so
    the scatter DROPS them: JAX scatters drop positive out-of-bounds
    indices but WRAP negative ones numpy-style, which would silently add
    padding grads into the last storage block."""
    r = spec.rows_per_block
    d = spec.dim_padded
    n = ids.shape[0]
    if spec.dim != d:
        updates = jnp.pad(updates, ((0, 0), (0, d - spec.dim)))
    dropped = jnp.asarray(spec.num_blocks, ids.dtype)
    if r == 1:
        return jnp.where(ids >= 0, ids, dropped), updates
    tiled = jnp.tile(updates, (1, r))  # [n, block_width]; lane l holds updates[:, l % d]
    lane_row = jnp.arange(spec.block_width, dtype=ids.dtype) // d  # [bw]
    mask = (lane_row[None, :] == (ids % r)[:, None]).astype(updates.dtype)
    return jnp.where(ids >= 0, ids // r, dropped), tiled * mask


def scatter_add(spec: PackedSpec, packed, ids, updates):
    """packed[ids] += updates, packed-layout fast path."""
    block_ids, rows = expand_updates(spec, ids, updates)
    return packed.at[block_ids].add(rows)


def grad_accumulate(spec: PackedSpec, packed_like, ids, grads):
    """Segment-sum grads by row, in packed layout: returns acc with
    acc[row] = sum of grads over every occurrence of that row in `ids`
    (zeros elsewhere).  This IS the dedup: duplicate ids sum, exactly like
    the reference's IndexedSlices -> unsorted_segment_sum before its Eigen
    sparse-apply kernels (elasticdl/pkg/kernel/capi)."""
    block_ids, rows = expand_updates(spec, ids, grads)
    return jnp.zeros_like(packed_like).at[block_ids].add(rows)


def touched_mask(spec: PackedSpec, acc):
    """[num_blocks, rows_per_block] bool: rows whose summed gradient is
    nonzero.  Zero-summed rows (padding ids, fully-masked batches, exact
    cancellation) must not decay optimizer moments — same contract as the
    sorted-dedup implementation this replaced."""
    r = spec.rows_per_block
    d = spec.dim_padded
    return jnp.any(acc.reshape((-1, r, d)) != 0, axis=-1)


def real_lane_mask(spec: PackedSpec, dtype=jnp.float32):
    """[block_width] mask: 1 on lanes holding real dims, 0 on pad lanes.
    Keeps the invariant that pad lanes of every packed array stay zero
    (scatter-side expand_updates zero-pads; streaming updates must mask)."""
    lane = jnp.arange(spec.block_width)
    return ((lane % spec.dim_padded) < spec.dim).astype(dtype)


def broadcast_rows(spec: PackedSpec, per_row):
    """[num_blocks, rows_per_block] -> [num_blocks, block_width] by
    repeating each row value across its dim lanes (elementwise-streaming
    friendly; no gathers)."""
    return jnp.repeat(per_row, spec.dim_padded, axis=1, total_repeat_length=spec.block_width)


# -- touched-rows (lazy) support ----------------------------------------
#
# The streaming optimizer path above costs O(local-table) HBM traffic per
# step; at the north-star table scale (26M rows resident) that pass
# dominates the whole train step (measured 839k -> 192k samples/s).  The
# helpers below give the O(touched-rows) alternative: dedup the batch ids
# WITHOUT a sort (`jnp.unique` lowers to an O(n log n) TPU sort; this is
# a pair of O(n) scatters plus one O(vocab) i32 buffer — 64x less traffic
# than one full f32 table pass), then gather/update/scatter just the
# touched rows.


def _slot_mask(spec: PackedSpec, ids):
    """[n, rows_per_block] bool: one-hot of each id's slot in its block."""
    r = spec.rows_per_block
    return jnp.arange(r, dtype=ids.dtype)[None, :] == (ids % r)[:, None]


def dedup_representatives(spec: PackedSpec, ids, grads):
    """Sort-free dedup of (ids, grads) for lazy row-wise optimizers —
    ALSO the segment-combine prologue of the fused Pallas apply
    (ops/sparse_embedding.fused_dedup_apply), which consumes
    (safe, gsum, touched) directly so both engines see identical
    summed-gradient bits.

    Returns (safe_ids [n] int32, gsum [n, dim], touched [n] bool) where
    exactly ONE position per distinct in-bounds id — its last occurrence,
    the "representative" — is marked touched, `gsum` at that position
    holds the SUMMED grads of all occurrences (the IndexedSlices dedup
    contract of the reference's sparse-apply kernels), and rows whose sum
    is exactly zero are untouched (no moment decay — same contract as
    `touched_mask`).  Out-of-bounds ids (negative padding, >= vocab_padded)
    are dropped, matching the scatter-bounds behaviour of the streaming
    path.

    Mechanism: scatter-max each position's index into a per-logical-row
    i32 buffer (last write wins = max), gather it back to find every
    occurrence's representative, then scatter-add grads onto the
    representative position.
    """
    n = ids.shape[0]
    r = spec.rows_per_block
    ids = ids.astype(jnp.int32)
    valid = (ids >= 0) & (ids < spec.vocab_padded)
    safe = jnp.where(valid, ids, 0)
    pos = jnp.arange(n, dtype=jnp.int32)
    mask = _slot_mask(spec, safe)  # [n, r]
    # last-occurrence index per logical row (-1 = never written).
    buf = jnp.full((spec.num_blocks, r), -1, jnp.int32)
    block_ids = jnp.where(valid, safe // r, spec.num_blocks)  # OOB -> dropped
    buf = buf.at[block_ids].max(jnp.where(mask, pos[:, None], -1))
    got = jnp.take(buf, safe // r, axis=0)  # [n, r] (gather clamps; masked below)
    last = jnp.max(jnp.where(mask, got, -1), axis=1)  # [n]
    # Sum every occurrence's grad onto its representative position.
    tgt = jnp.where(valid, last, n)  # invalid -> out of bounds -> dropped
    gsum = jnp.zeros_like(grads).at[tgt].add(grads)
    is_repr = valid & (pos == last)
    touched = is_repr & jnp.any(gsum != 0, axis=-1)
    return safe, gsum, touched


