"""Ring attention: sequence/context parallelism over a mesh axis.

Net-new TPU scope beyond the reference (SURVEY.md §5 records the
reference has no long-context machinery; the rebuild treats long-context
as first-class).  Design follows the public ring-attention recipe
(Liu et al., blockwise parallel transformers): shard the sequence over a
mesh axis, keep Q local, rotate K/V blocks around the ring with
`lax.ppermute`, and accumulate attention with the flash-attention online
softmax (running max + running denominator) so the full [T, T] score
matrix never materializes — memory is O(T_local^2) per device and the
KV transfer rides ICI overlapped with each block's compute.

Public surface:

- `blockwise_attention(q, k, v, causal=)` — single-device reference
  numerics (also the per-block kernel), f32 accumulation.
- `ring_attention(q, k, v, axis_name=, causal=, q_offset/k_offset)` —
  the SPMD collective form; call inside `shard_map` with the sequence
  dim sharded over `axis_name`.
- `ring_self_attention(mesh, q, k, v, axis=, causal=)` — host-level
  wrapper: shard_maps over the mesh's `model` axis (the context axis in
  this framework's 2-D mesh; see parallel/mesh.py).

Shapes follow the JAX convention [batch, seq, heads, head_dim].
"""

from __future__ import annotations

import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.parallel import compile as pc
from elasticdl_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

NEG_INF = -1e30


def _attn_block(q, k, v, scale, q_pos, k_pos, causal, m, l, acc):
    """One (q-block, kv-block) flash update.  q:[B,Tq,H,D] k,v:[B,Tk,H,D];
    m,l:[B,H,Tq]; acc:[B,Tq,H,D].  f32 throughout (inputs may be bf16)."""
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k.astype(jnp.float32),
    ) * scale
    if causal:
        mask = k_pos[None, None, None, :] > q_pos[None, None, :, None]
        scores = jnp.where(mask, NEG_INF, scores)
    block_max = jnp.max(scores, axis=-1)  # [B,H,Tq]
    m_new = jnp.maximum(m, block_max)
    # exp of a fully-masked row's NEG_INF max would overflow: clamp.
    safe_m = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(scores - safe_m[..., None])  # [B,H,Tq,Tk]
    if causal:
        p = jnp.where(mask, 0.0, p)
    correction = jnp.exp(jnp.where(m <= NEG_INF / 2, NEG_INF, m) - safe_m)
    l_new = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    acc_new = acc * correction.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, acc_new


def _finalize(m, l, acc, dtype):
    # Rows that attended to nothing (can't happen for causal self-attn
    # with q_pos >= 0, but keep the division safe) return zeros.
    denom = jnp.where(l == 0.0, 1.0, l)
    out = acc / denom.transpose(0, 2, 1)[..., None]
    return out.astype(dtype)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    q_offset: int = 0,
    k_offset: int = 0,
    scale: Optional[float] = None,
    kv_chunk: int = 1024,
):
    """Single-device attention with flash numerics — the reference
    semantics ring_attention must match, and the per-ring-step kernel.

    K/V are processed in `kv_chunk`-sized blocks (when the chunk divides
    the KV length) so the materialized score slab is [B, H, Tq, kv_chunk]
    rather than the full [Tq, Tk] — the flash-attention memory shape.
    `q_offset`/`k_offset` give the global position of the first local row
    (needed for causal masking when the sequence is sharded)."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    q_pos = q_offset + jnp.arange(tq)
    m = jnp.full((b, h, tq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, tq), jnp.float32)
    acc = jnp.zeros((b, tq, h, d), jnp.float32)
    if kv_chunk and tk > kv_chunk and tk % kv_chunk == 0:
        n_chunks = tk // kv_chunk
        k_blocks = k.reshape(b, n_chunks, kv_chunk, h, d).transpose(
            1, 0, 2, 3, 4
        )
        v_blocks = v.reshape(b, n_chunks, kv_chunk, h, d).transpose(
            1, 0, 2, 3, 4
        )

        def body(carry, xs):
            m, l, acc = carry
            k_blk, v_blk, chunk = xs
            k_pos = k_offset + chunk * kv_chunk + jnp.arange(kv_chunk)
            return (
                _attn_block(
                    q, k_blk, v_blk, scale, q_pos, k_pos, causal, m, l, acc
                ),
                None,
            )

        (m, l, acc), _ = jax.lax.scan(
            body, (m, l, acc), (k_blocks, v_blocks, jnp.arange(n_chunks))
        )
    else:
        k_pos = k_offset + jnp.arange(tk)
        m, l, acc = _attn_block(
            q, k, v, scale, q_pos, k_pos, causal, m, l, acc
        )
    return _finalize(m, l, acc, q.dtype)


def zigzag_order(t: int, n_shards: int):
    """Global-position permutation for `layout="zigzag"`: applying it to
    the sequence dim and then sharding contiguously gives shard i the
    position chunks (i, 2N-1-i) — every shard then carries one early and
    one late chunk, so causal ring work is BALANCED across shards
    instead of piling onto the last one.  `t % (2 * n_shards) == 0`.
    Invert with `inverse_order`."""
    import numpy as np

    if t % (2 * n_shards):
        raise ValueError(f"t={t} must divide into 2*{n_shards} chunks")
    h = t // (2 * n_shards)
    idx = []
    for i in range(n_shards):
        idx.extend(range(i * h, (i + 1) * h))
        j = 2 * n_shards - 1 - i
        idx.extend(range(j * h, (j + 1) * h))
    return np.asarray(idx)


def inverse_order(order):
    import numpy as np

    inv = np.empty_like(order)
    inv[order] = np.arange(len(order))
    return inv


def zigzag_orders(t: int, n_shards: int):
    """(order, inverse) pair for `layout="zigzag"` — the one helper both
    ring_self_attention and mesh-aware models use, so the permute-around-
    attend contract lives in one place."""
    order = zigzag_order(t, n_shards)
    return order, inverse_order(order)


def _shard_positions(index, t_local, axis_size, layout):
    """Global positions of shard `index`'s local rows under `layout`."""
    if layout == "contiguous":
        return index * t_local + jnp.arange(t_local)
    half = t_local // 2
    late = 2 * axis_size - 1 - index
    return jnp.concatenate(
        [
            index * half + jnp.arange(half),
            late * half + jnp.arange(half),
        ]
    )


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = False,
    scale: Optional[float] = None,
    layout: str = "contiguous",
):
    """Collective attention over sequence shards; call under shard_map.

    Local shapes [B, T_local, H, D]; the global sequence is the
    concatenation over `axis_name` in axis-index order.  Each of the
    `axis_size` ring steps attends Q against one rotating KV block, then
    ppermutes KV to the next device — the transfer and the next block's
    compute overlap under XLA's scheduler.

    `layout` declares how global positions map to shards:

    - "contiguous": shard i holds positions [i*T_local, (i+1)*T_local).
      Causal fully-masked blocks are lax.cond-skipped — reclaiming FLOPs
      but NOT wall-clock (the ring is lockstep; the last shard attends
      at every step, so the critical path still runs N full blocks).
    - "zigzag": shard i holds chunks (i, 2N-1-i) of 2N chunks (pre-
      permute the global sequence with `zigzag_order`).  Every shard
      does the same ~half-masked work at every causal step, cutting the
      causal critical path toward N/2 block-attends — the standard
      balanced causal ring.
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    b, tq, h, d = q.shape
    tk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    q_pos = _shard_positions(my_index, tq, axis_size, layout)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, step):
        m, l, acc, k_blk, v_blk = carry
        # KV block currently held arrived from `my_index - step`.
        src = (my_index - step) % axis_size
        k_pos = _shard_positions(src, tk, axis_size, layout)

        def attend(operands):
            m, l, acc = operands
            return _attn_block(
                q, k_blk, v_blk, scale, q_pos, k_pos, causal, m, l, acc
            )

        if causal and layout == "contiguous":
            # A KV block from a strictly-later shard (src > my_index) is
            # fully masked — skip its matmuls (FLOPs, not wall-clock;
            # see the layout note above — "zigzag" is the wall-clock fix).
            m, l, acc = jax.lax.cond(
                src > my_index, lambda ops: ops, attend, (m, l, acc)
            )
        else:
            # Zigzag blocks are never fully masked (every shard holds an
            # early chunk): always attend — that uniformity IS the
            # balance.
            m, l, acc = attend((m, l, acc))
        # Rotate for the next step (skipped result on the last step).
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (m, l, acc, k_blk, v_blk), None

    # Derive the initial accumulators FROM q (zeros_like) rather than
    # fresh jnp.zeros: under shard_map's typed-varying-axes model the
    # scan carry must vary over the same mesh axes as the body output,
    # and zeros born of q inherit q's varying type.
    acc = jnp.zeros_like(q, jnp.float32)  # [B,Tq,H,D]
    l = acc[..., 0].transpose(0, 2, 1)  # [B,H,Tq] zeros
    m = NEG_INF + l
    (m, l, acc, _, _), _ = jax.lax.scan(
        body, (m, l, acc, k, v), jnp.arange(axis_size)
    )
    return _finalize(m, l, acc, q.dtype)


def _to_kernel(x):  # [B, T, H, D] -> [B, H, T, D]
    return x.transpose(0, 2, 1, 3)


def _ring_axis_geometry(cfg, tq, tk):
    """(axis_size, my_index, q_pos, perm) — recomputed inside EVERY side
    of the custom VJP below: closing over these (they are tracers under
    shard_map) leaks tracers across the custom_vjp boundary when the
    ring runs under jit+scan."""
    axis_name, causal, scale, layout, interpret = cfg
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    q_pos = _shard_positions(my_index, tq, axis_size, layout)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return axis_size, my_index, q_pos, perm


def _ring_pallas_forward(cfg, q, k, v):
    """Forward ring: each step runs the flash kernel on the rotating KV
    block with the lse-space combine FUSED into the kernel epilogue
    (flash_ring_step_carry — the (acc, lse) carry buffers alias in
    place, so no per-step [B,H,T,D] combine pass ever touches HBM; a
    fully-masked step's lse_i = NEG_INF contributes exp(-inf) = 0)."""
    from elasticdl_tpu.ops.flash_attention import flash_ring_step_carry

    axis_name, causal, scale, layout, interpret = cfg
    tq, tk = q.shape[1], k.shape[1]
    axis_size, my_index, q_pos, perm = _ring_axis_geometry(cfg, tq, tk)
    qk = _to_kernel(q)
    acc0 = jnp.zeros_like(qk, jnp.float32)
    lse0 = jnp.full(qk.shape[:3] + (1,), NEG_INF, jnp.float32) + (
        0.0 * qk[..., :1].astype(jnp.float32)
    )  # inherit q's varying mesh axes (shard_map typed-axes rule)

    def body(carry, step):
        acc, lse_c, k_blk, v_blk = carry
        src = (my_index - step) % axis_size
        k_pos = _shard_positions(src, tk, axis_size, layout)
        acc, lse_c = flash_ring_step_carry(
            qk, k_blk, v_blk, acc, lse_c,
            q_pos, k_pos, causal=causal, scale=scale, interpret=interpret,
        )
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (acc, lse_c, k_blk, v_blk), None

    # KV rotate in KERNEL layout [B,H,T,D]: one transpose before the
    # ring instead of two per step (measured ~10% of the per-step device
    # time at T_local=2048; ppermute cost is layout-independent).
    (acc, lse, _, _), _ = jax.lax.scan(
        body, (acc0, lse0, _to_kernel(k), _to_kernel(v)),
        jnp.arange(axis_size),
    )
    out = _to_kernel(acc).astype(q.dtype)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring_pallas(cfg, q, k, v):
    """Pallas-engined ring attention core (per-shard; under shard_map).
    `cfg` = (axis_name, causal, scale, layout, interpret), all static.
    The round-2 'fuse the kernel into the ring' gap (VERDICT #3)."""
    return _ring_pallas_forward(cfg, q, k, v)[0]


def _ring_pallas_fwd(cfg, q, k, v):
    out, lse = _ring_pallas_forward(cfg, q, k, v)
    return out, (q, k, v, out, lse)


def _ring_pallas_bwd(cfg, res, g):
    """Ring-aware backward: re-rotate KV (and the dk/dv accumulators
    with them) for axis_size steps; every step reuses the flash backward
    identity P = exp(S - lse_final) via stateless step kernels, so after
    the full rotation each KV block's gradient arrives home."""
    from elasticdl_tpu.ops.flash_attention import flash_ring_step_bwd

    axis_name, causal, scale, layout, interpret = cfg
    q, k, v, out, lse = res
    tq, tk = q.shape[1], k.shape[1]
    axis_size, my_index, q_pos, perm = _ring_axis_geometry(cfg, tq, tk)
    qk = _to_kernel(q)
    do = _to_kernel(g).astype(jnp.float32)
    outk = _to_kernel(out).astype(jnp.float32)
    delta = jnp.sum(do * outk, axis=-1, keepdims=True)  # [B,H,Tq,1]
    kk, vk = _to_kernel(k), _to_kernel(v)
    dq0 = jnp.zeros_like(qk, jnp.float32)
    dk0 = jnp.zeros_like(kk, jnp.float32)
    dv0 = jnp.zeros_like(dk0)

    def body(carry, step):
        dq_acc, k_blk, v_blk, dk_blk, dv_blk = carry
        src = (my_index - step) % axis_size
        k_pos = _shard_positions(src, tk, axis_size, layout)
        dq_i, dk_i, dv_i = flash_ring_step_bwd(
            qk, k_blk, v_blk, do, lse, delta,
            q_pos, k_pos, causal=causal, scale=scale,
            interpret=interpret,
        )
        dq_acc = dq_acc + dq_i
        dk_blk = dk_blk + dk_i
        dv_blk = dv_blk + dv_i
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        dk_blk = jax.lax.ppermute(dk_blk, axis_name, perm)
        dv_blk = jax.lax.ppermute(dv_blk, axis_name, perm)
        return (dq_acc, k_blk, v_blk, dk_blk, dv_blk), None

    # KV (and their gradient accumulators, which ride the same rotation)
    # in KERNEL layout across the ring — transposes once outside the
    # scan, not per step (same trade as the forward).
    (dq_acc, _, _, dk_acc, dv_acc), _ = jax.lax.scan(
        body, (dq0, kk, vk, dk0, dv0), jnp.arange(axis_size)
    )
    return (
        _to_kernel(dq_acc).astype(q.dtype),
        _to_kernel(dk_acc).astype(k.dtype),
        _to_kernel(dv_acc).astype(v.dtype),
    )


_ring_pallas.defvjp(_ring_pallas_fwd, _ring_pallas_bwd)


def ring_attention_pallas(
    q, k, v, *, axis_name, causal=False, scale=None,
    layout="contiguous", interpret=None,
):
    """Ring attention with the Pallas flash kernel as the per-step block
    engine.  Same contract as `ring_attention` (call under shard_map,
    local [B, T_local, H, D] shards); `interpret=None` auto-selects
    interpret mode off-TPU."""
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    from elasticdl_tpu.ops.flash_attention import _use_interpret

    interpret = _use_interpret() if interpret is None else interpret
    scale_ = scale if scale is not None else 1.0 / (q.shape[-1] ** 0.5)
    return _ring_pallas(
        (axis_name, causal, scale_, layout, interpret), q, k, v
    )


def _ring_dispatch(q, k, v, *, axis_name, causal, scale=None,
                   layout="contiguous", impl="auto"):
    """Per-shard impl selection (shapes are static at trace time):
    'pallas' = flash kernels per ring step (2.4x the XLA block engine on
    the chip, BASELINE.md), 'xla' = the blockwise einsum engine, 'auto' =
    pallas whenever the kernel supports the local shard shape."""
    if impl == "auto":
        from elasticdl_tpu.ops.flash_attention import (
            supports,
            warn_if_vmem_is_sole_blocker,
        )

        t, d = q.shape[1], q.shape[3]
        tk = k.shape[1]
        ok = supports(t, d) and supports(tk, d)
        impl = "pallas" if ok else "xla"
        if not ok:
            from elasticdl_tpu.ops.flash_attention import shape_aligned

            # BOTH operand shapes must be kernel-alignable before the
            # flag advice is honest — a misaligned q shard would still
            # block attn_impl=pallas after the operator sets the flag.
            if shape_aligned(t, d) and shape_aligned(tk, d):
                warn_if_vmem_is_sole_blocker(
                    "parallel.ring_attention", max(t, tk), d
                )
    if impl == "pallas":
        return ring_attention_pallas(
            q, k, v, axis_name=axis_name, causal=causal, scale=scale,
            layout=layout,
        )
    if impl != "xla":
        raise ValueError(f"impl must be auto|pallas|xla, got {impl!r}")
    return ring_attention(
        q, k, v, axis_name=axis_name, causal=causal, scale=scale,
        layout=layout,
    )


def make_ring_attention(mesh, *, axis: str = MODEL_AXIS,
                        causal: bool = False, layout: str = "contiguous",
                        impl: str = "auto"):
    """Build the shard_mapped ring-attention callable for `mesh`: batch
    sharded over `data`, sequence over `axis`.  The ONE place the
    sharding specs live — both ring_self_attention and mesh-aware models
    (model_zoo/transformer) call this.  With `layout="zigzag"` the
    caller is responsible for feeding sequences permuted by
    `zigzag_order` (and un-permuting outputs with `inverse_order`).
    `impl` selects the per-step block engine (see _ring_dispatch)."""
    spec = P(DATA_AXIS, axis, None, None)
    fn = partial(
        _ring_dispatch, axis_name=axis, causal=causal, layout=layout,
        impl=impl,
    )
    # Built through the compile layer's shard_map shim (the one place
    # that owns the jax.shard_map fallback + check_vma/check_rep
    # rename).  check_vma stays ON for the pure-XLA engine; it is off
    # only where the pallas engine can be selected — kernel interpret
    # mode (CPU tests/dryruns) trips a jax limitation inside the kernel
    # interpreter ("Primitive dynamic_slice requires varying manual
    # axes to match ... as a temporary workaround pass
    # check_vma=False"); collective placement is pinned by the
    # parity+HLO-structure tests instead.
    return pc.shard_map_call(
        fn, mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=None if impl == "xla" else False,
    )


def ring_self_attention(
    mesh,
    q: jax.Array,
    k: jax.Array = None,
    v: jax.Array = None,
    *,
    axis: str = MODEL_AXIS,
    causal: bool = False,
    layout: str = "contiguous",
    impl: str = "auto",
):
    """Host-level entry: global [B, T, H, D] arrays in, attention out,
    computed ring-wise with batch sharded over `data` and sequence over
    `axis`.  (Inside a jitted step prefer calling `make_ring_attention`'s
    result from your own code so it fuses with the rest of the program.)

    `layout="zigzag"` handles the permutation here: inputs/outputs stay
    in natural sequence order, the balanced layout is internal."""
    k = q if k is None else k
    v = q if v is None else v
    fn = make_ring_attention(
        mesh, axis=axis, causal=causal, layout=layout, impl=impl
    )
    sharding = NamedSharding(mesh, P(DATA_AXIS, axis, None, None))
    if layout == "zigzag":
        if k.shape[1] != q.shape[1] or v.shape[1] != q.shape[1]:
            raise ValueError(
                "layout='zigzag' requires equal q/k/v sequence lengths "
                f"(got q={q.shape[1]}, k={k.shape[1]}, v={v.shape[1]}); "
                "the balanced layout is a self-attention arrangement"
            )
        order, inv = zigzag_orders(q.shape[1], mesh.shape[axis])
        q, k, v = (x[:, order] for x in (q, k, v))
        q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
        return fn(q, k, v)[:, inv]
    q, k, v = (jax.device_put(x, sharding) for x in (q, k, v))
    return fn(q, k, v)
