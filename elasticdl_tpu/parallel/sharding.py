"""Sharding helpers: NamedShardings, global-batch assembly, padding.

XLA requires static shapes; the data-parallel batch dim must divide the
`data` mesh axis.  The reference streams arbitrary-size minibatches through
TF eager (no such constraint), so the TPU path pads ragged final batches
and masks padded rows out of the loss — no records are dropped, preserving
the at-least-once task semantics of the task manager.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

from elasticdl_tpu.parallel.mesh import DATA_AXIS


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def batch_sharded(mesh):
    """Leading dim sharded over the data axis, rest replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(DATA_AXIS))


def window_sharded(mesh):
    """[window, batch, ...]: dim 1 (batch) sharded over the data axis.
    Used by the windowed staging path — K batches ride ONE host->device
    transfer and the step function dynamic-slices batch k on device."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(None, DATA_AXIS))


def data_axis_size(mesh) -> int:
    return mesh.shape[DATA_AXIS]


def pad_batch(tree: Any, multiple: int) -> Tuple[Any, np.ndarray]:
    """Pad every array's leading dim up to `multiple`; return (tree, mask).

    Padding repeats row 0 (keeps dtypes/values in-distribution so the
    forward pass stays numerically safe); the mask is 1.0 for real rows and
    0.0 for padding and is used for the weighted loss.
    """
    import jax

    leaves = jax.tree.leaves(tree)
    if not leaves:
        return tree, np.zeros((0,), np.float32)
    batch = leaves[0].shape[0]
    # An empty local slice (possible at a ragged tail in a multi-process
    # world) still pads up to one full block so shapes agree across ranks.
    padded = -(-batch // multiple) * multiple if batch else multiple
    mask = np.ones((padded,), np.float32)
    mask[batch:] = 0.0
    if padded == batch:
        return tree, mask

    def pad(x):
        x = np.asarray(x)
        if batch == 0:
            return np.zeros((padded,) + x.shape[1:], x.dtype)
        pad_rows = np.repeat(x[:1], padded - batch, axis=0)
        return np.concatenate([x, pad_rows], axis=0)

    return jax.tree.map(pad, tree), mask


def shard_batch(tree: Any, mesh):
    """Place a host-global batch onto the mesh, sharded over `data`."""
    import jax

    sharding = batch_sharded(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def put(tree: Any, shardings: Any):
    """Place a host pytree under per-leaf shardings — works in
    multi-process worlds too (each process materializes its local shards
    from its own host copy, which must hold the GLOBAL value)."""
    import jax

    if jax.process_count() == 1:
        return jax.tree.map(jax.device_put, tree, shardings)

    def place(x, s):
        arr = np.asarray(x)
        return jax.make_array_from_callback(
            arr.shape, s, lambda idx: arr[idx]
        )

    return jax.tree.map(place, tree, shardings)




def assemble_global_batch(tree: Any, mesh):
    """Turn per-process local batch arrays into the global data-sharded
    batch.  Single process: a plain device_put of the host-global batch.
    Multi-process: each process contributes its contiguous slice (all
    processes must pass equal-size local arrays)."""
    import jax

    sharding = batch_sharded(mesh)
    if jax.process_count() == 1:
        return jax.tree.map(
            lambda x: jax.device_put(np.asarray(x), sharding), tree
        )
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(x)
        ),
        tree,
    )


def stack_window(batches):
    """Host-stack K (features, labels, mask) batches into [K, ...] arrays
    for assemble_window (shared by the PS and DP trainers' stage_window)."""
    import jax

    feats = [b[0] for b in batches]
    stacked_f = jax.tree.map(lambda *xs: np.stack(xs), *feats)
    stacked_l = np.stack([np.asarray(b[1]) for b in batches])
    stacked_m = np.stack([np.asarray(b[2], np.float32) for b in batches])
    return stacked_f, stacked_l, stacked_m


def assemble_window(tree: Any, mesh):
    """Like assemble_global_batch for a stacked window [K, batch, ...]:
    dim 1 is the (global) batch.  One transfer carries K minibatches —
    per-transfer overhead amortizes K-fold, and the windowed step slices
    batch k on device."""
    import jax

    sharding = window_sharded(mesh)
    if jax.process_count() == 1:
        return jax.tree.map(
            lambda x: jax.device_put(np.asarray(x), sharding), tree
        )
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.ascontiguousarray(x)
        ),
        tree,
    )


def gather_to_host(tree: Any):
    """Fetch possibly process-sharded device arrays as full host arrays
    (allgathers across processes when needed)."""
    import jax

    if jax.process_count() == 1:
        return jax.tree.map(np.asarray, tree)
    from jax.experimental import multihost_utils

    return jax.tree.map(np.asarray, multihost_utils.process_allgather(tree, tiled=True))
