"""TPU-native parallelism: device meshes, sharded trainers, collectives.

This package replaces the reference's three communication planes
(SURVEY.md §5) the TPU way:

- NCCL/Gloo rings (FTlib / elastic Horovod)  →  XLA collectives compiled
  into the step function over a `jax.sharding.Mesh` (ICI within a slice,
  DCN across slices).
- The Go parameter server's data plane      →  sharded HBM arrays
  (see elasticdl_tpu.layers.embedding for the table-sharded path).
- Elastic communicator re-formation          →  mesh re-formation over the
  surviving hosts via `jax.distributed` re-initialization
  (elasticdl_tpu.parallel.elastic).
"""

from elasticdl_tpu.parallel.mesh import MeshConfig, build_mesh  # noqa: F401
from elasticdl_tpu.parallel.dp_trainer import DataParallelTrainer  # noqa: F401
from elasticdl_tpu.parallel.collective import (  # noqa: F401
    CollectiveCommunicator,
    CollectiveResult,
)
