"""Data-parallel trainer: the AllReduce mode, compiled.

Parity: the reference's AllReduce path (worker/allreduce_trainer.py +
collective_ops/communicator.py — per-step gradient allreduce over
NCCL/Gloo, SURVEY.md §3.4).  TPU-native design: parameters are replicated
over the mesh's `data` axis, the batch is sharded over it, and the gradient
all-reduce is *not a library call* — XLA inserts `psum` when it lowers the
replicated-out gradient of a data-sharded loss, and schedules it onto ICI
overlapped with the backward pass.  One compiled program per step; no
Horovod, no ring management.

Ragged final batches are padded and masked (see parallel/sharding.py) so
shapes stay static across the whole epoch — one compilation, every batch.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.parallel import compile as pc
from elasticdl_tpu.parallel import sharding as shd
from elasticdl_tpu.worker.trainer import TrainState, _model_apply

logger = get_logger("parallel.dp_trainer")


def per_example_loss_fn(loss_fn: Callable) -> Callable:
    """Lift a batch-mean loss into a per-example loss via vmap.

    The model-zoo contract's `loss(labels, outputs)` returns the batch mean
    (reference contract).  Applying it to singleton batches under vmap
    recovers the per-example loss for any mean-of-per-example loss, which
    lets the trainer mask padded rows exactly.
    """

    def singleton(label, output):
        return loss_fn(
            jax.tree.map(lambda x: x[None], label),
            jax.tree.map(lambda x: x[None], output),
        )

    return jax.vmap(singleton)


class DataParallelTrainer:
    """Same public surface as worker.trainer.Trainer, over an N-device mesh.

    Batch sharded over `data`; loss is a mask-weighted mean so padded
    rows contribute zero gradient.  Dense state placement is selectable
    (SURVEY.md §5 "dense: replicated or FSDP-sharded"):

    - `dense_sharding="replicated"` (default): params/opt-state replicated;
      XLA reduces gradients with a psum.
    - `dense_sharding="fsdp"`: params/opt-state sharded on dim0 over the
      `data` axis — each chip holds 1/N of the model+optimizer memory.
      No hand-written gather/scatter: the jit's in/out shardings declare
      the layout and XLA's SPMD partitioner inserts the all-gathers
      (weights, before use) and reduce-scatters (gradients) itself,
      scheduled onto ICI overlapped with compute.  Leaves too small or
      not divisible by the axis stay replicated.
    """

    FSDP_MIN_LEAF = 1024  # elements; below this, sharding buys nothing

    def __init__(
        self,
        model,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        mesh,
        seed: int = 0,
        dense_sharding: str = "replicated",
    ):
        if dense_sharding not in ("replicated", "fsdp"):
            raise ValueError(
                f"dense_sharding must be 'replicated' or 'fsdp', "
                f"got {dense_sharding!r}"
            )
        self._model = model
        self._loss_fn = loss_fn
        self._per_example_loss = per_example_loss_fn(loss_fn)
        self._tx = optimizer
        self._mesh = mesh
        self._seed = seed
        self._dense_sharding = dense_sharding
        self._state: Optional[TrainState] = None
        # Host-side mirror of state.step (avoids a per-batch device sync).
        self._host_step = 0
        self._dp = shd.data_axis_size(mesh)
        self._pending_sharded_restore = None

        # FSDP needs per-leaf state shardings, which need the state's
        # STRUCTURE — compile lazily at first state (ps_trainer pattern).
        self._train_step = None
        self._train_window_jit = None
        self._eval_step = None

    # -- sharding layout (declarative rule table, parallel/compile.py) --

    def _partition_rules(self) -> pc.RuleTable:
        """The dense trainer's placement policy as a rule table.
        Replicated mode is one catch-all entry; FSDP shards
        params/opt_state dim0 over `data` when the leaf divides the
        axis and is worth sharding (shape-aware callable rule — the
        FSDP_MIN_LEAF/divisibility policy reads as ONE table entry).
        Scalars and everything else (step counter, batch stats)
        replicate."""
        from jax.sharding import PartitionSpec as P

        from elasticdl_tpu.parallel.mesh import DATA_AXIS

        if self._dense_sharding == "replicated":
            return pc.RuleTable([pc.Rule(".*", P())], name="dp-replicated")
        dp = self._dp
        min_leaf = self.FSDP_MIN_LEAF

        def fsdp_leaf(path, shape):
            if shape[0] % dp == 0 and int(np.prod(shape)) >= min_leaf:
                return P(DATA_AXIS, *([None] * (len(shape) - 1)))
            return P()

        return pc.RuleTable(
            [
                pc.Rule(r"^(params|opt_state)(/|$)", fsdp_leaf),
                pc.Rule(".*", P()),
            ],
            name="dp-fsdp",
        )

    def _plan(self) -> pc.CompilePlan:
        return pc.CompilePlan(
            self._mesh, self._partition_rules(), trainer="dp_trainer"
        )

    def _state_shardings(self, state: TrainState, plan=None):
        # Works on concrete arrays AND jax.eval_shape's ShapeDtypeStructs
        # (the sharded-init path computes shardings from shapes alone).
        plan = plan or self._plan()
        tree = plan.state_shardings({
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "model_state": state.model_state,
        })
        return TrainState(
            tree["step"], tree["params"], tree["opt_state"],
            tree["model_state"],
        )

    def _place_state(self, state: TrainState) -> TrainState:
        return shd.put(state, self._state_shardings(state))

    def _compile_steps(self, state: TrainState):
        plan = self._plan()
        repl = plan.replicated()
        batch = shd.batch_sharded(self._mesh)
        window = shd.window_sharded(self._mesh)
        state_shardings = self._state_shardings(state, plan)
        self._train_step = plan.compile(
            self._train_step_impl,
            name="dp_train_step",
            in_shardings=(state_shardings, batch, batch, batch),
            out_shardings=(state_shardings, repl),
            donate_argnums=(0,),
        )
        self._train_window_jit = plan.compile(
            self._train_window_impl,
            name="dp_train_window",
            in_shardings=(state_shardings, window, window, window),
            out_shardings=(state_shardings, repl),
            donate_argnums=(0,),
        )
        self._eval_step = plan.compile(
            self._eval_step_impl,
            name="dp_eval_step",
            in_shardings=(state_shardings, batch),
            out_shardings=batch,
        )

    def jitted_entrypoints(self) -> dict:
        """Current jitted entrypoints by name — the step-anatomy
        retrace watcher (obs/stepstats.py) polls their compile-cache
        sizes between dispatches.  Empty until first compile; re-read
        per poll because compilation is lazy."""
        return {
            "dp_train_step": self._train_step,
            "dp_train_window": self._train_window_jit,
            "dp_eval_step": self._eval_step,
        }

    # -- state ----------------------------------------------------------

    @property
    def mesh(self):
        return self._mesh

    def local_block(self, per_rank_batch: int) -> int:
        """Rows each process must supply per collective step: the requested
        per-rank batch rounded up to a multiple of the process's local
        device count (the global batch must divide the `data` axis)."""
        local_devices = max(1, self._dp // jax.process_count())
        return -(-per_rank_batch // local_devices) * local_devices

    @property
    def state(self) -> Optional[TrainState]:
        return self._state

    @state.setter
    def state(self, value: TrainState):
        value = TrainState(*value)
        self._state = self._place_state(jax.device_get(value))
        self._host_step = int(np.asarray(jax.device_get(value.step)))
        if self._train_step is None:
            self._compile_steps(self._state)

    @property
    def step(self) -> int:
        return self._host_step

    def _make_state(self, rng, features):
        """Pure state constructor — runs under jit so FSDP state is BORN
        sharded (out_shardings), never materialized whole on one device.
        Returns (state, specs_collection) — the tiny packed-table specs
        ride out for host-side export mapping."""
        from elasticdl_tpu.layers.embedding import (
            SPECS_COLLECTION,
            strip_capture_collections,
        )
        from elasticdl_tpu.worker.trainer import _unbox_partitioned

        variables = dict(self._model.init(rng, features))
        specs = variables.get(SPECS_COLLECTION, {})
        variables = strip_capture_collections(variables)
        variables = _unbox_partitioned(variables)
        params = variables.pop("params")
        state = TrainState(
            jnp.zeros((), jnp.int32),
            params,
            self._tx.init(params),
            variables,
        )
        return state, specs

    def ensure_initialized(self, features) -> TrainState:
        if self._state is None:
            from elasticdl_tpu.layers.embedding import (
                SPECS_COLLECTION,
                export_spec_map,
            )

            rng = jax.random.PRNGKey(self._seed)
            features = jax.tree.map(jnp.asarray, features)
            # Structure first (no FLOPs, no memory), shardings from it,
            # then a jitted init whose out_shardings birth the state in
            # its final layout — under FSDP no device ever holds the
            # full params+opt_state (the point of sharding them).
            state_shapes, _specs_shapes = jax.eval_shape(
                self._make_state, rng, features
            )
            if self._pending_sharded_restore is not None:
                # Restore path: the checkpoint supplies every value, so
                # never run (or even compile) the full init — the shape
                # tree is template enough, and the tiny export specs come
                # from a specs-only jit whose unused param computations
                # XLA dead-code-eliminates.
                # Specs-only jit: the outputs are a handful of [2] int32
                # packed-table specs (host-bound, layout-irrelevant) and
                # the param computations feeding them are dead-code-
                # eliminated — declaring shardings here would force the
                # full init to compile (jit_utility is the compile
                # layer's sanctioned non-step passthrough).
                specs = pc.jit_utility(
                    lambda r, f: self._make_state(r, f)[1]
                )(rng, features)
                self._state = self._restore_sharded(state_shapes)
            else:
                plan = self._plan()
                repl = plan.replicated()
                init = plan.compile(
                    self._make_state,
                    name="dp_init",
                    out_shardings=(
                        self._state_shardings(state_shapes, plan),
                        jax.tree.map(lambda _: repl, _specs_shapes),
                    ),
                )
                self._state, specs = init(rng, features)
            self._export_specs = export_spec_map(
                {SPECS_COLLECTION: jax.device_get(specs)}
            )
            logger.info(
                "Initialized %s model over %d-way data parallel: "
                "%d parameters",
                self._dense_sharding,
                self._dp,
                sum(
                    int(np.prod(p.shape))
                    for p in jax.tree.leaves(state_shapes.params)
                ),
            )
        if self._pending_sharded_restore is not None:
            # State arrived via the setter (or was already live) after a
            # deferred restore was registered: apply it now.
            self._state = self._restore_sharded(self._state)
        if self._train_step is None:
            self._compile_steps(self._state)
        return self._state

    # -- compiled steps -------------------------------------------------

    def _train_step_impl(self, state: TrainState, features, labels, mask):
        mutable_keys = list(state.model_state.keys())

        def compute_loss(params):
            variables = {"params": params, **state.model_state}
            outputs, new_model_state = _model_apply(
                self._model, variables, features, train=True, mutable=mutable_keys
            )
            losses = self._per_example_loss(labels, outputs)
            loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            return loss, new_model_state

        (loss, new_model_state), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        updates, new_opt_state = self._tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if not mutable_keys:
            new_model_state = state.model_state
        return (
            TrainState(state.step + 1, new_params, new_opt_state, new_model_state),
            loss,
        )

    def _train_window_impl(self, state, feat_win, label_win, mask_win):
        """K train steps in one device program (see ps_trainer)."""

        def body(st, xs):
            features, labels, mask = xs
            new_state, loss = self._train_step_impl(st, features, labels, mask)
            return new_state, loss

        return jax.lax.scan(body, state, (feat_win, label_win, mask_win))

    def _eval_step_impl(self, state: TrainState, features):
        variables = {"params": state.params, **state.model_state}
        outputs, _ = _model_apply(
            self._model, variables, features, train=False, mutable=False
        )
        return outputs

    # -- host-side entry points ----------------------------------------

    def _place_batch(self, features, labels=None):
        features, mask = shd.pad_batch(features, self._dp)
        if labels is not None:
            labels, _ = shd.pad_batch(labels, self._dp)
            labels = shd.shard_batch(labels, self._mesh)
        features = shd.shard_batch(features, self._mesh)
        mask = shd.shard_batch(mask, self._mesh)
        return features, labels, mask

    def train_step(self, features, labels):
        state = self.ensure_initialized(features)
        features, labels, mask = self._place_batch(features, labels)
        self._state, loss = self._train_step(state, features, labels, mask)
        self._host_step += 1
        return loss

    def train_step_local(self, features, labels, mask):
        """Collective-mode entry: `features`/`labels`/`mask` are this
        process's equal-size slice of the global batch (pre-padded by the
        caller); all processes must call this in lockstep."""
        self.ensure_initialized(features)
        return self.train_step_staged(self.stage_batch(features, labels, mask))

    def stage_batch(self, features, labels, mask):
        """Async device placement of one lockstep batch (stage k+1 before
        stepping k to overlap H2D with compute; see ps_trainer)."""
        return (
            shd.assemble_global_batch(features, self._mesh),
            shd.assemble_global_batch(labels, self._mesh),
            shd.assemble_global_batch(np.asarray(mask, np.float32), self._mesh),
        )

    def train_step_staged(self, staged):
        state = self.ensure_initialized(staged[0])
        self._state, loss = self._train_step(state, *staged)
        self._host_step += 1
        return loss

    def stage_window(self, batches):
        """Stage K same-shape (features, labels, mask) batches as one
        stacked transfer (see ps_trainer.stage_window)."""
        stacked_f, stacked_l, stacked_m = shd.stack_window(batches)
        return (
            shd.assemble_window(stacked_f, self._mesh),
            shd.assemble_window(stacked_l, self._mesh),
            shd.assemble_window(stacked_m, self._mesh),
        )

    def train_window(self, window):
        """Run every batch of a staged window; returns the [K] losses."""
        if self._state is None:
            self.ensure_initialized(jax.tree.map(lambda x: x[0], window[0]))
        k = jax.tree.leaves(window[1])[0].shape[0]
        self._state, losses = self._train_window_jit(self._state, *window)
        self._host_step += k
        return losses

    def eval_step_local(self, features):
        """Collective-mode eval: local slice in, FULL global outputs out
        (host numpy, identical on every process)."""
        state = self.ensure_initialized(features)
        features = shd.assemble_global_batch(features, self._mesh)
        outputs = self._eval_step(state, features)
        return shd.gather_to_host(outputs)

    def eval_step(self, features):
        state = self.ensure_initialized(features)
        n = jax.tree.leaves(features)[0].shape[0]
        features, _, _ = self._place_batch(features)
        outputs = self._eval_step(state, features)
        # Strip padding rows before returning to the host.
        return jax.tree.map(lambda x: np.asarray(x)[:n], outputs)

    def state_to_host(self) -> Optional[TrainState]:
        """Host-complete snapshot for checkpointing.  Replicated state
        materializes locally; FSDP-sharded leaves allgather — a COLLECTIVE
        in multi-process worlds (every process must call this).  FSDP jobs
        normally checkpoint via save_checkpoint (shard-wise, no gather);
        this full-gather remains for export/debug paths."""
        if self._state is None:
            return None
        if self._dense_sharding == "replicated":
            return jax.device_get(self._state)
        return shd.gather_to_host(self._state)

    # -- sharded checkpointing (FSDP) -----------------------------------

    @staticmethod
    def _leaf_key(path) -> str:
        return "dense|" + "/".join(str(getattr(p, "key", p)) for p in path)

    def save_checkpoint(self, saver, step: int) -> None:
        """COLLECTIVE shard-wise checkpoint (checkpoint/sharded.py):
        each process writes only its local rows of FSDP-sharded leaves —
        no host ever gathers the full model+optimizer state (which is
        the thing FSDP exists to avoid holding)."""
        if self._state is None:
            return
        state = self._state
        shardings = self._state_shardings(state)
        flat_state = jax.tree_util.tree_flatten_with_path(state)[0]
        flat_shard = jax.tree.leaves(
            shardings, is_leaf=lambda x: hasattr(x, "is_fully_replicated")
        )
        sharded = {}
        dense_leaves = {}
        for (path, leaf), sharding in zip(flat_state, flat_shard):
            key = self._leaf_key(path)
            if sharding.is_fully_replicated:
                if jax.process_index() == 0:
                    dense_leaves[key] = jax.device_get(leaf)
            else:
                sharded[key] = leaf
        dense = None
        if jax.process_index() == 0:
            dense = {
                "step": int(self._host_step),
                "leaves": dense_leaves,
            }
        saver.save(step, dense, sharded)

    def set_sharded_restore(self, saver, step: int) -> None:
        self._pending_sharded_restore = (saver, step)
        self._host_step = step

    def _restore_sharded(self, template: TrainState) -> TrainState:
        saver, step = self._pending_sharded_restore
        self._pending_sharded_restore = None
        shardings = self._state_shardings(template)
        manifest_arrays = saver.manifest(step)["arrays"]
        dense = saver.load_dense(step)
        flat_template, treedef = jax.tree_util.tree_flatten_with_path(
            template
        )
        flat_shard = jax.tree.leaves(
            shardings, is_leaf=lambda x: hasattr(x, "is_fully_replicated")
        )
        leaves = []
        for (path, leaf), sharding in zip(flat_template, flat_shard):
            key = self._leaf_key(path)
            if key in manifest_arrays:
                leaves.append(saver.load_array(step, key, sharding))
            elif key in dense["leaves"]:
                leaves.append(shd.put(dense["leaves"][key], sharding))
            else:
                raise KeyError(
                    f"Checkpoint at step {step} missing leaf {key} "
                    "(model structure changed?)"
                )
        if hasattr(saver, "release"):
            saver.release(step)
        self._host_step = int(dense["step"])
        logger.info("Restored sharded checkpoint at step %d", self._host_step)
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def get_variables_numpy(self) -> dict:
        """Flat logical view; packed tables unpacked (see worker.trainer).
        COLLECTIVE under FSDP in multi-process worlds (see state_to_host)."""
        from elasticdl_tpu.parallel import packed as pk

        if self._state is None:
            return {}
        specs = getattr(self, "_export_specs", {})
        flat = {}
        tree = {"params": self._state.params, **self._state.model_state}
        if self._dense_sharding == "fsdp":
            tree = shd.gather_to_host(tree)
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            key = "/".join(str(getattr(p, "key", p)) for p in path)
            if key in specs:
                leaf = pk.unpack(specs[key], leaf)
            flat[key] = np.asarray(leaf)
        return flat
