"""Data-parallel trainer: the AllReduce mode, compiled.

Parity: the reference's AllReduce path (worker/allreduce_trainer.py +
collective_ops/communicator.py — per-step gradient allreduce over
NCCL/Gloo, SURVEY.md §3.4).  TPU-native design: parameters are replicated
over the mesh's `data` axis, the batch is sharded over it, and the gradient
all-reduce is *not a library call* — XLA inserts `psum` when it lowers the
replicated-out gradient of a data-sharded loss, and schedules it onto ICI
overlapped with the backward pass.  One compiled program per step; no
Horovod, no ring management.

Ragged final batches are padded and masked (see parallel/sharding.py) so
shapes stay static across the whole epoch — one compilation, every batch.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.parallel import sharding as shd
from elasticdl_tpu.worker.trainer import TrainState, _model_apply

logger = get_logger("parallel.dp_trainer")


def per_example_loss_fn(loss_fn: Callable) -> Callable:
    """Lift a batch-mean loss into a per-example loss via vmap.

    The model-zoo contract's `loss(labels, outputs)` returns the batch mean
    (reference contract).  Applying it to singleton batches under vmap
    recovers the per-example loss for any mean-of-per-example loss, which
    lets the trainer mask padded rows exactly.
    """

    def singleton(label, output):
        return loss_fn(
            jax.tree.map(lambda x: x[None], label),
            jax.tree.map(lambda x: x[None], output),
        )

    return jax.vmap(singleton)


class DataParallelTrainer:
    """Same public surface as worker.trainer.Trainer, over an N-device mesh.

    Params/opt-state replicated; batch sharded over `data`; loss is a
    mask-weighted mean so padded rows contribute zero gradient.
    """

    def __init__(
        self,
        model,
        loss_fn: Callable,
        optimizer: optax.GradientTransformation,
        mesh,
        seed: int = 0,
    ):
        self._model = model
        self._loss_fn = loss_fn
        self._per_example_loss = per_example_loss_fn(loss_fn)
        self._tx = optimizer
        self._mesh = mesh
        self._seed = seed
        self._state: Optional[TrainState] = None
        # Host-side mirror of state.step (avoids a per-batch device sync).
        self._host_step = 0
        self._dp = shd.data_axis_size(mesh)

        repl = shd.replicated(mesh)
        batch = shd.batch_sharded(mesh)
        window = shd.window_sharded(mesh)
        self._train_step = jax.jit(
            self._train_step_impl,
            in_shardings=(repl, batch, batch, batch),
            out_shardings=(repl, repl),
            donate_argnums=(0,),
        )
        self._train_window_jit = jax.jit(
            self._train_window_impl,
            in_shardings=(repl, window, window, window),
            out_shardings=(repl, repl),
            donate_argnums=(0,),
        )
        self._eval_step = jax.jit(
            self._eval_step_impl,
            in_shardings=(repl, batch),
            out_shardings=batch,
        )

    # -- state ----------------------------------------------------------

    @property
    def mesh(self):
        return self._mesh

    def local_block(self, per_rank_batch: int) -> int:
        """Rows each process must supply per collective step: the requested
        per-rank batch rounded up to a multiple of the process's local
        device count (the global batch must divide the `data` axis)."""
        local_devices = max(1, self._dp // jax.process_count())
        return -(-per_rank_batch // local_devices) * local_devices

    @property
    def state(self) -> Optional[TrainState]:
        return self._state

    @state.setter
    def state(self, value: TrainState):
        self._state = shd.put_replicated(value, self._mesh)
        self._host_step = int(np.asarray(jax.device_get(value.step)))

    @property
    def step(self) -> int:
        return self._host_step

    def ensure_initialized(self, features) -> TrainState:
        if self._state is None:
            from elasticdl_tpu.layers.embedding import (
                export_spec_map,
                strip_capture_collections,
            )
            from elasticdl_tpu.worker.trainer import _unbox_partitioned

            rng = jax.random.PRNGKey(self._seed)
            variables = dict(
                self._model.init(rng, jax.tree.map(jnp.asarray, features))
            )
            self._export_specs = export_spec_map(variables)
            variables = strip_capture_collections(variables)
            variables = _unbox_partitioned(variables)
            params = variables.pop("params")
            state = TrainState(
                jnp.zeros((), jnp.int32),
                params,
                self._tx.init(params),
                variables,
            )
            self._state = shd.put_replicated(jax.device_get(state), self._mesh)
            logger.info(
                "Initialized replicated model over %d-way data parallel: "
                "%d parameters",
                self._dp,
                sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params)),
            )
        return self._state

    # -- compiled steps -------------------------------------------------

    def _train_step_impl(self, state: TrainState, features, labels, mask):
        mutable_keys = list(state.model_state.keys())

        def compute_loss(params):
            variables = {"params": params, **state.model_state}
            outputs, new_model_state = _model_apply(
                self._model, variables, features, train=True, mutable=mutable_keys
            )
            losses = self._per_example_loss(labels, outputs)
            loss = jnp.sum(losses * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            return loss, new_model_state

        (loss, new_model_state), grads = jax.value_and_grad(
            compute_loss, has_aux=True
        )(state.params)
        updates, new_opt_state = self._tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        if not mutable_keys:
            new_model_state = state.model_state
        return (
            TrainState(state.step + 1, new_params, new_opt_state, new_model_state),
            loss,
        )

    def _train_window_impl(self, state, feat_win, label_win, mask_win):
        """K train steps in one device program (see ps_trainer)."""

        def body(st, xs):
            features, labels, mask = xs
            new_state, loss = self._train_step_impl(st, features, labels, mask)
            return new_state, loss

        return jax.lax.scan(body, state, (feat_win, label_win, mask_win))

    def _eval_step_impl(self, state: TrainState, features):
        variables = {"params": state.params, **state.model_state}
        outputs, _ = _model_apply(
            self._model, variables, features, train=False, mutable=False
        )
        return outputs

    # -- host-side entry points ----------------------------------------

    def _place_batch(self, features, labels=None):
        features, mask = shd.pad_batch(features, self._dp)
        if labels is not None:
            labels, _ = shd.pad_batch(labels, self._dp)
            labels = shd.shard_batch(labels, self._mesh)
        features = shd.shard_batch(features, self._mesh)
        mask = shd.shard_batch(mask, self._mesh)
        return features, labels, mask

    def train_step(self, features, labels):
        state = self.ensure_initialized(features)
        features, labels, mask = self._place_batch(features, labels)
        self._state, loss = self._train_step(state, features, labels, mask)
        self._host_step += 1
        return loss

    def train_step_local(self, features, labels, mask):
        """Collective-mode entry: `features`/`labels`/`mask` are this
        process's equal-size slice of the global batch (pre-padded by the
        caller); all processes must call this in lockstep."""
        self.ensure_initialized(features)
        return self.train_step_staged(self.stage_batch(features, labels, mask))

    def stage_batch(self, features, labels, mask):
        """Async device placement of one lockstep batch (stage k+1 before
        stepping k to overlap H2D with compute; see ps_trainer)."""
        return (
            shd.assemble_global_batch(features, self._mesh),
            shd.assemble_global_batch(labels, self._mesh),
            shd.assemble_global_batch(np.asarray(mask, np.float32), self._mesh),
        )

    def train_step_staged(self, staged):
        state = self.ensure_initialized(staged[0])
        self._state, loss = self._train_step(state, *staged)
        self._host_step += 1
        return loss

    def stage_window(self, batches):
        """Stage K same-shape (features, labels, mask) batches as one
        stacked transfer (see ps_trainer.stage_window)."""
        stacked_f, stacked_l, stacked_m = shd.stack_window(batches)
        return (
            shd.assemble_window(stacked_f, self._mesh),
            shd.assemble_window(stacked_l, self._mesh),
            shd.assemble_window(stacked_m, self._mesh),
        )

    def train_window(self, window):
        """Run every batch of a staged window; returns the [K] losses."""
        if self._state is None:
            self.ensure_initialized(jax.tree.map(lambda x: x[0], window[0]))
        k = jax.tree.leaves(window[1])[0].shape[0]
        self._state, losses = self._train_window_jit(self._state, *window)
        self._host_step += k
        return losses

    def eval_step_local(self, features):
        """Collective-mode eval: local slice in, FULL global outputs out
        (host numpy, identical on every process)."""
        state = self.ensure_initialized(features)
        features = shd.assemble_global_batch(features, self._mesh)
        outputs = self._eval_step(state, features)
        return shd.gather_to_host(outputs)

    def eval_step(self, features):
        state = self.ensure_initialized(features)
        n = jax.tree.leaves(features)[0].shape[0]
        features, _, _ = self._place_batch(features)
        outputs = self._eval_step(state, features)
        # Strip padding rows before returning to the host.
        return jax.tree.map(lambda x: np.asarray(x)[:n], outputs)

    def state_to_host(self) -> Optional[TrainState]:
        """Host-complete snapshot for checkpointing.  All state is fully
        replicated, so every process can materialize it locally."""
        return None if self._state is None else jax.device_get(self._state)

    def get_variables_numpy(self) -> dict:
        """Flat logical view; packed tables unpacked (see worker.trainer)."""
        from elasticdl_tpu.parallel import packed as pk

        if self._state is None:
            return {}
        specs = getattr(self, "_export_specs", {})
        flat = {}
        tree = {"params": self._state.params, **self._state.model_state}
        for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
            key = "/".join(str(getattr(p, "key", p)) for p in path)
            if key in specs:
                leaf = pk.unpack(specs[key], leaf)
            flat[key] = np.asarray(leaf)
        return flat
