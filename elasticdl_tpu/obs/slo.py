"""Declarative SLOs with error-budget burn-rate alerting.

The sensor layer for serving autoscale (ROADMAP item 2) and the
freshness/goodput planes: each `SLOSpec` names an objective over
metrics already in the registry, `SLORegistry.evaluate(now)` turns the
`MetricsHistory` ring (obs/history.py) into burn rates, and alerting
follows the Google-SRE multi-window multi-burn-rate recipe:

    pair   short window   long window   burn threshold   grade
    fast   W/8640 (5m)    W/720 (1h)    14.4             page
    slow   W/720  (1h)    W/120 (6h)    6.0              warn

where W is the spec's rolling compliance window (the canonical 30-day
fractions, scaled to job time) and every window is clamped to
``min_window_s``.  ``burn_rate = bad_fraction(window) / (1 - objective)``
— burn 1.0 spends the budget exactly over the compliance window; an
alert pair fires only when BOTH its windows are over threshold (the
short window for reaction time, the long one to ignore blips).

Two spec kinds:

- ``ratio``      good/total counter deltas (serving availability from
                 the `AvailabilityLedger` outcome counters)
- ``threshold``  fraction of gauge samples beyond a bound (p99 latency
                 vs target, freshness lag, goodput ratio)

Events are schema-registered in scripts/validate_journal.py:
``slo_status`` (rate-limited, on tick) and ``slo_alert``
(edge-triggered fire/clear with evidence: per-window burn rates,
budget remaining, offending series).  Exported gauges:
``elasticdl_slo_burn_rate{slo,window}``,
``elasticdl_slo_budget_remaining_ratio{slo}``,
``elasticdl_slo_alerting{slo}`` — label values are spec names
(validated slugs) and the four window positions, both bounded
(metric-label-cardinality rule).

Clock discipline: `evaluate(now)`/`tick(now)` are caller-driven like
`FreshnessTracker.evaluate(now)`; `SLOPlane.start()` is the production
convenience that feeds `time.monotonic()` from a named daemon thread.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from elasticdl_tpu import obs
from elasticdl_tpu.analysis.runtime import make_lock
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.obs.history import MetricsHistory

logger = get_logger("obs.slo")

_SLO_NAME_RE = re.compile(r"[a-z][a-z0-9_]{0,39}$")

#: Window positions, in (pair, length) order — the `window` label enum.
WINDOWS = ("fast_short", "fast_long", "slow_short", "slow_long")

#: Canonical 30-day-window fractions (5m/1h, 1h/6h), scaled to the
#: spec's compliance window.
WINDOW_FRACTIONS = {
    "fast_short": 1.0 / 8640.0,
    "fast_long": 1.0 / 720.0,
    "slow_short": 1.0 / 720.0,
    "slow_long": 1.0 / 120.0,
}

PAGE_BURN_THRESHOLD = 14.4
WARN_BURN_THRESHOLD = 6.0


@dataclass
class SLOSpec:
    """One objective over registry metrics.

    ``ratio`` kind: ``good_metric{good_labels}`` / all series of
    ``total_metric`` (counter deltas).  ``threshold`` kind: fraction of
    ``value_metric`` samples beyond ``threshold`` (``bad_when`` says
    which side is bad)."""

    name: str
    kind: str  # "ratio" | "threshold"
    objective: float  # target good fraction, e.g. 0.999
    compliance_window_s: float = 3600.0
    # ratio kind
    good_metric: str = ""
    good_labels: Dict[str, str] = field(default_factory=dict)
    total_metric: str = ""
    total_labels: Optional[Dict[str, str]] = None  # None = every series
    # threshold kind
    value_metric: str = ""
    threshold: float = 0.0
    bad_when: str = "above"  # or "below"
    # window scaling
    min_window_s: float = 5.0
    fast_burn_threshold: float = PAGE_BURN_THRESHOLD
    slow_burn_threshold: float = WARN_BURN_THRESHOLD

    def __post_init__(self):
        if not _SLO_NAME_RE.match(self.name):
            raise ValueError(f"Invalid SLO name {self.name!r}")
        if self.kind not in ("ratio", "threshold"):
            raise ValueError(f"Unknown SLO kind {self.kind!r}")
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name}: objective must be in (0, 1), "
                f"got {self.objective}"
            )
        if self.bad_when not in ("above", "below"):
            raise ValueError(f"Unknown bad_when {self.bad_when!r}")

    def windows(self) -> Dict[str, float]:
        """Window name -> seconds, scaled + clamped."""
        w = float(self.compliance_window_s)
        return {
            name: min(w, max(float(self.min_window_s), w * frac))
            for name, frac in WINDOW_FRACTIONS.items()
        }

    def budget(self) -> float:
        """The allowed bad fraction (1 - objective), floored > 0."""
        return max(1e-9, 1.0 - float(self.objective))

    def metric_names(self) -> List[str]:
        if self.kind == "ratio":
            return sorted({self.good_metric, self.total_metric})
        return [self.value_metric]


# ---------------------------------------------------------------------------
# Built-in spec constructors (the four planes named by the roadmap)
# ---------------------------------------------------------------------------


def serving_availability_slo(objective: float = 0.999,
                             compliance_window_s: float = 3600.0,
                             min_window_s: float = 5.0) -> SLOSpec:
    """Good = served requests, total = every outcome, from the
    `AvailabilityLedger` counters."""
    return SLOSpec(
        name="serving_availability",
        kind="ratio",
        objective=objective,
        compliance_window_s=compliance_window_s,
        good_metric="elasticdl_serving_requests_total",
        good_labels={"outcome": "served"},
        total_metric="elasticdl_serving_requests_total",
        min_window_s=min_window_s,
    )


def serving_latency_slo(p99_ms: float, objective: float = 0.99,
                        compliance_window_s: float = 3600.0,
                        min_window_s: float = 5.0) -> SLOSpec:
    """p99 samples must stay under `p99_ms` for `objective` of the
    window (the ledger gauge is itself a sliding-window percentile)."""
    return SLOSpec(
        name="serving_latency",
        kind="threshold",
        objective=objective,
        compliance_window_s=compliance_window_s,
        value_metric="elasticdl_serving_latency_p99_ms",
        threshold=float(p99_ms),
        bad_when="above",
        min_window_s=min_window_s,
    )


def freshness_slo(lag_slo_s: float, objective: float = 0.99,
                  compliance_window_s: float = 3600.0,
                  min_window_s: float = 5.0) -> SLOSpec:
    """Event-time -> servable-model lag (obs/freshness.py gauge) under
    `lag_slo_s` — the windowed companion to the breach/clear edge."""
    return SLOSpec(
        name="freshness",
        kind="threshold",
        objective=objective,
        compliance_window_s=compliance_window_s,
        value_metric="elasticdl_freshness_lag_seconds",
        threshold=float(lag_slo_s),
        bad_when="above",
        min_window_s=min_window_s,
    )


def quality_slo(max_logloss: float, objective: float = 0.95,
                compliance_window_s: float = 3600.0,
                min_window_s: float = 5.0) -> SLOSpec:
    """Windowed online logloss (the quality ledger's
    `elasticdl_quality_logloss` gauge, obs/quality.py) must stay under
    `max_logloss` — the model-quality page.  The gauge reads 0.0 while
    no labels have joined, so quality-unknown never burns budget; a
    poisoned model that DOES get labeled burns fast and the alert's
    advisory evidence reaches the policy engine like every other SLO."""
    return SLOSpec(
        name="model_quality",
        kind="threshold",
        objective=objective,
        compliance_window_s=compliance_window_s,
        value_metric="elasticdl_quality_logloss",
        threshold=float(max_logloss),
        bad_when="above",
        min_window_s=min_window_s,
    )


def goodput_slo(ratio: float, objective: float = 0.95,
                compliance_window_s: float = 3600.0,
                min_window_s: float = 5.0) -> SLOSpec:
    """Goodput ledger ratio must stay ABOVE `ratio` (bad when below)."""
    return SLOSpec(
        name="goodput",
        kind="threshold",
        objective=objective,
        compliance_window_s=compliance_window_s,
        value_metric="elasticdl_goodput_ratio",
        threshold=float(ratio),
        bad_when="below",
        min_window_s=min_window_s,
    )


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------


class SLORegistry:
    """Evaluates specs against a `MetricsHistory` on a caller tick.

    Burn/budget/alerting gauges land in the SAME registry the history
    samples — the burn-rate series therefore has history of its own,
    which is what the `/slo` sparklines render."""

    def __init__(self, history: MetricsHistory, specs=(),
                 status_interval_s: float = 10.0, origin: str = ""):
        self.history = history
        self.status_interval_s = float(status_interval_s)
        self.origin = str(origin)
        self._lock = make_lock("SLORegistry._lock")
        self._specs: Dict[str, SLOSpec] = {}  # guarded-by: _lock
        self._alerting: Dict[str, str] = {}  # name -> grade, guarded-by: _lock
        self._statuses: Dict[str, dict] = {}  # guarded-by: _lock
        self._last_status_t = float("-inf")  # guarded-by: _lock
        self._callbacks: List[Callable[[str, bool, dict], None]] = []  # guarded-by: _lock
        self._exemplar_provider: Optional[Callable[[str], List[str]]] = None  # guarded-by: _lock
        registry = history.registry
        self._g_burn = registry.gauge(
            "elasticdl_slo_burn_rate",
            "Error-budget burn rate per evaluation window",
            labelnames=("slo", "window"),
        )
        self._g_budget = registry.gauge(
            "elasticdl_slo_budget_remaining_ratio",
            "Fraction of the error budget left over the compliance window",
            labelnames=("slo",),
        )
        self._g_alerting = registry.gauge(
            "elasticdl_slo_alerting",
            "1 while the SLO has a fired burn-rate alert",
            labelnames=("slo",),
        )
        for spec in specs:
            self.add(spec)

    def add(self, spec: SLOSpec) -> SLOSpec:
        with self._lock:
            if spec.name in self._specs:
                raise ValueError(f"SLO {spec.name} already registered")
            self._specs[spec.name] = spec
        self._g_alerting.set(0, slo=spec.name)
        self._g_budget.set(1.0, slo=spec.name)
        return spec

    def add_alert_callback(
        self, fn: Callable[[str, bool, dict], None]
    ) -> None:
        """fn(slo_name, alerting, evidence) on every fire/clear edge."""
        with self._lock:
            self._callbacks.append(fn)

    def set_exemplar_provider(
        self, fn: Callable[[str], List[str]]
    ) -> None:
        """fn(slo_name) -> trace ids attached to FIRE edges as evidence.

        Wired by the serving replica to its ExemplarSampler so a latency
        page carries the slowest sampled request trace ids — resolvable
        in the Perfetto trace built from the same journal.  Trace ids
        ride the alert event/evidence (unbounded values), never a metric
        label (metric-label-cardinality rule)."""
        with self._lock:
            self._exemplar_provider = fn

    def specs(self) -> List[SLOSpec]:
        with self._lock:
            return list(self._specs.values())

    def alerting(self) -> Dict[str, str]:
        """Currently-fired SLOs: name -> grade."""
        with self._lock:
            return dict(self._alerting)

    def statuses(self) -> List[dict]:
        """Last-evaluated status per spec (the `/slo` payload rows)."""
        with self._lock:
            return [dict(s) for _n, s in sorted(self._statuses.items())]

    # -- evaluation ------------------------------------------------------

    def _bad_fraction(self, spec: SLOSpec, window_s: float,
                      now: float) -> Optional[float]:
        """Bad fraction over the window; None = no data (not a breach)."""
        if spec.kind == "ratio":
            total = self.history.delta(
                spec.total_metric, window_s, now, labels=spec.total_labels
            )
            if total <= 0:
                return None
            good = self.history.delta(
                spec.good_metric, window_s, now, labels=spec.good_labels
            )
            return min(1.0, max(0.0, 1.0 - good / total))
        frac = self.history.threshold_fraction(
            spec.value_metric, window_s, spec.threshold, now,
            above=(spec.bad_when == "above"),
        )
        return frac

    def _offending(self, spec: SLOSpec, window_s: float, now: float) -> str:
        """The series that burned the budget, as `metric{labels}`."""
        if spec.kind == "threshold":
            return spec.value_metric
        worst = None
        for labels, inc in self.history.series_deltas(
            spec.total_metric, window_s, now
        ):
            if all(labels.get(k) == str(v)
                   for k, v in spec.good_labels.items()):
                continue  # the good series never offends
            if inc > 0 and (worst is None or inc > worst[1]):
                worst = (labels, inc)
        if worst is None:
            return spec.total_metric
        rendered = ",".join(f"{k}={v}" for k, v in sorted(worst[0].items()))
        return f"{spec.total_metric}{{{rendered}}}"

    def _status_for(self, spec: SLOSpec, now: float) -> dict:
        windows = spec.windows()
        budget = spec.budget()
        burn_rates: Dict[str, float] = {}
        for wname, wsec in windows.items():
            frac = self._bad_fraction(spec, wsec, now)
            burn_rates[wname] = round((frac or 0.0) / budget, 4)
        compliance_frac = self._bad_fraction(
            spec, spec.compliance_window_s, now
        )
        budget_remaining = min(1.0, max(
            0.0, 1.0 - (compliance_frac or 0.0) / budget
        ))
        page = (burn_rates["fast_short"] > spec.fast_burn_threshold
                and burn_rates["fast_long"] > spec.fast_burn_threshold)
        warn = (burn_rates["slow_short"] > spec.slow_burn_threshold
                and burn_rates["slow_long"] > spec.slow_burn_threshold)
        grade = "page" if page else ("warn" if warn else "")
        offending = (
            self._offending(spec, windows["fast_long"], now) if grade else ""
        )
        return {
            "slo": spec.name,
            "kind": spec.kind,
            "objective": spec.objective,
            "window_s": spec.compliance_window_s,
            "bad_fraction": round(compliance_frac or 0.0, 6),
            "budget_remaining_ratio": round(budget_remaining, 4),
            "burn_rates": burn_rates,
            "alerting": bool(grade),
            "grade": grade,
            "offending": offending,
            "origin": self.origin,
        }

    def evaluate(self, now: float) -> List[dict]:
        """Evaluate every spec at `now`; returns the `slo_alert` edge
        events journaled this tick (possibly empty).  Journal writes and
        callbacks run outside the lock."""
        now = float(now)
        statuses = [self._status_for(spec, now) for spec in self.specs()]
        edges: List[dict] = []
        status_due = False
        with self._lock:
            if now - self._last_status_t >= self.status_interval_s:
                self._last_status_t = now
                status_due = True
            for status in statuses:
                name = status["slo"]
                self._statuses[name] = status
                was = name in self._alerting
                if status["alerting"] and not was:
                    self._alerting[name] = status["grade"]
                    edges.append(dict(status, state="fire"))
                elif not status["alerting"] and was:
                    fired_grade = self._alerting.pop(name)
                    edges.append(dict(status, state="clear",
                                      grade=fired_grade))
                elif status["alerting"]:
                    self._alerting[name] = status["grade"]
            callbacks = list(self._callbacks)
            exemplar_provider = self._exemplar_provider
        for status in statuses:
            name = status["slo"]
            for wname, burn in status["burn_rates"].items():
                self._g_burn.set(burn, slo=name, window=wname)
            self._g_budget.set(status["budget_remaining_ratio"], slo=name)
            self._g_alerting.set(1 if status["alerting"] else 0, slo=name)
        journal = obs.journal()
        if status_due:
            for status in statuses:
                journal.record(
                    "slo_status",
                    slo=status["slo"],
                    kind=status["kind"],
                    objective=status["objective"],
                    window_s=status["window_s"],
                    bad_fraction=status["bad_fraction"],
                    budget_remaining_ratio=status["budget_remaining_ratio"],
                    burn_rates=status["burn_rates"],
                    alerting=status["alerting"],
                    grade=status["grade"],
                    origin=status["origin"],
                )
        for edge in edges:
            exemplars: List[str] = []
            if edge["state"] == "fire" and exemplar_provider is not None:
                try:
                    exemplars = [str(t) for t
                                 in exemplar_provider(edge["slo"]) if t]
                except Exception:
                    logger.exception("SLO exemplar provider failed")
            extra = {"exemplars": exemplars} if exemplars else {}
            journal.record(
                "slo_alert",
                slo=edge["slo"],
                state=edge["state"],
                grade=edge["grade"],
                burn_rates=edge["burn_rates"],
                budget_remaining_ratio=edge["budget_remaining_ratio"],
                offending=edge["offending"],
                origin=edge["origin"],
                **extra,
            )
            if edge["state"] == "fire":
                logger.warning(
                    "SLO ALERT %s [%s]: burn %s, budget %.1f%% left "
                    "(offending: %s)",
                    edge["slo"], edge["grade"], edge["burn_rates"],
                    100.0 * edge["budget_remaining_ratio"],
                    edge["offending"] or "-",
                )
            else:
                logger.info("SLO alert cleared: %s", edge["slo"])
            evidence = {
                "grade": edge["grade"],
                "burn_rates": edge["burn_rates"],
                "budget_remaining_ratio": edge["budget_remaining_ratio"],
                "offending": edge["offending"],
                "origin": edge["origin"],
            }
            if exemplars:
                evidence["exemplars"] = exemplars
            for fn in callbacks:
                try:
                    fn(edge["slo"], edge["state"] == "fire", evidence)
                except Exception:
                    logger.exception("SLO alert callback failed")
        return edges


# ---------------------------------------------------------------------------
# Plane: history + registry + tick thread + /slo payload
# ---------------------------------------------------------------------------


class SLOPlane:
    """One process's SLO sensor: a `MetricsHistory` sampler and an
    `SLORegistry`, ticked together.  `tick(now)` is the deterministic
    entry point (tests, chaos drivers, the replica telemetry loop);
    `start()` runs a wall-clock tick thread for the master."""

    def __init__(self, registry=None, specs=(),
                 tick_interval_s: float = 2.0,
                 status_interval_s: float = 10.0, origin: str = "",
                 max_series: int = 256, max_samples: int = 512):
        self.history = MetricsHistory(
            registry, max_series=max_series, max_samples=max_samples
        )
        self.slos = SLORegistry(
            self.history, specs,
            status_interval_s=status_interval_s, origin=origin,
        )
        self.tick_interval_s = float(tick_interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._ticks = 0

    def tick(self, now: Optional[float] = None) -> List[dict]:
        """Sample + evaluate once; `now` defaults to the wall clock."""
        import time
        now = time.monotonic() if now is None else float(now)
        now = self.history.sample(now)
        self._ticks += 1
        return self.slos.evaluate(now)

    def start(self, interval_s: Optional[float] = None) -> "SLOPlane":
        if self._thread is not None:
            return self
        if interval_s is not None:
            self.tick_interval_s = float(interval_s)

        def _loop():
            while not self._stop.wait(self.tick_interval_s):
                try:
                    self.tick()
                except Exception:
                    logger.exception("SLO tick failed")

        self._thread = threading.Thread(
            target=_loop, name="slo-plane-tick", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5)
            self._thread = None

    def snapshot(self, samples_per_series: int = 32) -> dict:
        """The bounded `/slo` endpoint payload: statuses (each with a
        fast-window burn-rate sparkline), the headline metric series,
        and the alert set.  Nothing unbounded, no file paths."""
        samples_per_series = min(128, max(1, int(samples_per_series)))
        statuses = self.slos.statuses()
        names: List[str] = []
        for spec in self.slos.specs():
            for metric in spec.metric_names():
                names.extend((metric, metric + "_count", metric + "_sum"))
        for status in statuses:
            status["sparkline"] = [
                round(v, 4) for v in self.history.sparkline(
                    "elasticdl_slo_burn_rate", n=samples_per_series,
                    labels={"slo": status["slo"], "window": "fast_short"},
                )
            ]
        return {
            "origin": self.slos.origin,
            "ticks": self._ticks,
            "alerting": sorted(self.slos.alerting()),
            "statuses": statuses,
            "series": self.history.snapshot(
                max_series=16, samples_per_series=samples_per_series,
                names=names,
            ),
        }


# ---------------------------------------------------------------------------
# Selftest (the `make slo-gates` gate)
# ---------------------------------------------------------------------------


def _selftest() -> int:
    """Deterministic burn-rate run on a virtual clock: a latency
    regression trips the fast pair within bounded ticks and clears
    after draining; an all-served availability SLO never fires; a
    control run with no fault journals zero alerts."""
    import json
    import os
    import tempfile

    from elasticdl_tpu.obs.metrics import MetricsRegistry

    def run(fault: bool, tmp: str):
        obs.init_journal(tmp)
        registry = MetricsRegistry()
        p99 = registry.gauge("elasticdl_serving_latency_p99_ms", "")
        served = registry.counter(
            "elasticdl_serving_requests_total", "", labelnames=("outcome",)
        )
        plane = SLOPlane(
            registry=registry,
            specs=[
                serving_latency_slo(
                    20.0, objective=0.99, compliance_window_s=7200.0
                ),
                serving_availability_slo(
                    0.999, compliance_window_s=7200.0
                ),
            ],
            status_interval_s=10.0,
            origin="selftest",
        )
        edges = []
        plane.slos.add_alert_callback(
            lambda slo, alerting, ev: edges.append((slo, alerting))
        )
        fired_tick = cleared_tick = None
        for tick in range(240):
            p99.set(50.0 if fault and 60 <= tick < 120 else 2.0)
            served.inc(100, outcome="served")
            plane.tick(float(tick))
            alerting = plane.slos.alerting()
            if fired_tick is None and alerting:
                fired_tick = tick
            if fired_tick is not None and cleared_tick is None \
                    and tick >= 120 and not alerting:
                cleared_tick = tick
        return plane, edges, fired_tick, cleared_tick

    with tempfile.TemporaryDirectory() as tmp:
        plane, edges, fired, cleared = run(fault=True, tmp=tmp)
        assert fired is not None and 60 < fired <= 90, fired
        assert cleared is not None and cleared <= 200, cleared
        assert edges == [("serving_latency", True),
                         ("serving_latency", False)], edges
        events = [json.loads(line)
                  for line in open(os.path.join(tmp, "events.jsonl"))]
        alerts = [e for e in events if e.get("event") == "slo_alert"]
        assert [a["state"] for a in alerts] == ["fire", "clear"], alerts
        assert alerts[0]["grade"] == "page", alerts[0]
        assert alerts[0]["offending"] == \
            "elasticdl_serving_latency_p99_ms", alerts[0]
        for alert in alerts:
            for need in ("slo", "state", "burn_rates",
                         "budget_remaining_ratio", "origin"):
                assert need in alert, (need, alert)
        statuses = [e for e in events if e.get("event") == "slo_status"]
        assert 20 <= len(statuses) <= 80, len(statuses)
        for status in statuses:
            for need in ("slo", "budget_remaining_ratio"):
                assert need in status, (need, status)
        latency = plane.slos.statuses()[1]
        assert latency["slo"] == "serving_latency", latency
        assert latency["budget_remaining_ratio"] < 1.0, latency
        snap = plane.snapshot()
        assert snap["statuses"] and snap["series"], snap.keys()
        assert not snap["alerting"], snap["alerting"]

    with tempfile.TemporaryDirectory() as tmp:
        _plane, edges, fired, _cleared = run(fault=False, tmp=tmp)
        assert fired is None and not edges, (fired, edges)
        lines = open(os.path.join(tmp, "events.jsonl")).read()
        assert '"slo_alert"' not in lines, "control run fired an alert"

    print("slo selftest: OK")
    return 0


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description="SLO plane")
    parser.add_argument("--selftest", action="store_true")
    args = parser.parse_args(argv)
    if args.selftest:
        return _selftest()
    parser.error("nothing to do (use --selftest)")
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
